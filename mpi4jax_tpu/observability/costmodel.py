"""Analytic collective cost model: fingerprint -> wire bytes, steps,
expected time.

The telemetry layers record *what* communicated (op, payload bytes,
dtype, mesh axes, world size — ``metrics.py`` / ``recorder.py``
emission fingerprints) and, with runtime sampling, *how long* it took.
This module supplies the missing third column: how long it *should*
take, so achieved bandwidth and %-of-peak fall out of a join
(:mod:`.perf`) instead of a profiler session.

Per op the model gives the **per-rank bytes on the wire** and the
**algorithm step count** of the standard algorithm XLA/this package
uses (topology-aware collective cost modelling in the Cloud
Collectives sense, arXiv:2105.14088):

====================  =======================  ==================
op                    wire bytes (per rank)    steps
====================  =======================  ==================
AllReduce             2 (n-1)/n * B            2 (n-1)   ring RS+AG
ReduceScatter         (n-1)/n * B              n-1       ring
AllGather             (n-1) * B                n-1       ring (B = shard)
AllToAll              (n-1)/n * B              n-1       pairwise
Bcast / Reduce        B                        ceil(log2 n)  tree
Gather / Scatter      (n-1) * B                n-1       linear @ root
Scan                  B                        n-1       chain
Barrier               0                        ceil(log2 n)
Send/Recv/Sendrecv/
CollectivePermute     B                        1
QuantizedAllReduce    2 (n-1) * q(B/n)         2 (n-1)   int8 ring
====================  =======================  ==================

where ``B`` is the recorded payload bytes of the emission and
``q(...)`` is the quantized wire format (int8 + one f32 scale per
256-value block; the canonical implementation lives beside the kernel
in ``ops/quantized.py`` — ``wire_format_bytes`` / ``ring_chunk_elems``
— and a test pins this module's mirror to it so the two cannot
drift). Expected time is the alpha-beta model

    t = steps * alpha + wire_bytes / (peak_gbps * 1e9)

with ``alpha`` from ``M4T_ALPHA_US`` (default 1 us/step) and
``peak_gbps`` from ``M4T_PEAK_GBPS`` or the per-generation ICI table
below (the companion of ``benchmarks/roofline.py``'s HBM table).

Import-light on purpose (no jax): the offline consumers (doctor,
perf CLI) parse logs on hosts where importing a backend is either
slow or impossible.
"""

from __future__ import annotations

import math
import os
import warnings
from typing import Any, Dict, List, Optional, Tuple

from .. import config

#: nominal aggregate ICI bandwidth by TPU generation, GB/s per chip
#: (public TPU system-architecture docs: v4 2400 Gbit/s, v5e 1600,
#: v5p 4800, v6e 3584). Substring-matched on ``device_kind``, same
#: convention as ``benchmarks/roofline.py:HBM_PEAK_GBPS``.
ICI_PEAK_GBPS = {
    "v5 lite": 200.0,  # v5e reports device_kind "TPU v5 lite"
    "v5litepod": 200.0,
    "v5e": 200.0,
    "v5p": 600.0,
    "v4": 300.0,
    "v6 lite": 448.0,
    "v6e": 448.0,
}

#: fallback peak when no generation matches (CPU container / shm
#: backend: a conservative single-host memory-channel figure — the
#: point of the default is a finite, explicit denominator, not a
#: hardware claim; override with M4T_PEAK_GBPS)
DEFAULT_PEAK_GBPS = 25.0

#: quantized wire format mirror (ops/quantized.py: _BLOCK, int8
#: payload + one f32 scale per block); pinned by
#: tests/test_perf.py::test_quantized_mirror_matches_kernel
_QUANT_BLOCK = 256


#: M4T_PEAK_GBPS values already warned about (one warning per distinct
#: bad value, not one per cost-model call)
_WARNED_PEAK: set = set()


def peak_gbps(device_kind: Optional[str] = None) -> float:
    """The peak link bandwidth the attribution divides by:
    ``M4T_PEAK_GBPS`` when set, else the generation table keyed by
    ``device_kind``, else :data:`DEFAULT_PEAK_GBPS`.

    An unparseable or non-positive ``M4T_PEAK_GBPS`` warns once and
    falls back to the table — a typo'd override must not silently
    poison every achieved-bandwidth figure downstream."""
    # read the env dynamically (not the import-time snapshot) so the
    # CLI and tests can retarget without reloading the module
    raw = os.environ.get("M4T_PEAK_GBPS", "")
    if raw:
        value = None
        try:
            value = float(raw)
        except ValueError:
            pass
        if value is not None and value > 0:
            return value
        if raw not in _WARNED_PEAK:
            _WARNED_PEAK.add(raw)
            warnings.warn(
                f"M4T_PEAK_GBPS={raw!r} is not a positive number; "
                "falling back to the generation table",
                RuntimeWarning,
                stacklevel=2,
            )
    elif config.PEAK_GBPS > 0:
        return config.PEAK_GBPS
    if device_kind:
        kind = device_kind.lower()
        for key, gbps in ICI_PEAK_GBPS.items():
            if key in kind:
                return gbps
    return DEFAULT_PEAK_GBPS


def alpha_s() -> float:
    """Per-step latency term of the alpha-beta model, seconds."""
    raw = os.environ.get("M4T_ALPHA_US", "")
    if raw:
        try:
            return max(0.0, float(raw)) * 1e-6
        except ValueError:
            pass
    return config.ALPHA_US * 1e-6


#: dtype -> itemsize for the dtypes the op layer records; numpy is
#: deliberately not consulted (bfloat16 needs ml_dtypes registration)
_ITEMSIZE = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
    "complex128": 16,
}


def itemsize(dtype: Optional[str]) -> int:
    return _ITEMSIZE.get(str(dtype or ""), 4)


#: first-class cost entries for planner impls whose step structure was
#: verified elsewhere: ``algo:<name>@<fingerprint>`` tags registered by
#: ``planner/algo.registry`` from each algorithm's admission pass
#: (M4T205), so ``lint --cost``, ``launch --verify`` and the
#: autotuner's analytic seed all price it from the *proven* round
#: structure rather than a guess
_IMPL_COSTS: Dict[str, Dict[str, Any]] = {}


def register_impl_cost(
    impl: str,
    *,
    op: str,
    label: str,
    per_world: Dict[int, Dict[str, int]],
) -> None:
    """Register an impl's verified step structure: per world,
    ``{"chunks", "wire_chunks", "rounds"}`` — wire bytes scale as
    ``wire_chunks * ceil(payload / chunks)``, steps are the proven
    synchronization rounds."""
    _IMPL_COSTS[impl] = {
        "op": op,
        "label": label,
        "per_world": {int(w): dict(v) for w, v in per_world.items()},
    }


def registered_impl_cost(impl: str) -> Optional[Dict[str, Any]]:
    """The registered entry for one impl tag; ``algo:*`` tags trigger
    a lazy registry scan so offline consumers (lint/doctor reading a
    record stream) price them without arming anything first."""
    entry = _IMPL_COSTS.get(impl)
    if entry is None and impl.startswith("algo:"):
        try:
            from ..planner import algo as _algo

            _algo.registry()
        except Exception:
            return None
        entry = _IMPL_COSTS.get(impl)
    return entry


def _quant_wire_format_bytes(n_elems: int) -> int:
    if n_elems <= 0:
        return 0
    return int(n_elems) + 4 * (-(-int(n_elems) // _QUANT_BLOCK))


def _quant_ring_chunk_elems(total_elems: int, world: int) -> int:
    if world <= 1:
        return 0
    chunk = -(-int(total_elems) // int(world))
    return -(-chunk // _QUANT_BLOCK) * _QUANT_BLOCK


def cost(
    op: str,
    *,
    nbytes: int,
    world: Optional[int],
    dtype: Optional[str] = None,
    impl: Optional[str] = None,
    params: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Expected per-rank wire bytes and algorithm steps for one
    emission. Returns ``{"op", "wire_bytes", "steps", "algorithm"}``
    (plus ``"impl"`` when a non-default implementation was asked
    for); unknown ops get the conservative identity model (wire =
    payload, 1 step) with ``algorithm: "unknown"``.

    ``impl`` is the planner's implementation tag
    (``planner/plan.AVAILABLE``): ``None``/``"hlo"`` is the plain op
    model below; ``"pallas_ring"`` moves the same bytes (the table's
    AllReduce/RS/AG rows *are* the ring schedule) under a distinct
    algorithm label; ``"quantized"`` re-routes AllReduce through the
    int8 wire format; ``"hierarchical"`` is the two-level AllReduce
    (ring RS+AG on the fast axis of ``params["fast"]`` ranks, ring
    allreduce of the 1/fast shard across the ``world/fast`` slow
    groups)."""
    n = int(world) if world else 1
    b = max(0, int(nbytes))
    if n <= 1:
        return {"op": op, "wire_bytes": 0, "steps": 0,
                "algorithm": "local (world size 1)"}
    if impl and impl != "hlo":
        c = _impl_cost(op, impl, b, n, dtype, params or {})
        if c is not None:
            c["impl"] = impl
            return c
    log2n = int(math.ceil(math.log2(n)))
    if op == "AllReduce":
        return {"op": op, "wire_bytes": int(round(2 * (n - 1) * b / n)),
                "steps": 2 * (n - 1),
                "algorithm": "ring reduce-scatter + all-gather"}
    if op == "ReduceScatter":
        return {"op": op, "wire_bytes": int(round((n - 1) * b / n)),
                "steps": n - 1, "algorithm": "ring"}
    if op == "AllGather":
        # B is the local shard (the op's input operand): each rank
        # forwards its shard around the whole ring
        return {"op": op, "wire_bytes": (n - 1) * b, "steps": n - 1,
                "algorithm": "ring"}
    if op == "AllToAll":
        return {"op": op, "wire_bytes": int(round((n - 1) * b / n)),
                "steps": n - 1, "algorithm": "pairwise exchange"}
    if op in ("Bcast", "Reduce"):
        return {"op": op, "wire_bytes": b, "steps": log2n,
                "algorithm": "binomial tree"}
    if op in ("Gather", "Scatter"):
        # root-link bottleneck: the root moves every peer's block
        return {"op": op, "wire_bytes": (n - 1) * b, "steps": n - 1,
                "algorithm": "linear at root"}
    if op == "Scan":
        return {"op": op, "wire_bytes": b, "steps": n - 1,
                "algorithm": "chain"}
    if op == "Barrier":
        return {"op": op, "wire_bytes": 0, "steps": log2n,
                "algorithm": "dissemination"}
    if op in ("Send", "Recv", "Sendrecv", "CollectivePermute",
              "PallasRing"):
        return {"op": op, "wire_bytes": b, "steps": 1,
                "algorithm": "point-to-point"}
    if op == "QuantizedAllReduce":
        elems = b // itemsize(dtype)
        chunk = _quant_ring_chunk_elems(elems, n)
        hop = _quant_wire_format_bytes(chunk)
        return {"op": op, "wire_bytes": 2 * (n - 1) * hop,
                "steps": 2 * (n - 1),
                "algorithm": "int8 ring (absmax/256 block scales)"}
    return {"op": op, "wire_bytes": b, "steps": 1, "algorithm": "unknown"}


def _impl_cost(
    op: str,
    impl: str,
    b: int,
    n: int,
    dtype: Optional[str],
    params: Dict[str, Any],
) -> Optional[Dict[str, Any]]:
    """Planner-impl variants of the op models above (n > 1 here).
    Returns None for an impl this model does not know for this op —
    the caller then falls through to the plain op model, so a plan
    from a newer schema degrades to a conservative estimate instead
    of crashing an offline report."""
    reg = registered_impl_cost(impl)
    if reg is not None:
        if op != reg["op"]:
            return None
        ent = reg["per_world"].get(n)
        if ent is None:
            return None
        chunk_b = -(-b // max(1, int(ent["chunks"])))
        return {
            "op": op,
            "wire_bytes": int(ent["wire_chunks"]) * chunk_b,
            "steps": int(ent["rounds"]),
            "algorithm": reg["label"],
        }
    if impl == "pallas_ring" and op in (
        "AllReduce", "ReduceScatter", "AllGather"
    ):
        base = cost(op, nbytes=b, world=n, dtype=dtype)
        base["algorithm"] = {
            "AllReduce": "pallas RDMA ring RS+AG",
            "ReduceScatter": "pallas RDMA ring",
            "AllGather": "pallas RDMA ring",
        }[op]
        return base
    if impl == "quantized" and op == "AllReduce":
        c = cost("QuantizedAllReduce", nbytes=b, world=n, dtype=dtype)
        c["op"] = op
        return c
    if impl == "hierarchical" and op == "AllReduce":
        fast = int(params.get("fast") or 0)
        if not (1 < fast < n and n % fast == 0):
            return None
        slow = n // fast
        # fast-axis ring RS+AG over the full payload, plus a ring
        # allreduce of the 1/fast shard across the slow groups — one
        # crossing of the slow axis
        fast_wire = int(round(2 * (fast - 1) * b / fast))
        slow_wire = int(round(2 * (slow - 1) * (b / fast) / slow))
        return {
            "op": op,
            "wire_bytes": fast_wire + slow_wire,
            "steps": 2 * (fast - 1) + 2 * (slow - 1),
            "algorithm": (
                f"hierarchical ring (fast {fast} x slow {slow})"
            ),
        }
    return None


def _ring_edges(n: int) -> List[Tuple[int, int]]:
    return [(r, (r + 1) % n) for r in range(n)]


def edge_phases(
    op: str,
    *,
    nbytes: int,
    world: Optional[int],
    dtype: Optional[str] = None,
    impl: Optional[str] = None,
    params: Optional[Dict[str, Any]] = None,
) -> List[Dict[str, Any]]:
    """Directed-edge decomposition of one emission: which physical
    links the algorithm's bytes actually ride. Returns a list of
    *phases* — ``{"edges": [(src, dst), ...], "per_edge_bytes",
    "steps"}`` — where ``per_edge_bytes`` is the total bytes each
    listed edge carries across the phase and ``steps`` the
    synchronization rounds the phase contributes (phases are
    sequential; edges within a phase move concurrently).

    The built-in models mirror :func:`cost`: ring AllReduce/RS/AG use
    the ring edges ``r -> (r+1) % n``, AllToAll one rotation per
    displacement, hierarchical AllReduce a per-group fast ring plus a
    stride-``fast`` slow ring, quantized the int8 ring, and verified
    ``algo:*`` impls their proven per-round ``RoundGroup`` edges from
    the m4t-algo/1 lowering. Ops with no meaningful link decomposition
    (trees whose edge set depends on the root, point-to-point with
    unrecorded peers) return ``[]`` — consumers (per-link attribution,
    :func:`expected_time_topo`) skip those records rather than guess."""
    n = int(world) if world else 1
    b = max(0, int(nbytes))
    if n <= 1 or b <= 0:
        return []
    if impl and impl != "hlo":
        phases = _impl_edge_phases(op, impl, b, n, dtype, params or {})
        if phases is not None:
            return phases
    ring = _ring_edges(n)
    if op == "AllReduce":
        return [{"edges": ring,
                 "per_edge_bytes": int(round(2 * (n - 1) * b / n)),
                 "steps": 2 * (n - 1)}]
    if op == "ReduceScatter":
        return [{"edges": ring,
                 "per_edge_bytes": int(round((n - 1) * b / n)),
                 "steps": n - 1}]
    if op == "AllGather":
        return [{"edges": ring, "per_edge_bytes": (n - 1) * b,
                 "steps": n - 1}]
    if op == "AllToAll":
        # pairwise exchange: rotation d moves every rank's block for
        # destination (r+d) % n — one phase per displacement
        return [
            {"edges": [(r, (r + d) % n) for r in range(n)],
             "per_edge_bytes": int(round(b / n)), "steps": 1}
            for d in range(1, n)
        ]
    return []


def _impl_edge_phases(
    op: str,
    impl: str,
    b: int,
    n: int,
    dtype: Optional[str],
    params: Dict[str, Any],
) -> Optional[List[Dict[str, Any]]]:
    """Planner-impl edge decompositions; None falls through to the
    plain op model (same degradation contract as :func:`_impl_cost`)."""
    if impl.startswith("algo:"):
        reg = registered_impl_cost(impl)
        if reg is None or op != reg["op"] or n not in reg["per_world"]:
            return None
        try:
            from ..planner import algo as _algo

            ai = _algo.get(impl)
            low = ai.lowered(n) if ai is not None else None
        except Exception:
            return None
        if low is None:
            return None
        return lowered_phases(low, b)
    if impl == "pallas_ring" and op in (
        "AllReduce", "ReduceScatter", "AllGather"
    ):
        # the Pallas kernels run the same ring schedule over the same
        # edges — only the engine differs
        return edge_phases(op, nbytes=b, world=n, dtype=dtype)
    if impl == "quantized" and op == "AllReduce":
        elems = b // itemsize(dtype)
        hop = _quant_wire_format_bytes(_quant_ring_chunk_elems(elems, n))
        return [{"edges": _ring_edges(n),
                 "per_edge_bytes": 2 * (n - 1) * hop,
                 "steps": 2 * (n - 1)}]
    if impl == "hierarchical" and op == "AllReduce":
        fast = int(params.get("fast") or 0)
        if not (1 < fast < n and n % fast == 0):
            return None
        slow = n // fast
        # fast groups are contiguous rank blocks (the innermost mesh
        # axis is minor in the rank order); the slow ring strides by
        # ``fast`` and is the phase that crosses between groups
        fast_edges: List[Tuple[int, int]] = []
        for g0 in range(0, n, fast):
            fast_edges.extend(
                (g0 + i, g0 + (i + 1) % fast) for i in range(fast)
            )
        slow_edges = [(r, (r + fast) % n) for r in range(n)]
        return [
            {"edges": fast_edges,
             "per_edge_bytes": int(round(2 * (fast - 1) * b / fast)),
             "steps": 2 * (fast - 1)},
            {"edges": slow_edges,
             "per_edge_bytes": int(round(2 * (slow - 1) * (b / fast) / slow)),
             "steps": 2 * (slow - 1)},
        ]
    return None


def lowered_phases(low: Any, nbytes: int) -> List[Dict[str, Any]]:
    """Edge phases of one ``m4t-algo/1`` :class:`~..planner.algo.Lowered`
    schedule at a payload — the decomposition ``expected_time_topo``
    prices. Public so the schedule-space generator (``planner/algogen``)
    and ``planner algo lower --topo`` can price *candidate* lowerings
    that are not (yet) registered impls."""
    b = max(0, int(nbytes))
    chunk_b = -(-b // max(1, int(low.chunks)))
    phases: List[Dict[str, Any]] = []
    for groups in low.rounds:
        first = True
        for g in groups:
            if not g.edges:
                continue
            phases.append({
                "edges": [(int(s), int(d)) for s, d in g.edges],
                "per_edge_bytes": int(g.count) * chunk_b,
                # one synchronization round per simulator round,
                # however many fused bundles it carries
                "steps": 1 if first else 0,
            })
            first = False
    return phases


def record_edge_phases(record: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Edge decomposition of one emission/recorder record (the shared
    JSONL schema), impl-aware like :func:`record_cost`."""
    return edge_phases(
        record.get("op", "?"),
        nbytes=record.get("bytes") or 0,
        world=record.get("world"),
        dtype=record.get("dtype"),
        impl=record.get("impl"),
        params=record.get("impl_params"),
    )


def expected_time_topo(
    op: str,
    *,
    nbytes: int,
    world: Optional[int],
    betas: Dict[Tuple[int, int], float],
    dtype: Optional[str] = None,
    impl: Optional[str] = None,
    params: Optional[Dict[str, Any]] = None,
    gbps: Optional[float] = None,
    alpha: Optional[float] = None,
) -> Optional[float]:
    """Edge-aware alpha-beta expected time: per phase, ``steps *
    alpha`` plus the drain time of the phase's *slowest* link (edges
    in a phase move concurrently, so the phase completes when its
    worst edge does). ``betas`` is the measured per-link bandwidth map
    (``topology.edge_betas``); unmeasured edges price at the uniform
    ``gbps``. Returns None when the op/impl has no edge decomposition
    — callers fall back to :func:`expected_time_s`."""
    phases = edge_phases(
        op, nbytes=nbytes, world=world, dtype=dtype, impl=impl,
        params=params,
    )
    if not phases:
        return None
    return phases_time_topo(phases, betas=betas, gbps=gbps, alpha=alpha)


def phase_drain_topo(
    phase: Dict[str, Any],
    *,
    betas: Dict[Tuple[int, int], float],
    gbps: Optional[float] = None,
) -> Tuple[float, Optional[Tuple[int, int]]]:
    """Drain time of one edge phase over a measured link map: the
    phase completes when its slowest link has moved its bytes.
    Returns ``(seconds, slowest_edge)`` (edge None when the phase has
    no positive-bandwidth edges). Unmeasured edges price at the
    uniform ``gbps``."""
    gbps = peak_gbps() if gbps is None else float(gbps)
    worst = 0.0
    worst_edge: Optional[Tuple[int, int]] = None
    for src, dst in phase["edges"]:
        e = (int(src), int(dst))
        beta = betas.get(e, gbps)
        if beta and beta > 0:
            drain = int(phase["per_edge_bytes"]) / (beta * 1e9)
            if drain >= worst:
                worst, worst_edge = drain, e
    return worst, worst_edge


def phases_time_topo(
    phases: List[Dict[str, Any]],
    *,
    betas: Dict[Tuple[int, int], float],
    gbps: Optional[float] = None,
    alpha: Optional[float] = None,
) -> float:
    """Total edge-aware alpha-beta time of a phase list (the
    :func:`expected_time_topo` accumulation, factored out so
    ``planner/algogen`` and ``algo lower --topo`` price candidate
    lowerings through the identical formula)."""
    alpha = alpha_s() if alpha is None else float(alpha)
    t = 0.0
    for phase in phases:
        t += int(phase["steps"]) * alpha
        worst, _edge = phase_drain_topo(phase, betas=betas, gbps=gbps)
        t += worst
    return t


def record_cost(record: Dict[str, Any]) -> Dict[str, Any]:
    """Cost of one emission/recorder record (the JSONL schema both
    sinks share). Records stamped with a planner ``impl`` tag
    (``ops/_core.py`` under an armed plan) are costed as that
    implementation."""
    return cost(
        record.get("op", "?"),
        nbytes=record.get("bytes") or 0,
        world=record.get("world"),
        dtype=record.get("dtype"),
        impl=record.get("impl"),
        params=record.get("impl_params"),
    )


def expected_time_s(
    c: Dict[str, Any],
    *,
    gbps: Optional[float] = None,
    alpha: Optional[float] = None,
) -> float:
    """Alpha-beta expected time for a cost dict: steps * alpha +
    wire_bytes / peak."""
    gbps = peak_gbps() if gbps is None else float(gbps)
    alpha = alpha_s() if alpha is None else float(alpha)
    beta = c["wire_bytes"] / (gbps * 1e9) if gbps > 0 else 0.0
    return c["steps"] * alpha + beta


def total_cost(
    costs,
    *,
    gbps: Optional[float] = None,
    alpha: Optional[float] = None,
) -> Dict[str, Any]:
    """Aggregate a sequence of per-emission cost dicts into program
    totals: summed wire bytes, summed algorithm steps, and the
    alpha-beta expected time of the whole sequence (collectives are
    serialized by the ordering-token chain, so times add). Consumed by
    the static schedule cost report (``analysis/schedule.py``) and the
    ``lint --cost`` CLI."""
    gbps = peak_gbps() if gbps is None else float(gbps)
    alpha = alpha_s() if alpha is None else float(alpha)
    wire = 0
    steps = 0
    t = 0.0
    for c in costs:
        wire += int(c["wire_bytes"])
        steps += int(c["steps"])
        t += expected_time_s(c, gbps=gbps, alpha=alpha)
    return {"wire_bytes": wire, "steps": steps, "expected_s": t}


def achieved_gbps(c: Dict[str, Any], seconds: float) -> Optional[float]:
    """Achieved wire bandwidth for a measured latency (None when the
    op moved no bytes or the measurement is unusable)."""
    if seconds <= 0 or c["wire_bytes"] <= 0:
        return None
    return c["wire_bytes"] / seconds / 1e9


# ---------------------------------------------------------------------
# overlappable fraction (the overlap observatory's planning prior)
# ---------------------------------------------------------------------

#: per-impl prior for the fraction of an op's wire time a step loop
#: can hide behind independent compute. Chunked/pipelined schedules
#: (the Pallas RDMA ring streams chunk k while chunk k-1 reduces;
#: generated ``algo:`` schedules move data in per-round ppermute hops)
#: expose windows compute can fill; monolithic collectives (one HLO
#: AllReduce, flat quantize->wire->dequantize) hold the whole payload
#: on the critical path. These are *priors*, not measurements — the
#: overlap report prints predicted-vs-achieved per route precisely so
#: the table can be corrected from evidence. Kept separate from
#: :func:`cost` on purpose: the cost() result dict is golden-pinned.
OVERLAPPABLE_FRACTION: Dict[str, float] = {
    "hlo": 0.0,
    "shm": 0.0,
    "quantized": 0.0,
    "pallas_ring": 0.75,
    "hierarchical": 0.25,
}

#: chunked ppermute rounds of a generated m4t-algo/1 schedule
ALGO_OVERLAPPABLE = 0.5

#: impl tag unknown / unplanned emission: assume nothing hides
DEFAULT_OVERLAPPABLE = 0.0


def overlappable_fraction(op: str, impl: Optional[str] = None) -> float:
    """Expected fraction of ``op``'s comm time hideable behind compute
    when routed through ``impl`` (None/unknown impl = the conservative
    default). Point-to-point ops are fully overlappable by
    construction — the caller decides when to wait on them."""
    if op in ("Isend", "Irecv"):
        return 1.0
    if impl is None:
        return DEFAULT_OVERLAPPABLE
    tag = str(impl)
    if tag.startswith("algo:"):
        return ALGO_OVERLAPPABLE
    return OVERLAPPABLE_FRACTION.get(tag, DEFAULT_OVERLAPPABLE)


def expected_exposed_s(
    c: Dict[str, Any],
    *,
    impl: Optional[str] = None,
    gbps: Optional[float] = None,
    alpha: Optional[float] = None,
    fraction: Optional[float] = None,
) -> float:
    """Predicted *exposed* (critical-path) seconds of one costed
    emission: the alpha-beta expected time scaled by the fraction the
    impl cannot hide. ``lint --cost`` sums this per rank so a schedule
    review predicts exposed time before a single step runs."""
    t = expected_time_s(c, gbps=gbps, alpha=alpha)
    f = (
        overlappable_fraction(c.get("op", "?"), impl)
        if fraction is None
        else float(fraction)
    )
    return t * max(0.0, 1.0 - min(1.0, f))
