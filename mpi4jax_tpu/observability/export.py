"""OpenMetrics / Prometheus text export of the live telemetry plane.

Renders a :meth:`..live.LiveAggregator.snapshot` as OpenMetrics 1.0
text (the Prometheus exposition format plus the mandatory ``# EOF``
terminator) and serves it two ways:

- a periodic on-disk snapshot (``metrics.prom`` in the run directory
  under ``launch --live``; atomic tmp+rename so a scraping sidecar
  never reads a torn file) — the zero-dependency path: point a
  ``node_exporter`` textfile collector or a log shipper at it;
- an optional localhost HTTP endpoint
  (``http://127.0.0.1:<port>/metrics``, ``launch --metrics-port`` /
  ``live --port``) for a real Prometheus scrape while the run lives.

Exported families (all prefixed ``m4t_``; labels are escaped per the
exposition-format rules)::

    m4t_live_ranks                      gauge   ranks with any sink
    m4t_live_records_total              counter records ingested
    m4t_rank_last_seq{rank=}            gauge   collective seq per rank
    m4t_rank_heartbeat_age_seconds{rank=} gauge liveness per rank
    m4t_rank_emission_age_seconds{rank=}  gauge progress per rank
    m4t_seq_skew                        gauge   front seq - min seq
    m4t_stalled_seconds                 gauge   time since any progress
    m4t_emissions_total{op=,impl=}      counter per-route emissions
    m4t_payload_bytes_total{op=,impl=}  counter per-route payload
    m4t_throughput_bytes_per_second{op=,impl=} gauge windowed rate
    m4t_achieved_gbps{op=,impl=,axes=}  gauge   attribution join
    m4t_pct_of_peak{op=,impl=,axes=}    gauge   achieved vs cost model
    m4t_plan_key_emissions_total{key=}  counter per plan-key traffic
    m4t_anomalies_total                 counter perf-watch anomalies
    m4t_overlap_ratio[{rank=}]          gauge   comm hidden / total comm
    m4t_comm_exposed_seconds_total[{rank=}] counter exposed comm time
    m4t_topo_link_gbps{src=,dst=}       gauge   per-link achieved GB/s
    m4t_topo_link_probe_gbps{src=,dst=} gauge   per-link probed beta
    m4t_verdicts_total{kind=,klass=}    counter confirmed verdicts

Import-light (stdlib only) like the rest of the offline stack.
"""

from __future__ import annotations

import os
import tempfile
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: the OpenMetrics content type (negotiated by Prometheus scrapers)
CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def _escape(value: Any) -> str:
    """Label-value escaping per the exposition format."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _labels(pairs: Iterable[Tuple[str, Any]]) -> str:
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs)
    return f"{{{inner}}}" if inner else ""


def _num(value: Any) -> str:
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Family:
    def __init__(self, out: List[str], name: str, mtype: str, help_: str):
        self.out = out
        self.name = name
        out.append(f"# TYPE {name} {mtype}")
        out.append(f"# HELP {name} {help_}")

    def sample(self, value: Any, **labels: Any) -> None:
        if value is None:
            return
        self.out.append(
            f"{self.name}{_labels(sorted(labels.items()))} {_num(value)}"
        )


def _split_route(key: str) -> Tuple[str, str]:
    op, _, impl = key.partition("|")
    return op, impl or "-"


def render_openmetrics(
    snap: Dict[str, Any],
    *,
    verdicts: Optional[List[Dict[str, Any]]] = None,
    topo_links: Optional[Dict[str, Dict[str, Any]]] = None,
) -> str:
    """One OpenMetrics exposition of a live snapshot (plus confirmed
    streaming-doctor verdicts and per-link topology attribution, when
    given — ``topo_links`` is the ``topology.attribute_links`` /
    ``topology.edge_betas`` link table keyed ``"src->dst"``)."""
    out: List[str] = []

    g = _Family(out, "m4t_live_ranks", "gauge",
                "Ranks that produced any telemetry sink.")
    g.sample(len(snap.get("ranks", [])))
    c = _Family(out, "m4t_live_records_total", "counter",
                "Telemetry records ingested by the live aggregator.")
    c.sample(snap.get("records", 0))

    g = _Family(out, "m4t_rank_last_seq", "gauge",
                "Last collective sequence number seen per rank.")
    for rank, seq in sorted(snap.get("seqs", {}).items()):
        g.sample(seq, rank=rank)
    g = _Family(out, "m4t_rank_heartbeat_age_seconds", "gauge",
                "Seconds since each rank's last heartbeat record.")
    for rank, age in sorted(snap.get("heartbeat_age_s", {}).items()):
        g.sample(age, rank=rank)
    g = _Family(out, "m4t_rank_emission_age_seconds", "gauge",
                "Seconds since each rank's last collective emission.")
    for rank, age in sorted(snap.get("emission_age_s", {}).items()):
        g.sample(age, rank=rank)

    g = _Family(out, "m4t_seq_skew", "gauge",
                "Front rank seq minus slowest rank seq.")
    g.sample(snap.get("seq_skew", 0))
    g = _Family(out, "m4t_stalled_seconds", "gauge",
                "Seconds since any rank made progress (emission/exec/"
                "latency record).")
    g.sample(snap.get("stalled_s"))

    c = _Family(out, "m4t_emissions_total", "counter",
                "Collective emissions per (op, routed impl).")
    b = _Family(out, "m4t_payload_bytes_total", "counter",
                "Payload bytes per (op, routed impl).")
    for key, tot in sorted(snap.get("totals", {}).items()):
        op, impl = _split_route(key)
        c.sample(tot.get("emissions", 0), op=op, impl=impl)
        b.sample(tot.get("payload_bytes", 0), op=op, impl=impl)

    g = _Family(out, "m4t_throughput_bytes_per_second", "gauge",
                "Windowed payload throughput per (op, routed impl).")
    for key, rate in sorted(snap.get("rates", {}).items()):
        op, impl = _split_route(key)
        g.sample(rate.get("bytes_per_s"), op=op, impl=impl)

    attribution = snap.get("attribution") or {}
    rows = attribution.get("rows") or []
    g = _Family(out, "m4t_achieved_gbps", "gauge",
                "Achieved wire bandwidth per fingerprint group "
                "(cost-model join).")
    p = _Family(out, "m4t_pct_of_peak", "gauge",
                "Achieved bandwidth as a percentage of the modelled "
                "peak.")
    for row in rows:
        labels = {
            "op": row.get("op", "?"),
            "impl": row.get("impl") or "-",
            "axes": row.get("axes", "<none>"),
        }
        g.sample(row.get("achieved_gbps"), **labels)
        p.sample(row.get("pct_of_peak"), **labels)

    c = _Family(out, "m4t_plan_key_emissions_total", "counter",
                "Emissions per collective plan key (plannable ops).")
    for key, tot in sorted(snap.get("plan_keys", {}).items()):
        c.sample(tot.get("emissions", 0), key=key)

    c = _Family(out, "m4t_anomalies_total", "counter",
                "Perf-watch anomaly events observed.")
    c.sample(snap.get("anomalies", 0))

    overlap = snap.get("overlap")
    if overlap:
        # overlap observatory (armed runs only: the snapshot carries
        # the section only when step spans exist on the sinks)
        g = _Family(out, "m4t_overlap_ratio", "gauge",
                    "Fraction of communication time hidden behind "
                    "compute inside step spans (no label: fleet; "
                    "rank label: per rank).")
        g.sample(overlap.get("overlap_ratio"))
        c = _Family(out, "m4t_comm_exposed_seconds_total", "counter",
                    "Communication time not hidden behind compute "
                    "inside step spans.")
        c.sample(overlap.get("comm_exposed_s"))
        for rank, tot in sorted((overlap.get("per_rank") or {}).items()):
            g.sample(tot.get("overlap_ratio"), rank=rank)
            c.sample(tot.get("comm_exposed_s"), rank=rank)

    if topo_links:
        g = _Family(out, "m4t_topo_link_gbps", "gauge",
                    "Achieved (or probed) bandwidth per directed link "
                    "(topology observatory).")
        p = _Family(out, "m4t_topo_link_probe_gbps", "gauge",
                    "Probe-fitted beta per directed link "
                    "(m4t-topo/1 map).")
        for key in sorted(topo_links):
            row = topo_links[key]
            src, _, dst = str(key).partition("->")
            src = row.get("src", src)
            dst = row.get("dst", dst)
            g.sample(row.get("gbps_p50"), src=src, dst=dst)
            p.sample(row.get("beta_gbps"), src=src, dst=dst)

    c = _Family(out, "m4t_verdicts_total", "counter",
                "Confirmed streaming-doctor verdicts.")
    counts: Dict[Tuple[str, str], int] = {}
    for v in verdicts or []:
        k = (
            str(v.get("finding", {}).get("kind", "?")),
            str(v.get("klass", "?")),
        )
        counts[k] = counts.get(k, 0) + 1
    for (kind, klass), n in sorted(counts.items()):
        c.sample(n, kind=kind, klass=klass)

    out.append("# EOF")
    return "\n".join(out) + "\n"


def write_prom(path: str, text: str) -> str:
    """Atomic snapshot write (tmp + rename, the repo's commit idiom):
    a scraper reading ``path`` sees the old exposition or the new one,
    never a torn one."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".prom-", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


# ---------------------------------------------------------------------
# localhost HTTP endpoint
# ---------------------------------------------------------------------


class _MetricsHandler(BaseHTTPRequestHandler):
    render = staticmethod(lambda: "# EOF\n")  # replaced per server

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path.split("?", 1)[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        try:
            body = self.server.render().encode()  # type: ignore[attr-defined]
        except Exception as exc:  # pragma: no cover — render best-effort
            self.send_error(500, str(exc))
            return
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *_args):  # silence per-request stderr noise
        pass


def serve(render, *, port: int = 0, host: str = "127.0.0.1"):
    """Serve ``render()`` (the OpenMetrics text) on
    ``http://host:port/metrics`` from a daemon thread. ``port=0``
    binds a free port — read it back from ``server.server_port``.
    Call ``server.shutdown()`` to stop. Localhost by default on
    purpose: telemetry is an operator surface, not a public one."""
    server = ThreadingHTTPServer((host, int(port)), _MetricsHandler)
    server.daemon_threads = True
    server.render = render  # type: ignore[attr-defined]
    thread = threading.Thread(
        target=server.serve_forever, name="m4t-metrics-http", daemon=True
    )
    thread.start()
    return server
