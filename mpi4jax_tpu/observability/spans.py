"""Per-job lifecycle spans: the serving plane's distributed trace.

PRs 10–11 put every queue transition on ``serving.jsonl`` as audit
*points* (submitted, admitted, completed). This module upgrades the
transition points into **spans** — named intervals with a start and an
end on one wall clock — so a job's life is a gapless chain instead of
a list of timestamps to mentally subtract:

==================  ==================================================
span                covers
==================  ==================================================
``queued``          submit accepted -> claimed by a server
``verify``          the static admission gate (only when it ran)
``dispatch``        claim/verify -> the per-job supervisor starts
``run``             first attempt spawned -> last attempt finished
``result``          supervisor done -> outcome durably in ``done/``
==================  ==================================================

``queued -> [verify] -> dispatch -> run -> result`` is the **chain**:
adjacent spans share their boundary timestamp by construction (the
server reuses the same clock read), so chain completeness is a
checkable property, not a hope — :func:`verify_chain` proves a job's
chain is present, ordered, and gapless, and the span-chain test in
``tests/test_spans.py`` asserts it for every terminal job id.

Inside ``run``, *child* spans attribute where the time went:

- ``attempt<k>`` — one world attempt (emitted by the
  :class:`~..resilience.supervisor.Supervisor` through its ``span_fn``
  seam),
- ``spawn`` — the cold path's fork loop (``launch.spawn_world``),
- ``warm_dispatch`` — the warm pool's mailbox hand-off
  (``serving/pool.py``),
- ``reshard`` — the elastic checkpoint reshard between attempts.

The event-driven dispatch plane (``serving/dispatch.py``) batches
claims and coalesces same-shape jobs into one sub-mesh run; every
member job still gets its own full chain, with adjacent boundaries
shared across members by the same reused-clock-read construction, so
:func:`verify_chain` holds unchanged. Coalesced members' spans carry
additive ``coalesced``/``batch``/``leader`` fields (never emitted on
the classic path — its record schema stays byte-identical) that mark
which world actually executed.

Span records are ``kind: "span"`` lines appended to the *same*
``serving.jsonl`` the audit uses (one file still tells the whole
story; every pre-existing reader filters on ``kind == "serving"`` and
is unaffected), each carrying the job's ``trace`` id — the key that
joins them to the per-rank emission/exec/latency records stamped by
``ops/_core.py`` when ``M4T_TRACE_ID`` is armed. ``trace --serve
SPOOL`` (:mod:`.trace`) renders the whole thing as one Perfetto file:
per-tenant process groups, one lifecycle track per job, and the job's
per-rank collective slices nested under its ``run`` span.

CLI::

    python -m mpi4jax_tpu.observability.spans SPOOL [--json]
    python -m mpi4jax_tpu.observability.spans --selftest

The selftest is device-free (a stub-runner serving loop in a temp
dir), per the standing ``--selftest`` constraint.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from typing import Any, Dict, Iterable, List, Optional

SPAN_SCHEMA = "m4t-span/1"

#: the top-level chain, in order (``verify`` is optional)
CHAIN = ("queued", "verify", "dispatch", "run", "result")
REQUIRED = ("queued", "dispatch", "run", "result")

#: child spans live inside ``run`` and never break the chain
_ATTEMPT_RE = re.compile(r"^attempt(\d+)$")
CHILD_SPANS = frozenset({"spawn", "warm_dispatch", "reshard"})

#: adjacent chain spans share a boundary clock read; anything beyond
#: this is a real gap (a transition nobody recorded)
GAP_TOLERANCE_S = 1e-6


def is_child(name: str) -> bool:
    return name in CHILD_SPANS or bool(_ATTEMPT_RE.match(name or ""))


def span_record(
    name: str,
    *,
    job: str,
    t0: float,
    t1: float,
    trace: Optional[str] = None,
    tenant: Optional[str] = None,
    **fields: Any,
) -> Dict[str, Any]:
    """Build one ``m4t-span/1`` record (the shape ``Spool.span``
    appends)."""
    rec: Dict[str, Any] = {
        "kind": "span",
        "schema": SPAN_SCHEMA,
        "span": str(name),
        "job": str(job),
        "t0": float(t0),
        "t1": float(t1),
        "dur_s": round(max(0.0, float(t1) - float(t0)), 9),
    }
    if trace:
        rec["trace"] = str(trace)
    if tenant:
        rec["tenant"] = str(tenant)
    for key, value in fields.items():
        if value is not None:
            rec[key] = value
    return rec


# ---------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------


def _audit_paths(inputs: Iterable[str]) -> List[str]:
    """``serving.jsonl`` beside each input or up to three levels up —
    the same discovery walk as ``doctor.load_serving_audit``, so a
    span reader pointed at a job attempt dir finds the spool."""
    seen: set = set()
    out: List[str] = []
    for item in inputs:
        d = item if os.path.isdir(item) else os.path.dirname(item)
        d = os.path.abspath(d)
        cands = [d]
        for _ in range(3):
            cands.append(os.path.dirname(cands[-1]))
        for cand in cands:
            path = os.path.join(cand, "serving.jsonl")
            if path in seen:
                continue
            seen.add(path)
            if os.path.exists(path):
                out.append(path)
    return out


def load_spans(inputs: Iterable[str]) -> List[Dict[str, Any]]:
    """Every ``kind == "span"`` record reachable from the given files
    or directories (a spool root, a job dir, or ``serving.jsonl``
    itself)."""
    from . import events

    records: List[Dict[str, Any]] = []
    for path in _audit_paths(inputs):
        try:
            records.extend(
                r for r in events.iter_records(path)
                if r.get("kind") == "span"
            )
        except OSError:
            continue
    return records


def chains(
    records: Iterable[Dict[str, Any]],
) -> Dict[str, List[Dict[str, Any]]]:
    """Span records grouped per job, chain spans first, each group
    sorted by ``t0`` (ties broken by chain order so zero-width spans
    stay in lifecycle order)."""
    rank = {name: i for i, name in enumerate(CHAIN)}
    by_job: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("kind") != "span" or not rec.get("job"):
            continue
        by_job.setdefault(str(rec["job"]), []).append(rec)
    for job, spans in by_job.items():
        spans.sort(key=lambda r: (
            float(r.get("t0") or 0.0),
            rank.get(r.get("span"), len(CHAIN)),
        ))
    return by_job


def verify_chain(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Prove one job's chain: every required span present exactly
    once, in order, gapless (adjacent boundaries equal within
    :data:`GAP_TOLERANCE_S`), children inside ``run``. Returns::

        {"complete": bool, "missing": [...], "problems": [...],
         "spans": [names in order], "trace": <id or None>}
    """
    chain = [s for s in spans if s.get("span") in CHAIN]
    children = [s for s in spans if is_child(s.get("span", ""))]
    names = [s["span"] for s in chain]
    problems: List[str] = []
    missing = [n for n in REQUIRED if n not in names]
    for name in CHAIN:
        if names.count(name) > 1:
            problems.append(f"span {name!r} appears {names.count(name)}x")
    expected = [n for n in CHAIN if n in names]
    if names != expected:
        problems.append(f"chain out of order: {names}")
    for prev, cur in zip(chain, chain[1:]):
        gap = float(cur.get("t0") or 0.0) - float(prev.get("t1") or 0.0)
        if gap > GAP_TOLERANCE_S:
            problems.append(
                f"gap of {gap:.6f}s between {prev['span']!r} and "
                f"{cur['span']!r}"
            )
        if gap < -GAP_TOLERANCE_S:
            problems.append(
                f"{cur['span']!r} starts {-gap:.6f}s before "
                f"{prev['span']!r} ends"
            )
    run = next((s for s in chain if s["span"] == "run"), None)
    if run is not None:
        for child in children:
            t0 = float(child.get("t0") or 0.0)
            t1 = float(child.get("t1") or 0.0)
            if t0 < float(run["t0"]) - GAP_TOLERANCE_S or (
                t1 > float(run["t1"]) + GAP_TOLERANCE_S
            ):
                problems.append(
                    f"child span {child['span']!r} escapes run window"
                )
    traces = {s.get("trace") for s in spans if s.get("trace")}
    if len(traces) > 1:
        problems.append(f"spans carry {len(traces)} distinct trace ids")
    return {
        "complete": not missing and not problems,
        "missing": missing,
        "problems": problems,
        "spans": [s["span"] for s in spans],
        "trace": next(iter(traces)) if traces else None,
    }


def verify_chains(
    records: Iterable[Dict[str, Any]],
    *,
    jobs: Optional[Iterable[str]] = None,
) -> Dict[str, Dict[str, Any]]:
    """Chain verdicts per job. ``jobs`` restricts (and *requires*) the
    checked set — pass the terminal job ids from the serving audit and
    a job that finished without ever writing spans shows up as an
    all-missing chain instead of silently passing."""
    by_job = chains(records)
    targets = list(jobs) if jobs is not None else sorted(by_job)
    return {job: verify_chain(by_job.get(job, [])) for job in targets}


def collect_job_records(
    root: str,
    job_id: str,
    trace: Optional[str] = None,
) -> Dict[int, List[Dict[str, Any]]]:
    """One job's per-rank telemetry records, wherever they landed:

    - the cold path writes dedicated dirs
      (``SPOOL/jobs/<id>/attempt<k>/events-rank*.jsonl``) — everything
      there belongs to the job;
    - the warm path executes in resident workers whose sinks
      (``SPOOL/pool/events-rank*.jsonl``) interleave *every* job the
      worker ever served — there, only records stamped with the job's
      ``trace`` id (or ``job`` field) are attributable, which is
      exactly why ``ops/_core.py`` stamps them.

    Output is the ``doctor.load`` by-rank shape, so the trace export
    and the perf attribution join consume it unchanged.
    """
    from . import doctor

    root = os.path.abspath(root)
    by_rank: Dict[int, List[Dict[str, Any]]] = {}
    jobdir = os.path.join(root, "jobs", job_id)
    if os.path.isdir(jobdir):
        attempts = sorted(
            os.path.join(jobdir, d) for d in os.listdir(jobdir)
            if d.startswith("attempt")
        )
        for rank, recs in doctor.load(attempts).items():
            by_rank.setdefault(rank, []).extend(recs)
    pool_dir = os.path.join(root, "pool")
    if os.path.isdir(pool_dir):
        for rank, recs in doctor.load([pool_dir]).items():
            matched = [
                r for r in recs
                if (trace and r.get("trace") == trace)
                or r.get("job") == job_id
            ]
            if matched:
                by_rank.setdefault(rank, []).extend(matched)
    for recs in by_rank.values():
        recs.sort(key=lambda r: (
            r.get("t") if isinstance(r.get("t"), (int, float)) else 0.0
        ))
    return by_rank


def terminal_jobs(audit_records: Iterable[Dict[str, Any]]) -> List[str]:
    """Job ids that reached a terminal outcome in a ``serving.jsonl``
    audit stream (completed/failed — rejected jobs never ran, so they
    carry no chain)."""
    out: Dict[str, None] = {}
    for rec in audit_records:
        if rec.get("event") in ("completed", "failed") and rec.get("job"):
            out.setdefault(str(rec["job"]))
    return list(out)


# ---------------------------------------------------------------------
# CLI + selftest
# ---------------------------------------------------------------------


def format_chains(verdicts: Dict[str, Dict[str, Any]]) -> str:
    lines = [f"span chains ({len(verdicts)} job(s)):"]
    for job in sorted(verdicts):
        v = verdicts[job]
        if v["complete"]:
            lines.append(
                f"  {job}: complete ({' -> '.join(v['spans'])})"
            )
        else:
            detail = "; ".join(
                ([f"missing {', '.join(v['missing'])}"]
                 if v["missing"] else []) + v["problems"]
            )
            lines.append(f"  {job}: INCOMPLETE — {detail}")
    return "\n".join(lines)


def selftest() -> int:
    """Device-free proof of the span plane: a stub-runner serving loop
    writes real spans for clean/failing/retried jobs, every terminal
    job's chain verifies complete, and the known failure shapes
    (missing span, gap, out-of-order) are named."""
    import tempfile

    # synthetic verdicts first: the checker itself
    good = [
        span_record("queued", job="j", t0=1.0, t1=2.0, trace="tr"),
        span_record("dispatch", job="j", t0=2.0, t1=2.5, trace="tr"),
        span_record("run", job="j", t0=2.5, t1=5.0, trace="tr"),
        span_record("attempt0", job="j", t0=2.5, t1=5.0, trace="tr"),
        span_record("result", job="j", t0=5.0, t1=5.1, trace="tr"),
    ]
    v = verify_chain(good)
    assert v["complete"], v
    assert v["trace"] == "tr"
    v = verify_chain([s for s in good if s["span"] != "dispatch"])
    assert not v["complete"] and v["missing"] == ["dispatch"], v
    gapped = [dict(s) for s in good]
    gapped[2] = span_record("run", job="j", t0=3.0, t1=5.0, trace="tr")
    v = verify_chain(gapped)
    assert not v["complete"] and any("gap" in p for p in v["problems"]), v
    stray = good + [
        span_record("attempt1", job="j", t0=6.0, t1=7.0, trace="tr")
    ]
    v = verify_chain(stray)
    assert any("escapes run" in p for p in v["problems"]), v

    # the real serving loop, stub runner: spans come from the actual
    # server/supervisor/spool transition points
    from ..serving.server import Server
    from ..serving.spool import Spool

    with tempfile.TemporaryDirectory() as tmp:
        spool = Spool(os.path.join(tmp, "spool"))
        for obj in (
            {"id": "ok", "tenant": "a", "cmd": ["-c", "pass"]},
            {"id": "flaky", "tenant": "b", "cmd": ["-c", "pass"],
             "retries": 1, "backoff_s": 0.0},
            {"id": "bad", "tenant": "a", "cmd": ["-c", "pass"]},
        ):
            r = spool.submit(obj)
            assert r["status"] == "queued", r

        def stub(spec, world, events_dir, attempt, resume_step):
            if spec.id == "bad":
                return 1, []
            if spec.id == "flaky" and attempt == 0:
                return 1, []
            return 0, []

        server = Server(
            spool, nproc=1, max_jobs=3, poll_s=0.01, runner=stub,
            log=lambda msg: None,
        )
        assert server.serve() == 0
        audit = spool.audit_records()
        terminals = terminal_jobs(audit)
        assert sorted(terminals) == ["bad", "flaky", "ok"], terminals
        verdicts = verify_chains(spool.span_records(), jobs=terminals)
        for job, v in verdicts.items():
            assert v["complete"], (job, v)
            assert v["trace"], (job, "span chain lost its trace id")
        # the retried job's run span contains both attempt children
        flaky = [
            s for s in chains(spool.span_records())["flaky"]
            if _ATTEMPT_RE.match(s["span"])
        ]
        assert [s["span"] for s in flaky] == ["attempt0", "attempt1"], flaky
        # done records carry the trace id minted at submit
        for rec in spool.done():
            assert rec.get("trace"), rec
        text = format_chains(verdicts)
        assert "complete" in text and "INCOMPLETE" not in text, text
    print("spans selftest ok")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    argv = list(sys.argv[1:] if argv is None else argv)
    if "--selftest" in argv:
        return selftest()
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.observability.spans",
        description="Verify per-job lifecycle span chains in a "
        "serving spool (queued -> [verify] -> dispatch -> run -> "
        "result, gapless).",
    )
    parser.add_argument(
        "inputs", nargs="+",
        help="spool root(s), job dirs, or serving.jsonl files",
    )
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)
    records = load_spans(args.inputs)
    if not records:
        print("spans: no span records in the given inputs",
              file=sys.stderr)
        return 2
    verdicts = verify_chains(records)
    if args.json:
        print(json.dumps(verdicts, indent=1, sort_keys=True))
    else:
        print(format_chains(verdicts))
    return 0 if all(v["complete"] for v in verdicts.values()) else 1


if __name__ == "__main__":
    sys.exit(main())


# re-exported for harness convenience (the server emits through the
# spool; tests build records directly)
__all__ = [
    "CHAIN",
    "REQUIRED",
    "CHILD_SPANS",
    "SPAN_SCHEMA",
    "chains",
    "format_chains",
    "is_child",
    "load_spans",
    "span_record",
    "terminal_jobs",
    "verify_chain",
    "verify_chains",
]


# keep a stable reference for "now" so server/pool/supervisor all
# stamp spans off one clock function (patchable in tests)
now = time.time
