"""Cross-rank post-mortem doctor: merge per-rank logs, name the bug.

The failure modes that actually kill SPMD programs are cross-rank
phenomena no single rank's log can diagnose:

- **mismatch** — ranks diverge in what they emit at the same sequence
  number (rank 0's 17th collective is an AllReduce, rank 1's is an
  AllGather, or same op with a different shape/dtype/mesh-axes
  fingerprint). Token ordering serializes emissions per rank, so equal
  seq ⇒ must be the same collective; the first unequal seq is where
  the program forked.
- **hang** — one rank's emission stream ends K or more seqs before its
  peers'. Heartbeat records separate the two sub-cases: a rank whose
  heartbeats kept arriving long after its last emission is *alive but
  stuck* (blocked inside a collective its peers never joined); a rank
  whose heartbeats stopped with its emissions is *gone* (crashed or
  killed).
- **straggler** — a rank whose runtime latency samples for an op are
  far above its peers' (slow host, bad link, noisy neighbor). Needs
  ``latency`` records (``M4T_TELEMETRY_RUNTIME=1``).

Inputs are the per-rank artifacts the rest of the subsystem produces:
JSONL event sinks (``launch --events-dir``, rank-templated
``M4T_TELEMETRY_EVENTS``) and/or flight-recorder dumps
(``recorder-rank*.jsonl``). Records carry their rank; filenames like
``...rank3.jsonl`` are the fallback.

CLI::

    python -m mpi4jax_tpu.observability.doctor RUNDIR
    python -m mpi4jax_tpu.observability.doctor rank0.jsonl rank1.jsonl \
        --json --hang-gap 2 --trace merged-trace.json
    python -m mpi4jax_tpu.observability.doctor RUNDIR \
        --static train.py:step --static-arg 'f32[1024]'

``--static`` cross-references runtime verdicts against the static
linter's CollectiveSites (``mpi4jax_tpu/analysis/``) by fingerprint:
a MISMATCH then names the source line of each diverging collective.

Exit status: 0 clean, 1 findings, 2 no usable input. Used by the
launcher's hang watchdog (``launch.py --hang-timeout``) to print a
diagnosis the moment a world is torn down.

``--json`` output is a **stable machine contract** (consumed by the
resilience supervisor and CI, not scraped from text), versioned by the
top-level ``schema`` field (:data:`SCHEMA`). The ``m4t-doctor/1``
schema::

    {"schema": "m4t-doctor/1",
     "ranks": [int, ...],             # ranks that produced any log
     "records": {"<rank>": int},      # raw records loaded per rank
     "seqs": {"<rank>": int},         # last collective seq per rank
     "findings": [ ... ]}             # ordered most- to least-causal

Finding kinds and their stable fields:

- ``mismatch`` — ``seq``, ``fingerprints`` {rank: fp},
  ``groups`` [{``fingerprint``, ``ranks``, and — when ``--static``
  joined — ``static_sites`` [{``index``, ``source``, ``path``,
  ``fingerprint``}]}];
- ``hang`` — ``rank``, ``verdict`` (``hung``/``dead``/``behind``),
  ``last_seq``, ``front_seq``, ``gap``, ``front_ranks``,
  ``stuck_before`` (fingerprint or null), ``last_heartbeat_t``,
  ``last_emission_t``, optional ``wedged`` (true when the rank
  *recorded* its last collective but — per the ``exec`` records
  runtime sampling mirrors to the sink — never began executing it
  while a peer did: the equal-seq hang a stream-length gap cannot
  show; ``gap`` is 0 and ``stuck_before`` is the rank's own
  never-executed collective), optional ``static_sites``, optional
  ``schedule_position`` (with ``--static``: the hung rank's position
  in its *simulated* per-rank schedule — ``expected_next`` names the
  collective it should emit next, ``peers_next`` what each peer
  expects, even when no peer log reached that seq);
- ``missing_rank`` — ``rank``, ``world``, ``note``;
- ``straggler`` — ``op``, ``rank``, ``mean_s``, ``peer_median_s``,
  ``ratio``, ``samples``, ``min_samples``, ``peer_samples``, optional
  ``link_diagnosis`` (with a measured ``m4t-topo/1`` map — ``--topo``
  or an auto-detected ``topology.json`` beside the inputs:
  ``topology.classify_rank``'s link-bound vs rank-bound verdict,
  naming the slowest incident edge and its measured-vs-fleet-median
  beta).

New fields may be added within a schema version; existing ones are
renamed or removed only with a version bump. Exit codes are part of
the contract and unchanged by ``--json``.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional

from . import events
from .recorder import fingerprint

#: report-schema version tag: the supervisor/CI contract for ``--json``
#: (and the dict ``analyze``/``diagnose`` return); bump only on
#: renames/removals, never for additive fields
SCHEMA = "m4t-doctor/1"

#: a rank is reported hung/behind when it trails the front rank by at
#: least this many seqs (1: any divergence in stream length matters —
#: token-ordered streams can legitimately differ by the one collective
#: currently in flight, so findings at gap 1 are advisory)
DEFAULT_HANG_GAP = 1

#: a rank is a straggler when its mean op latency exceeds the median
#: of the per-rank means by this factor
DEFAULT_STRAGGLER_RATIO = 2.0

#: minimum per-op latency samples a rank needs before it may be
#: compared at all: a single slow sample (first-execution warmup, a
#: page fault) must not brand a rank a straggler
DEFAULT_STRAGGLER_MIN_SAMPLES = 5

_RANK_RE = re.compile(r"rank[-_]?(\d+)")


# ---------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------


def _rank_of(record: Dict[str, Any], path: str) -> Optional[int]:
    rank = record.get("rank")
    if isinstance(rank, int):
        return rank
    if isinstance(rank, str) and rank.isdigit():
        return int(rank)
    m = _RANK_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def _expand_inputs(inputs: Iterable[str]) -> List[str]:
    paths: List[str] = []
    for item in inputs:
        if os.path.isdir(item):
            paths.extend(sorted(glob.glob(os.path.join(item, "*.jsonl"))))
        else:
            paths.append(item)
    # dedupe, keep order
    seen = set()
    out = []
    for p in paths:
        if p not in seen:
            seen.add(p)
            out.append(p)
    return out


def load(inputs: Iterable[str]) -> Dict[int, List[Dict[str, Any]]]:
    """Read every JSONL record from files/directories, grouped by
    rank. Records whose rank cannot be determined (no ``rank`` field,
    no ``rank<k>`` in the filename) are attributed to rank 0 only if
    nothing else claims a rank — otherwise they are dropped."""
    by_rank: Dict[int, List[Dict[str, Any]]] = defaultdict(list)
    unattributed: List[Dict[str, Any]] = []
    for path in _expand_inputs(inputs):
        for rec in events.iter_records(path):
            rank = _rank_of(rec, path)
            if rank is None:
                unattributed.append(rec)
            else:
                by_rank[rank].append(rec)
    if not by_rank and unattributed:
        by_rank[0] = unattributed
    return dict(by_rank)


def collective_stream(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One rank's ordered collective stream: ``emission`` (event sink)
    and ``recorder`` (flight-recorder dump) records merged by seq,
    preferring the richer ``emission`` record when both describe the
    same seq. Records without a seq (pre-PR2 logs) keep file order and
    are assigned positional seqs — alignment still works on artifacts
    from older runs."""
    chosen: Dict[int, Dict[str, Any]] = {}
    unseq: List[Dict[str, Any]] = []
    for rec in records:
        if rec.get("kind") not in ("emission", "recorder"):
            continue
        seq = rec.get("seq")
        if not isinstance(seq, int):
            unseq.append(rec)
            continue
        prev = chosen.get(seq)
        if prev is None or (
            prev.get("kind") == "recorder" and rec.get("kind") == "emission"
        ):
            chosen[seq] = rec
    stream = [chosen[k] for k in sorted(chosen)]
    if not stream and unseq:
        stream = [dict(rec, seq=i + 1) for i, rec in enumerate(unseq)]
    return stream


# ---------------------------------------------------------------------
# analyses
# ---------------------------------------------------------------------


def _find_mismatch(
    streams: Dict[int, List[Dict[str, Any]]]
) -> List[Dict[str, Any]]:
    """First seq at which the per-rank fingerprints disagree."""
    if len(streams) < 2:
        return []
    by_seq: Dict[int, Dict[int, str]] = defaultdict(dict)
    for rank, stream in streams.items():
        for rec in stream:
            by_seq[rec["seq"]][rank] = fingerprint(rec)
    for seq in sorted(by_seq):
        prints = by_seq[seq]
        if len(prints) < 2:
            continue  # only one rank got this far — hang analysis' job
        if len(set(prints.values())) > 1:
            groups: Dict[str, List[int]] = defaultdict(list)
            for rank, fp in sorted(prints.items()):
                groups[fp].append(rank)
            return [
                {
                    "kind": "mismatch",
                    "seq": seq,
                    "fingerprints": {str(r): fp for r, fp in sorted(prints.items())},
                    "groups": [
                        {"fingerprint": fp, "ranks": ranks}
                        for fp, ranks in groups.items()
                    ],
                }
            ]
    return []


def _last_heartbeat_t(records: List[Dict[str, Any]]) -> Optional[float]:
    ts = [
        rec.get("t")
        for rec in records
        if rec.get("kind") == "heartbeat" and isinstance(rec.get("t"), (int, float))
    ]
    return max(ts) if ts else None


def _find_hang(
    streams: Dict[int, List[Dict[str, Any]]],
    by_rank: Dict[int, List[Dict[str, Any]]],
    hang_gap: int,
) -> List[Dict[str, Any]]:
    """Ranks whose stream ends >= hang_gap seqs before the front rank,
    plus ranks missing entirely from a world the logs describe."""
    findings: List[Dict[str, Any]] = []
    if not streams:
        return findings
    last_seq = {rank: (s[-1]["seq"] if s else 0) for rank, s in streams.items()}
    front = max(last_seq.values())
    front_ranks = sorted(r for r, s in last_seq.items() if s == front)
    for rank in sorted(streams):
        gap = front - last_seq[rank]
        if gap < max(1, hang_gap):
            continue
        stream = streams[rank]
        last_emit_t = (
            stream[-1].get("t") if stream and isinstance(
                stream[-1].get("t"), (int, float)
            ) else None
        )
        hb_t = _last_heartbeat_t(by_rank.get(rank, []))
        if hb_t is not None and last_emit_t is not None and hb_t > last_emit_t + 1.0:
            verdict = "hung"  # alive (heartbeats continued) but stopped emitting
        elif hb_t is not None:
            verdict = "dead"  # heartbeats stopped with the emissions
        else:
            verdict = "behind"  # no liveness signal: hung or merely slow
        # what the front ranks emitted at the seq this rank never reached
        next_seq = last_seq[rank] + 1
        expected = None
        for fr in front_ranks:
            for rec in streams[fr]:
                if rec["seq"] == next_seq:
                    expected = fingerprint(rec)
                    break
            if expected:
                break
        findings.append(
            {
                "kind": "hang",
                "rank": rank,
                "verdict": verdict,
                "last_seq": last_seq[rank],
                "front_seq": front,
                "gap": gap,
                "front_ranks": front_ranks,
                "stuck_before": expected,
                "last_heartbeat_t": hb_t,
                "last_emission_t": last_emit_t,
            }
        )
    # one-rank-missing: the logs say the world was bigger than the set
    # of ranks that produced any log at all
    worlds = [
        rec.get("world")
        for recs in by_rank.values()
        for rec in recs
        if isinstance(rec.get("world"), int)
    ]
    if worlds:
        world = max(worlds)
        missing = sorted(set(range(world)) - set(by_rank))
        for rank in missing:
            findings.append(
                {
                    "kind": "missing_rank",
                    "rank": rank,
                    "world": world,
                    "note": "no log produced by this rank at all",
                }
            )
    return findings


def _executed_seqs(records: List[Dict[str, Any]]) -> set:
    """Alignment keys (seqs) this rank is known to have begun
    executing: ``exec`` records (runtime-start mirror, see
    ``metrics.mark_runtime_start``) and ``latency`` records (an end
    implies a start)."""
    out = set()
    for rec in records:
        if rec.get("kind") in ("exec", "latency") and isinstance(
            rec.get("seq"), int
        ):
            out.add(rec["seq"])
    return out


def _find_wedged(
    streams: Dict[int, List[Dict[str, Any]]],
    by_rank: Dict[int, List[Dict[str, Any]]],
) -> List[Dict[str, Any]]:
    """Equal-seq hangs the gap analysis cannot see.

    A rank that wedges *between recording a collective and executing
    it* (a stall in trace, a fault-injected hang, a deadlock before
    the native call) leaves the same stream length as its peers: the
    emission is written before the stall, and the peers — having
    entered the collective — block waiting for it, so nobody gets a
    seq ahead. The tiebreaker is the execution-side evidence runtime
    sampling leaves behind: ``exec``/``latency`` records. A rank at
    the front seq with *no* execution record for it, while some peer
    at the same seq has one, is stuck **before** its own last
    collective. Guard: the stuck rank must have execution records for
    earlier seqs (proof its callback path works), so a backend that
    never delivers callbacks can't be misread as wedged."""
    if len(streams) < 2:
        return []
    last_seq = {rank: (s[-1]["seq"] if s else 0) for rank, s in streams.items()}
    front = max(last_seq.values(), default=0)
    if front <= 0:
        return []
    at_front = [r for r, s in last_seq.items() if s == front]
    if len(at_front) < 2:
        return []
    executed = {r: _executed_seqs(by_rank.get(r, [])) for r in at_front}
    started = sorted(r for r in at_front if front in executed[r])
    stuck = sorted(
        r for r in at_front if executed[r] and front not in executed[r]
    )
    if not started or not stuck:
        return []
    findings = []
    for rank in stuck:
        stream = streams[rank]
        rec = stream[-1]
        last_emit_t = (
            rec.get("t") if isinstance(rec.get("t"), (int, float)) else None
        )
        hb_t = _last_heartbeat_t(by_rank.get(rank, []))
        if hb_t is not None and last_emit_t is not None and hb_t > last_emit_t + 1.0:
            verdict = "hung"
        elif hb_t is not None:
            verdict = "dead"
        else:
            verdict = "behind"
        findings.append(
            {
                "kind": "hang",
                "rank": rank,
                "verdict": verdict,
                "last_seq": front,
                "front_seq": front,
                "gap": 0,
                "front_ranks": started,
                "stuck_before": fingerprint(rec),
                "last_heartbeat_t": hb_t,
                "last_emission_t": last_emit_t,
                "wedged": True,
            }
        )
    return findings


def _find_stragglers(
    by_rank: Dict[int, List[Dict[str, Any]]],
    ratio: float,
    min_samples: int = DEFAULT_STRAGGLER_MIN_SAMPLES,
) -> List[Dict[str, Any]]:
    """Per-op, per-rank mean runtime latency vs the median rank.
    Ranks with fewer than ``min_samples`` samples for an op are not
    compared (in either direction): one noisy sample is not evidence,
    and a rank with thin data must not serve as a peer baseline
    either. The finding payload carries every compared rank's sample
    count so the verdict's statistical footing is auditable."""
    min_samples = max(1, int(min_samples))
    samples: Dict[str, Dict[int, List[float]]] = defaultdict(lambda: defaultdict(list))
    for rank, recs in by_rank.items():
        for rec in recs:
            if rec.get("kind") == "latency" and isinstance(
                rec.get("seconds"), (int, float)
            ):
                samples[rec.get("op", "?")][rank].append(float(rec["seconds"]))
    findings: List[Dict[str, Any]] = []
    for op, per_rank in sorted(samples.items()):
        means = {
            rank: sum(vals) / len(vals)
            for rank, vals in per_rank.items()
            if len(vals) >= min_samples
        }
        if len(means) < 2:
            continue
        for rank, mean in sorted(means.items()):
            # median of the *other* ranks: with 2 ranks a rank must
            # not be its own reference, or the outlier defines normal
            peers = sorted(v for r, v in means.items() if r != rank)
            peer_median = peers[(len(peers) - 1) // 2]
            if peer_median <= 0:
                continue
            if mean > ratio * peer_median:
                findings.append(
                    {
                        "kind": "straggler",
                        "op": op,
                        "rank": rank,
                        "mean_s": mean,
                        "peer_median_s": peer_median,
                        "ratio": mean / peer_median,
                        "samples": len(per_rank[rank]),
                        "min_samples": min_samples,
                        "peer_samples": {
                            str(r): len(per_rank[r])
                            for r in sorted(means)
                            if r != rank
                        },
                    }
                )
    return findings


def analyze(
    by_rank: Dict[int, List[Dict[str, Any]]],
    *,
    hang_gap: int = DEFAULT_HANG_GAP,
    straggler_ratio: float = DEFAULT_STRAGGLER_RATIO,
    straggler_min_samples: int = DEFAULT_STRAGGLER_MIN_SAMPLES,
) -> Dict[str, Any]:
    """Run every cross-rank analysis; returns a plain-JSON report:
    ``{"ranks": [...], "seqs": {rank: last_seq}, "findings": [...]}``
    with findings ordered mismatch > hang/missing > straggler (the
    order in which a human should read them: a mismatch usually
    *causes* the hang that follows it)."""
    streams = {rank: collective_stream(recs) for rank, recs in by_rank.items()}
    mismatches = _find_mismatch(streams)
    findings = (
        mismatches
        + _find_hang(streams, by_rank, hang_gap)
        # the wedge tiebreaker only when the program didn't fork: a
        # mismatch at the front seq already explains why nobody there
        # executed (different collectives can't rendezvous), and the
        # culprit is the divergence, not a wedged rank
        + ([] if mismatches else _find_wedged(streams, by_rank))
        + _find_stragglers(by_rank, straggler_ratio, straggler_min_samples)
    )
    return {
        "schema": SCHEMA,
        "ranks": sorted(by_rank),
        "records": {str(r): len(recs) for r, recs in sorted(by_rank.items())},
        "seqs": {
            str(r): (s[-1]["seq"] if s else 0) for r, s in sorted(streams.items())
        },
        "findings": findings,
    }


def diagnose(
    inputs: Iterable[str],
    *,
    hang_gap: int = DEFAULT_HANG_GAP,
    straggler_ratio: float = DEFAULT_STRAGGLER_RATIO,
    straggler_min_samples: int = DEFAULT_STRAGGLER_MIN_SAMPLES,
) -> Optional[Dict[str, Any]]:
    """Load + analyze; None when the inputs held no usable records."""
    by_rank = load(inputs)
    if not by_rank:
        return None
    return analyze(
        by_rank,
        hang_gap=hang_gap,
        straggler_ratio=straggler_ratio,
        straggler_min_samples=straggler_min_samples,
    )


# ---------------------------------------------------------------------
# static cross-reference (doctor --static)
# ---------------------------------------------------------------------


def collect_static_sites(
    target: str,
    *,
    arg_specs: Iterable[str] = (),
    axis_specs: Iterable[str] = (),
):
    """Lint ``target`` (``module:fn`` / ``file.py`` / a module with
    ``M4T_LINT_TARGETS``) and return its CollectiveSites. Imports jax —
    only reached through ``--static``."""
    from ..analysis import lint, lint_module
    from ..analysis.__main__ import (
        _import_target,
        _parse_arg_spec,
        parse_axis_env,
    )

    module, fn = _import_target(target)
    axis_env = parse_axis_env(axis_specs)
    if fn is not None:
        reports = [
            lint(
                fn,
                tuple(_parse_arg_spec(s) for s in arg_specs),
                axis_env=axis_env,
                name=target,
            )
        ]
    else:
        reports = lint_module(module)
    for r in reports:
        if r.error is not None:
            raise RuntimeError(f"--static {r.target}: {r.error}")
    return [s for r in reports for s in r.sites]


def collect_static_schedules(
    target: str,
    *,
    axis_specs: Iterable[str] = (),
    world: Optional[int] = None,
):
    """Enumerate the per-rank collective schedules of ``target``'s
    lint entry points (``analysis/schedule.py``), preferring a world
    size matching the observed run. Returns a list of provable
    ``ProgramSchedule``s (possibly empty). Imports jax — only reached
    through ``--static``."""
    from ..analysis import trace_schedule
    from ..analysis.__main__ import _import_target, parse_axis_env
    from ..analysis.linter import iter_module_targets

    module, fn = _import_target(target)
    axis_env = parse_axis_env(axis_specs)
    schedules = []
    if fn is not None:
        env = axis_env
        if env is None:
            env = {"ranks": world} if world else {"ranks": 8}
        try:
            schedules.append(trace_schedule(fn, (), axis_env=env))
        except Exception:
            pass
        return [s for s in schedules if s.provable]
    for _tname, t in iter_module_targets(module, world=world):
        try:
            schedules.append(
                trace_schedule(t.fn, t.args, axis_env=t.axis_env)
            )
        except Exception:
            continue
    return [s for s in schedules if s.provable]


def attach_schedule_positions(report: Dict[str, Any], schedules) -> int:
    """Join hang verdicts to the simulated schedule: a hung rank's
    ``last_seq`` is its position in its own enumerated schedule, so
    the doctor can cite the collective it *should* have emitted next —
    and what every peer expects next — without any peer log reaching
    that point. Mutates hang findings in place (``schedule_position``
    field); returns how many joins landed."""

    def describe(ev):
        return {
            "fingerprint": ev.fingerprint,
            "op": ev.op,
            "source": ev.source,
            "group": list(ev.group),
        }

    joined = 0
    seqs = {int(r): s for r, s in report.get("seqs", {}).items()}
    world = len(report.get("ranks", [])) or None
    # prefer a schedule enumerated at the observed world size
    candidates = sorted(
        schedules, key=lambda s: (s.world != world,)
    )
    for f in report.get("findings", []):
        if f.get("kind") != "hang":
            continue
        rank = f.get("rank")
        for sched in candidates:
            events = sched.events.get(rank)
            if events is None:
                continue
            pos = f.get("last_seq", 0)
            if pos >= len(events):
                continue
            peers = {}
            for peer, pseq in sorted(seqs.items()):
                pev = sched.events.get(peer)
                if peer != rank and pev is not None and pseq < len(pev):
                    peers[str(peer)] = describe(pev[pseq])
            f["schedule_position"] = {
                "world": sched.world,
                "position": pos,
                "expected_next": describe(events[pos]),
                "peers_next": peers,
            }
            joined += 1
            break
    return joined


def attach_static_sites(report: Dict[str, Any], sites) -> int:
    """Join runtime verdicts to static sites by fingerprint (the
    recorder schema both layers share; the p2p family is canonicalized
    so a runtime ``Sendrecv`` record matches a static
    ``CollectivePermute`` equation). Mutates mismatch groups and hang
    findings in place, adding ``static_sites`` lists; returns how many
    joins landed."""
    from ..analysis.sites import canonical_fingerprint

    by_fp: Dict[str, List[Any]] = defaultdict(list)
    for s in sites:
        by_fp[canonical_fingerprint(s.fingerprint)].append(s)

    def describe(s):
        return {
            "index": s.index,
            "source": s.source,
            "path": list(s.path),
            "fingerprint": s.fingerprint,
        }

    joined = 0
    for f in report.get("findings", []):
        if f.get("kind") == "mismatch":
            for group in f.get("groups", []):
                matches = by_fp.get(
                    canonical_fingerprint(group["fingerprint"]), []
                )
                group["static_sites"] = [describe(s) for s in matches]
                joined += len(matches)
        elif f.get("kind") == "hang" and f.get("stuck_before"):
            matches = by_fp.get(
                canonical_fingerprint(f["stuck_before"]), []
            )
            f["static_sites"] = [describe(s) for s in matches]
            joined += len(matches)
    return joined


def attach_link_classification(
    report: Dict[str, Any], topo: Dict[str, Any]
) -> int:
    """Join straggler verdicts to a measured topology map
    (``m4t-topo/1``, ``observability/topology.py``): is the straggling
    rank slow, or is one of its *links*? Each straggler finding gains
    a ``link_diagnosis`` — ``topology.classify_rank``'s verdict:
    ``link-bound`` (naming the slowest incident directed edge and its
    measured-vs-fleet-median beta) or ``rank-bound`` (its links look
    like everyone else's). Mutates findings in place; returns how many
    joins landed."""
    from . import topology

    joined = 0
    for f in report.get("findings", []):
        if f.get("kind") != "straggler" or f.get("rank") is None:
            continue
        diag = topology.classify_rank(topo, int(f["rank"]))
        if diag is None:
            continue
        f["link_diagnosis"] = diag
        joined += 1
    return joined


# ---------------------------------------------------------------------
# report formatting
# ---------------------------------------------------------------------


def _fmt_finding(f: Dict[str, Any]) -> str:
    kind = f["kind"]
    if kind == "mismatch":
        lines = [f"MISMATCH at seq {f['seq']}: ranks diverged"]
        for group in f["groups"]:
            ranks = ",".join(str(r) for r in group["ranks"])
            lines.append(f"  rank(s) {ranks}: {group['fingerprint']}")
            for site in group.get("static_sites", ()):
                where = "/".join(site["path"]) or "<root>"
                lines.append(
                    f"    declared at {site['source']} [{where}]"
                )
            if "static_sites" in group and not group["static_sites"]:
                lines.append(
                    "    (no static site with this fingerprint — "
                    "different shapes/axes at lint time?)"
                )
        return "\n".join(lines)
    if kind == "hang":
        head = {
            "hung": "HANG (alive but stuck)",
            "dead": "RANK DIED",
            "behind": "RANK BEHIND (hung or slow; no heartbeat to tell)",
        }[f["verdict"]]
        if f.get("wedged"):
            txt = (
                f"{head}: rank {f['rank']} recorded seq {f['last_seq']} "
                f"but never began executing it; rank(s) "
                f"{','.join(str(r) for r in f['front_ranks'])} entered "
                "the collective and are waiting on it"
            )
            if f.get("stuck_before"):
                txt += f"\n  stuck before: {f['stuck_before']}"
        else:
            txt = (
                f"{head}: rank {f['rank']} stopped at seq {f['last_seq']}, "
                f"{f['gap']} seq(s) behind rank(s) "
                f"{','.join(str(r) for r in f['front_ranks'])} (at seq {f['front_seq']})"
            )
            if f.get("stuck_before"):
                txt += f"\n  peers' next collective was: {f['stuck_before']}"
        for site in f.get("static_sites", ()):
            where = "/".join(site["path"]) or "<root>"
            txt += f"\n    declared at {site['source']} [{where}]"
        sp = f.get("schedule_position")
        if sp:
            nxt = sp["expected_next"]
            txt += (
                f"\n  simulated schedule (world {sp['world']}): rank "
                f"{f['rank']} should next emit [{sp['position']}] "
                f"{nxt['fingerprint']} declared at {nxt['source']}"
            )
            for peer, pev in sp.get("peers_next", {}).items():
                txt += (
                    f"\n    peer r{peer} expects next: "
                    f"{pev['fingerprint']} ({pev['source']})"
                )
        return txt
    if kind == "missing_rank":
        return (
            f"MISSING RANK: rank {f['rank']} of world {f['world']} "
            f"produced no log at all"
        )
    if kind == "straggler":
        txt = (
            f"STRAGGLER: rank {f['rank']} {f['op']} mean "
            f"{f['mean_s'] * 1e3:.2f}ms vs peer median "
            f"{f['peer_median_s'] * 1e3:.2f}ms "
            f"({f['ratio']:.1f}x, {f['samples']} samples)"
        )
        diag = f.get("link_diagnosis")
        if diag:
            if diag["klass"] == "link-bound":
                txt += (
                    f"\n  link-bound: edge {diag['slowest_edge']} "
                    f"measured {diag['slowest_edge_gbps']:.3g} GB/s vs "
                    f"fleet median {diag['fleet_median_gbps']:.3g} GB/s "
                    f"({diag['ratio']:.2f}x) — suspect the link, not "
                    "the rank"
                )
            else:
                txt += (
                    f"\n  rank-bound: slowest incident edge "
                    f"{diag['slowest_edge']} is healthy "
                    f"({diag['slowest_edge_gbps']:.3g} GB/s, "
                    f"{diag['ratio']:.2f}x fleet median) — suspect the "
                    "rank itself"
                )
        return txt
    return json.dumps(f)


def format_report(report: Dict[str, Any]) -> str:
    ranks = ",".join(str(r) for r in report["ranks"])
    seqs = ", ".join(f"r{r}:{s}" for r, s in report["seqs"].items())
    out = [
        f"doctor: {len(report['ranks'])} rank log(s) [{ranks}]; "
        f"last seq per rank: {seqs}"
    ]
    if not report["findings"]:
        out.append("no findings: ranks aligned, nobody behind, no stragglers")
    for f in report["findings"]:
        out.append(_fmt_finding(f))
    return "\n".join(out)


# ---------------------------------------------------------------------
# supervisor timeline (elastic recovery narration)
# ---------------------------------------------------------------------


def load_supervisor_audit(
    inputs: Iterable[str],
) -> List[Dict[str, Any]]:
    """``supervisor.jsonl`` records found beside the given inputs, or
    one level up — a doctor pointed at ``RUN/attempt01`` finds the
    audit log the supervisor writes at ``RUN/``. A per-attempt rank
    log can't explain a restart; the audit trail can."""
    seen: set = set()
    records: List[Dict[str, Any]] = []
    for item in inputs:
        d = item if os.path.isdir(item) else os.path.dirname(item)
        d = os.path.abspath(d)
        for cand in (d, os.path.dirname(d)):
            path = os.path.join(cand, "supervisor.jsonl")
            if path in seen:
                continue
            seen.add(path)
            if not os.path.exists(path):
                continue
            try:
                records.extend(
                    r for r in events.iter_records(path)
                    if r.get("kind") == "supervisor"
                )
            except OSError:
                continue
    return records


def format_supervisor_timeline(records: List[Dict[str, Any]]) -> str:
    """Narrate the supervisor's attempts — including elastic
    world-size transitions (old world → new world, the resharded
    checkpoint step) — so a run that was preempted, shrunk, resharded
    and resumed explains itself post-mortem."""
    out = [f"supervisor timeline ({len(records)} attempt(s)):"]
    for r in records:
        attempt = r.get("attempt", "?")
        world = r.get("world")
        line = f"  attempt {attempt}:"
        if world is not None:
            line += f" world {world},"
        line += (
            f" exit {r.get('exit_code')} -> {r.get('klass')}"
            f" ({r.get('reason')}), action {r.get('action')}"
        )
        pre = r.get("preempted_ranks")
        if pre:
            line += (
                f"; rank(s) {','.join(str(p) for p in pre)} preempted"
            )
        nxt = r.get("next_world")
        if nxt is not None:
            line += f"\n    ELASTIC: world {world} -> {nxt}"
            if r.get("resharded_from_step") is not None:
                line += (
                    f"; checkpoint step {r['resharded_from_step']} "
                    f"(world {r.get('resharded_from_world')}) "
                    f"resharded for {nxt} rank(s)"
                )
            else:
                line += "; no checkpoint carried over"
        if r.get("elastic_blocked"):
            line += f"\n    blocked: {r['elastic_blocked']}"
        if r.get("action") == "retry" and r.get("resume_step") is not None:
            line += f"; resume step {r['resume_step']}"
        out.append(line)
    return "\n".join(out)


# ---------------------------------------------------------------------
# serving timeline (queue-level narration)
# ---------------------------------------------------------------------


def load_serving_audit(
    inputs: Iterable[str],
) -> List[Dict[str, Any]]:
    """``serving.jsonl`` records found beside the given inputs or up
    to three levels up — a doctor pointed at a single job attempt
    (``SPOOL/jobs/<id>/attempt00``) finds the queue-level audit the
    serving supervisor writes at ``SPOOL/``. One rank log explains a
    crash; the serving audit explains what the *queue* did around it
    (admission, rejection, world shrink, drain)."""
    seen: set = set()
    records: List[Dict[str, Any]] = []
    for item in inputs:
        d = item if os.path.isdir(item) else os.path.dirname(item)
        d = os.path.abspath(d)
        cands = [d]
        for _ in range(3):
            cands.append(os.path.dirname(cands[-1]))
        for cand in cands:
            path = os.path.join(cand, "serving.jsonl")
            if path in seen:
                continue
            seen.add(path)
            if not os.path.exists(path):
                continue
            try:
                records.extend(
                    r for r in events.iter_records(path)
                    if r.get("kind") == "serving"
                )
            except OSError:
                continue
    return records


def format_serving_timeline(records: List[Dict[str, Any]]) -> str:
    """Narrate the serving plane's queue history: every submit /
    reject / admit / outcome, plus world-capacity transitions and the
    drain — so a spool that shed load at 2 a.m. and finished smaller
    explains itself in the morning."""
    out = [f"serving timeline ({len(records)} event(s)):"]
    for r in records:
        event = r.get("event", "?")
        job = r.get("job")
        tag = f" job {job}" if job else ""
        if event == "serve_start":
            line = (
                f"  serve start: world {r.get('world')}, queue "
                f"capacity {r.get('capacity')}"
                + (f" (server {r['server']})" if r.get("server")
                   else "")
                + (", elastic" if r.get("elastic") else "")
                + (", verify" if r.get("verify") else "")
            )
        elif event == "submitted":
            line = (
                f"  submitted:{tag} (tenant {r.get('tenant')}, "
                f"nproc {r.get('nproc')}, depth {r.get('depth')})"
            )
        elif event == "rejected":
            line = f"  REJECTED:{tag} — {r.get('reason')}"
            if r.get("reason") == "queue_full":
                line += (
                    f" (depth {r.get('depth')} >= capacity "
                    f"{r.get('capacity')})"
                )
        elif event == "admitted":
            line = (
                f"  admitted:{tag} at world {r.get('world')} after "
                f"{r.get('queue_wait_s', 0):.3g}s in queue"
            )
            if r.get("reclaims"):
                line += (
                    f" (reclaim #{r['reclaims']}"
                    + (f", resumed from step {r['resume_step']}"
                       if r.get("resume_step") is not None else "")
                    + ")"
                )
        elif event == "claimed":
            line = f"  claimed:{tag}"
            if r.get("server"):
                line += (
                    f" by server {r['server']} "
                    f"(epoch {r.get('epoch')})"
                )
        elif event == "server_register":
            line = (
                f"  server {r.get('server')} registered "
                f"(lease {r.get('lease_s')}s"
                + (f", world {r['world']}" if r.get("world") is not None
                   else "")
                + ")"
            )
        elif event == "server_stop":
            line = (
                f"  server {r.get('server')} stopped cleanly after "
                f"{r.get('jobs')} job(s)"
            )
        elif event == "lease_expired":
            line = (
                f"  FAILOVER: server {r.get('server')} presumed dead "
                f"— lease silent for "
                f"{r.get('lease_age_s', 0):.3g}s"
                + (f"; detected by {r['by']}" if r.get("by") else "")
            )
        elif event == "reclaim":
            if r.get("action") == "exhausted":
                line = (
                    f"  FAILOVER:{tag} reclaim cap reached after "
                    f"{r.get('reclaims')} reclaim(s) — terminal "
                    "failed: reclaim_exhausted"
                )
            else:
                line = (
                    f"  FAILOVER:{tag} reclaimed from server "
                    f"{r.get('from_server')} (claim epoch "
                    f"{r.get('epoch')}, {r.get('reason')})"
                    + (f" by {r['by']}" if r.get("by") else "")
                    + " — requeued with provenance"
                )
        elif event == "fenced":
            holder = r.get("holder") or {}
            line = (
                f"  FENCED:{tag} — zombie server {r.get('server')} "
                f"(stale claim epoch {r.get('epoch')}) tried to "
                f"write '{r.get('outcome_rejected')}'; rejected"
                + (f" (job now held by {holder.get('server')})"
                   if holder.get("server") else "")
            )
        elif event == "world":
            line = (
                f"  ELASTIC: world {r.get('world')} -> "
                f"{r.get('next_world')}"
            )
            pre = r.get("preempted_ranks")
            if pre:
                line += (
                    f"; rank(s) {','.join(str(p) for p in pre)} "
                    "preempted"
                )
            if r.get("resharded_from_step") is not None:
                line += (
                    f"; checkpoint step {r['resharded_from_step']} "
                    f"(world {r.get('resharded_from_world')}) "
                    "resharded"
                )
            if r.get("reason"):
                line += f" [{r['reason']}]"
        elif event in ("completed", "failed"):
            line = (
                f"  {event}:{tag} (world {r.get('world')}, "
                f"{r.get('attempts')} attempt(s)"
            )
            if event == "failed":
                line += f", {r.get('reason')}"
            line += ")"
        elif event == "drain_requested":
            line = "  drain requested: admission closed"
        elif event == "drained":
            line = (
                f"  drained: queue empty after {r.get('jobs')} "
                f"job(s) at world {r.get('world')}"
            )
        elif event == "pool_start":
            line = (
                f"  warm pool: {r.get('size')} resident worker(s)"
                + (", meshed" if r.get("mesh") else "")
                + f", heartbeat {r.get('heartbeat_s')}s / deadline "
                f"{r.get('deadline_s')}s"
            )
        elif event == "pool_quarantine":
            line = (
                f"  POOL: worker {r.get('worker')} quarantined — "
                f"{r.get('reason')}"
                + (f" (rc {r.get('rc')})" if r.get("rc") is not None
                   else "")
                + (f",{tag}" if job else "")
            )
        elif event == "pool_respawn":
            line = (
                f"  POOL: worker {r.get('worker')} respawned "
                f"(incarnation {r.get('incarnation')})"
            )
        elif event == "pool_retired":
            line = (
                f"  POOL: worker {r.get('worker')} preempted — slot "
                f"retired, capacity {r.get('capacity')}"
                + (f",{tag}" if job else "")
            )
        elif event == "pool_strike":
            line = (
                f"  POOL: strike {r.get('strikes')}/"
                f"{r.get('max_strikes')} against{tag} "
                f"({r.get('reason')})"
            )
        elif event == "pool_poisoned":
            line = (
                f"  POOL: POISONED{tag} after {r.get('strikes')} "
                "wedged attempt(s) — further dispatch refused"
            )
        elif event == "pool_hygiene":
            line = (
                f"  POOL: worker {r.get('worker')} failed the "
                f"post-job hygiene check after{tag}"
            )
        elif event == "pool_stop":
            line = (
                f"  warm pool stopped after {r.get('jobs')} work "
                f"item(s), {r.get('respawns')} respawn(s)"
            )
        else:
            line = f"  {event}:{tag}"
        out.append(line)
    return "\n".join(out)


def _print_slo_breaches(inputs: Iterable[str]) -> None:
    """Narrate SLO-breach verdicts (``SPOOL/slo.jsonl``, written by
    ``serving/slo.py``) found beside the inputs: each breached job is
    named with its dominant stage ("83% queue-wait -> capacity, not
    compute"). Best-effort, like every other narration section."""
    try:
        from ..serving import slo as _slo

        records = _slo.load_slo_verdicts(inputs)
        if records:
            print(_slo.format_slo_breaches(records))
    except Exception:
        pass


def _print_cp_profile(inputs: Iterable[str]) -> None:
    """Narrate the control-plane profile (``SPOOL/cp_profile.jsonl``,
    written when the server ran armed with ``M4T_CP_PROFILE=1``): each
    job's queue wait decomposed into named phases ("71% scan wait +
    18% submit fsync + 6% claim race lost"), the syscall budget, and
    the wasted-wakeup / claim-contention summary. Best-effort, like
    every other narration section."""
    try:
        from ..serving import profile as _cp

        for path in inputs:
            root = path if os.path.isdir(path) else os.path.dirname(path)
            if not _cp.profile_paths(root):
                continue
            report = _cp.profile_report(root)
            if report["records"]:
                print(_cp.format_cp_narration(report))
            return
    except Exception:
        pass


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.observability.doctor",
        description=(
            "Merge per-rank telemetry logs (event sinks, flight-recorder "
            "dumps) and diagnose cross-rank failures: collective "
            "mismatch, hung/behind/missing ranks, stragglers."
        ),
    )
    parser.add_argument(
        "inputs",
        nargs="+",
        help="per-rank .jsonl files and/or directories of them "
        "(e.g. the launcher's --events-dir)",
    )
    parser.add_argument(
        "--hang-gap",
        type=int,
        default=DEFAULT_HANG_GAP,
        metavar="K",
        help="report a rank as behind when it trails the front rank "
        "by >= K seqs (default %(default)s)",
    )
    parser.add_argument(
        "--straggler-ratio",
        type=float,
        default=DEFAULT_STRAGGLER_RATIO,
        metavar="R",
        help="report a rank as a straggler when its mean op latency "
        "exceeds the peer median by Rx (default %(default)s)",
    )
    parser.add_argument(
        "--straggler-min-samples",
        type=int,
        default=DEFAULT_STRAGGLER_MIN_SAMPLES,
        metavar="N",
        help="per-op latency samples a rank needs before straggler "
        "comparison considers it at all (default %(default)s; guards "
        "against single-sample noise)",
    )
    parser.add_argument(
        "--perf",
        action="store_true",
        help="append the perf attribution section: per-op achieved "
        "bandwidth and %%-of-peak from the same logs, via the "
        "analytic cost model (observability/perf.py); runs armed "
        "with step spans (launch --overlap) additionally get the "
        "exposed-communication section (observability/overlap.py)",
    )
    parser.add_argument(
        "--json", action="store_true", help="print the report as JSON"
    )
    parser.add_argument(
        "--static",
        metavar="TARGET",
        default=None,
        help="cross-reference verdicts against the static linter's "
        "collective sites for TARGET (module:fn, file.py, or a module "
        "with M4T_LINT_TARGETS): a MISMATCH fingerprint join names the "
        "offending source line",
    )
    parser.add_argument(
        "--static-arg",
        action="append",
        default=[],
        metavar="SPEC",
        help="abstract argument for a --static module:fn target "
        "(e.g. 'f32[64,128]'; repeatable, positional order)",
    )
    parser.add_argument(
        "--static-axis",
        action="append",
        default=[],
        metavar="NAME=SIZE",
        help="axis binding for the --static lint trace "
        "(default ranks=8; repeatable; 'none' lints with no bound "
        "axes — matches launcher-world/shm runtime fingerprints)",
    )
    parser.add_argument(
        "--trace",
        metavar="OUT.json",
        default=None,
        help="additionally export the merged logs as Chrome "
        "trace-event JSON (load in Perfetto / chrome://tracing)",
    )
    parser.add_argument(
        "--topo",
        metavar="TOPO.json",
        default=None,
        help="measured topology map (m4t-topo/1; launch "
        "--probe-topology) to classify stragglers as link-bound vs "
        "rank-bound; auto-detected from a topology.json beside the "
        "inputs when omitted",
    )
    args = parser.parse_args(argv)

    report = diagnose(
        args.inputs,
        hang_gap=args.hang_gap,
        straggler_ratio=args.straggler_ratio,
        straggler_min_samples=args.straggler_min_samples,
    )
    if report is None:
        # no per-rank telemetry — but the supervisor/serving audit
        # trails may still tell the story (a spool of jobs that never
        # armed telemetry, or a run whose sinks were swept)
        audit = load_supervisor_audit(args.inputs)
        serving = load_serving_audit(args.inputs)
        if not args.json and (audit or serving):
            if audit:
                print(format_supervisor_timeline(audit))
            if serving:
                print(format_serving_timeline(serving))
                _print_slo_breaches(args.inputs)
                _print_cp_profile(args.inputs)
            return 0
        print("doctor: no usable records in the given inputs", file=sys.stderr)
        return 2
    if args.static:
        try:
            sites = collect_static_sites(
                args.static,
                arg_specs=args.static_arg,
                axis_specs=args.static_axis,
            )
        except Exception as e:
            print(f"doctor: --static failed: {e}", file=sys.stderr)
            return 2
        joined = attach_static_sites(report, sites)
        print(
            f"# static: {len(sites)} site(s) from {args.static}, "
            f"{joined} fingerprint join(s)",
            file=sys.stderr,
        )
        # hang verdicts additionally cite the *simulated* schedule
        # position: the collective the hung rank should emit next and
        # what each peer expects next (analysis/schedule.py)
        try:
            schedules = collect_static_schedules(
                args.static,
                axis_specs=args.static_axis,
                world=len(report["ranks"]) or None,
            )
        except Exception as e:
            print(
                f"# static: schedule enumeration skipped: {e}",
                file=sys.stderr,
            )
            schedules = []
        if schedules:
            pos_joins = attach_schedule_positions(report, schedules)
            print(
                f"# static: {len(schedules)} simulated schedule(s), "
                f"{pos_joins} hang position join(s)",
                file=sys.stderr,
            )
    from . import topology

    topo = None
    if args.topo:
        try:
            topo = topology.load(args.topo)
        except (OSError, ValueError) as e:
            print(f"doctor: --topo failed: {e}", file=sys.stderr)
            return 2
    else:
        topo = topology.find(args.inputs)
    if topo is not None:
        link_joins = attach_link_classification(report, topo)
        if link_joins:
            print(
                f"# topology: {len(topo.get('edges') or {})} measured "
                f"edge(s), {link_joins} straggler link join(s)",
                file=sys.stderr,
            )
    if args.trace:
        from . import trace

        trace.export(args.inputs, args.trace)
        print(f"# trace written to {args.trace}", file=sys.stderr)
    if args.json:
        print(json.dumps(report, indent=1, default=str))
    else:
        print(format_report(report))
        audit = load_supervisor_audit(args.inputs)
        if audit:
            # the restart/elastic story around these artifacts: which
            # attempts failed, how they were classified, and any
            # world-size transitions (preemption -> shrink -> reshard)
            print(format_supervisor_timeline(audit))
        serving = load_serving_audit(args.inputs)
        if serving:
            # the queue-level story: admission, load shed, capacity
            # transitions, drain (mpi4jax_tpu/serving)
            print(format_serving_timeline(serving))
            _print_slo_breaches(args.inputs)
            _print_cp_profile(args.inputs)
    if args.perf:
        from . import perf

        by_rank = load(args.inputs)
        print()
        print(perf.format_table(perf.attribute(by_rank)))
        try:
            from . import overlap as _overlap

            orep = _overlap.build_report(by_rank)
            if orep["ranks"]:
                # exposed-communication section: only for armed runs
                # (streams carrying step spans), best-effort like the
                # rest of the perf tail
                print()
                print(_overlap.format_exposed(orep))
        except Exception:
            pass
    return 1 if report["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
