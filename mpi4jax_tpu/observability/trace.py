"""Export merged per-rank telemetry to Chrome trace-event JSON.

The output loads in Perfetto (ui.perfetto.dev) or ``chrome://tracing``
and turns the JSONL artifacts into the picture a human actually wants
of a multi-rank run:

- one **process track per rank** (``pid`` = rank, labeled ``rank N``),
- **duration slices** for every runtime latency sample (``ph: "X"`` —
  start reconstructed as ``t - seconds``), on the rank's "runtime"
  thread,
- **instant events** for every trace-time emission (``ph: "i"``) and
  heartbeat, so ranks with runtime sampling off still show their
  collective stream,
- a **counter track** (``ph: "C"``) of cumulative payload bytes per
  rank — the at-a-glance "who moved how much" view,
- an **achieved-bandwidth counter track** per rank: each latency
  sample that joins its emission (by cid, else seq) is divided into
  the cost model's expected wire bytes
  (``observability/costmodel.py``), so a degrading link shows up as
  a falling "achieved GB/s" curve right in the timeline.

Timestamps are microseconds relative to the earliest record across
all ranks, so unsynchronized-but-same-host ranks line up the way they
actually interleaved (cross-host clock skew shows up as track offset,
which is itself diagnostic).

Same inputs as the doctor: event-sink files, flight-recorder dumps,
or a directory of both (``launch --events-dir``).

CLI::

    python -m mpi4jax_tpu.observability.trace RUNDIR -o trace.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Iterable, List, Optional

from . import costmodel

#: trace-event "thread" ids within each rank's process track
TID_EMISSIONS = 0
TID_RUNTIME = 1
TID_HEARTBEAT = 2

_THREAD_NAMES = {
    TID_EMISSIONS: "collectives (trace-time)",
    TID_RUNTIME: "runtime",
    TID_HEARTBEAT: "heartbeat",
}


def _micros(t: float, t0: float) -> float:
    return round((t - t0) * 1e6, 1)


def build_trace(by_rank: Dict[int, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Build the Chrome trace-event object from rank-grouped records
    (the :func:`mpi4jax_tpu.observability.doctor.load` output)."""
    times = [
        rec["t"]
        for recs in by_rank.values()
        for rec in recs
        if isinstance(rec.get("t"), (int, float))
    ]
    t0 = min(times) if times else 0.0

    trace_events: List[Dict[str, Any]] = []
    for rank in sorted(by_rank):
        trace_events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": rank,
                "tid": 0,
                "args": {"name": f"rank {rank}"},
            }
        )
        for tid, tname in _THREAD_NAMES.items():
            trace_events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": rank,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )

        # latency -> emission join keys for the achieved-GB/s counter
        # (cid is exact; seq is the fallback for older latency logs)
        by_cid: Dict[str, Dict[str, Any]] = {}
        by_seq: Dict[Any, Dict[str, Any]] = {}
        for rec in by_rank[rank]:
            if rec.get("kind") in ("emission", "recorder"):
                if rec.get("cid"):
                    by_cid.setdefault(rec["cid"], rec)
                if rec.get("seq") is not None:
                    by_seq.setdefault(rec["seq"], rec)

        cumulative_bytes = 0
        for rec in by_rank[rank]:
            kind = rec.get("kind")
            t = rec.get("t")
            if not isinstance(t, (int, float)):
                continue
            if kind in ("emission", "recorder"):
                args = {
                    k: rec[k]
                    for k in ("seq", "cid", "bytes", "dtype", "world")
                    if rec.get(k) is not None
                }
                if rec.get("axes"):
                    args["axes"] = ",".join(str(a) for a in rec["axes"])
                trace_events.append(
                    {
                        "name": rec.get("op", "?"),
                        "ph": "i",
                        "s": "t",  # thread-scoped instant
                        "pid": rank,
                        "tid": TID_EMISSIONS,
                        "ts": _micros(t, t0),
                        "args": args,
                    }
                )
                cumulative_bytes += int(rec.get("bytes") or 0)
                trace_events.append(
                    {
                        "name": "payload bytes",
                        "ph": "C",
                        "pid": rank,
                        "ts": _micros(t, t0),
                        "args": {"cumulative": cumulative_bytes},
                    }
                )
            elif kind == "latency":
                seconds = rec.get("seconds")
                if not isinstance(seconds, (int, float)) or seconds < 0:
                    continue
                args = {
                    k: rec[k]
                    for k in ("seq", "cid")
                    if rec.get(k) is not None
                }
                trace_events.append(
                    {
                        "name": rec.get("op", "?"),
                        "ph": "X",
                        "pid": rank,
                        "tid": TID_RUNTIME,
                        "ts": _micros(t - seconds, t0),
                        "dur": round(seconds * 1e6, 1),
                        "args": args,
                    }
                )
                emission = by_cid.get(rec.get("cid") or "") or by_seq.get(
                    rec.get("seq")
                )
                if emission is not None and seconds > 0:
                    gbps = costmodel.achieved_gbps(
                        costmodel.record_cost(emission), seconds
                    )
                    if gbps is not None:
                        trace_events.append(
                            {
                                "name": "achieved GB/s",
                                "ph": "C",
                                "pid": rank,
                                "ts": _micros(t, t0),
                                "args": {"gbps": round(gbps, 6)},
                            }
                        )
            elif kind == "heartbeat":
                trace_events.append(
                    {
                        "name": "heartbeat",
                        "ph": "i",
                        "s": "t",
                        "pid": rank,
                        "tid": TID_HEARTBEAT,
                        "ts": _micros(t, t0),
                        "args": {
                            k: rec[k]
                            for k in ("source", "n")
                            if rec.get(k) is not None
                        },
                    }
                )
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "mpi4jax_tpu.observability.trace",
            "ranks": sorted(by_rank),
        },
    }


def export(
    inputs: Iterable[str], out_path: str
) -> Optional[Dict[str, Any]]:
    """Load rank logs (files/dirs) and write the trace JSON; returns
    the trace object, or None when the inputs held no records."""
    from . import doctor

    by_rank = doctor.load(inputs)
    if not by_rank:
        return None
    obj = build_trace(by_rank)
    with open(out_path, "w") as f:
        json.dump(obj, f, sort_keys=True)
    return obj


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.observability.trace",
        description="Export merged per-rank telemetry logs to Chrome "
        "trace-event JSON (Perfetto-loadable).",
    )
    parser.add_argument(
        "inputs", nargs="+", help="per-rank .jsonl files or directories"
    )
    parser.add_argument(
        "-o", "--output", required=True, metavar="OUT.json",
        help="trace file to write",
    )
    args = parser.parse_args(argv)
    obj = export(args.inputs, args.output)
    if obj is None:
        print("trace: no usable records in the given inputs", file=sys.stderr)
        return 2
    print(
        f"# {len(obj['traceEvents'])} trace events from "
        f"{len(obj['otherData']['ranks'])} rank(s) -> {args.output}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
