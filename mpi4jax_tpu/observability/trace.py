"""Export merged telemetry to Chrome trace-event JSON (Perfetto).

Two export shapes share one rendering core:

**Single run** (the PR 2 layout, ``launch --events-dir``): one
process track per rank (``pid`` = rank, labeled ``rank N``), with

- **duration slices** for every runtime latency sample (``ph: "X"`` —
  start reconstructed as ``t - seconds``), on the rank's "runtime"
  thread,
- **instant events** for every trace-time emission (``ph: "i"``) and
  heartbeat, so ranks with runtime sampling off still show their
  collective stream,
- a **counter track** (``ph: "C"``) of cumulative payload bytes per
  rank — the at-a-glance "who moved how much" view,
- an **achieved-bandwidth counter track** per rank: each latency
  sample that joins its emission (by cid, else seq) is divided into
  the cost model's expected wire bytes
  (``observability/costmodel.py``), so a degrading link shows up as
  a falling "achieved GB/s" curve right in the timeline,
- a **per-link counter track** (a dedicated "links" process): the
  same cid joins decomposed onto the directed edges the collective's
  algorithm rides (``costmodel.record_edge_phases`` — the topology
  observatory's attribution math), one "link src->dst GB/s" counter
  per measured edge, so *which link* degraded is visible without
  leaving the timeline,
- **occupancy tracks** (armed runs only — streams carrying the
  overlap observatory's ``step``/``compute`` span records,
  ``launch --overlap``): each step is a slice on the rank's "steps"
  thread and its exact compute/comm decomposition a stacked
  "occupancy (s)" counter (compute-only / overlapped / exposed /
  idle seconds per step).

**Merged serving trace** (``--serve SPOOL``): one Perfetto file for a
whole spool of jobs. Every job gets its *own* process group — a
lifecycle track carrying its span chain (``observability/spans.py``:
``queued -> verify -> dispatch -> run -> result`` plus
``attempt<k>``/``spawn``/``warm_dispatch``/``reshard`` children) and
one track per rank with that job's collective slices, joined by the
trace id minted at submit (``m4t-job/1`` ``trace`` field; warm-pool
worker sinks interleave many jobs, so only trace-stamped records are
attributed). Tracks are keyed by **(job, rank)** — two jobs' rank-0
streams can never land on one track — and carry
``process_sort_index`` metadata ordering the file tenant-by-tenant,
job-by-job, so Perfetto renders per-tenant groups with each job's
per-rank activity nested under its ``run`` span. When the spool was
served armed (``M4T_CP_PROFILE=1``, ``serving/profile.py``), each
serving loop / pool worker / the submit side additionally gets a
``controlplane · <id>`` process track of its micro-spans (fsyncs,
renames, dir scans, scheduler picks, poll wakeups), so "where did the
queue wait go" is answerable on the same timeline as the job spans.

Timestamps are microseconds relative to the earliest record across
all inputs, so unsynchronized-but-same-host processes line up the way
they actually interleaved (cross-host clock skew shows up as track
offset, which is itself diagnostic).

CLI::

    python -m mpi4jax_tpu.observability.trace RUNDIR -o trace.json
    python -m mpi4jax_tpu.observability.trace --serve SPOOL -o out.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Iterable, List, Optional

from . import costmodel

#: trace-event "thread" ids within each rank's process track
TID_EMISSIONS = 0
TID_RUNTIME = 1
TID_HEARTBEAT = 2
#: step spans (overlap observatory; the thread_name meta is emitted
#: only when a rank actually has step records, so unarmed exports —
#: and the committed goldens — are byte-identical)
TID_STEPS = 3

_THREAD_NAMES = {
    TID_EMISSIONS: "collectives (trace-time)",
    TID_RUNTIME: "runtime",
    TID_HEARTBEAT: "heartbeat",
}

#: thread ids within a job's lifecycle process track
TID_LIFECYCLE = 0
TID_ATTEMPTS = 1

#: pids in a merged serving trace: job ``i`` owns the contiguous block
#: ``[i * JOB_PID_STRIDE, (i+1) * JOB_PID_STRIDE)`` — lifecycle track
#: first, then one pid per rank — so (job, rank) can never collide
JOB_PID_STRIDE = 100


def _micros(t: float, t0: float) -> float:
    return round((t - t0) * 1e6, 1)


def _process_meta(
    events: List[Dict[str, Any]],
    pid: int,
    name: str,
    sort_index: int,
    thread_names: Dict[int, str],
) -> None:
    events.append(
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": name},
        }
    )
    events.append(
        {
            "name": "process_sort_index",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"sort_index": sort_index},
        }
    )
    for tid, tname in thread_names.items():
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": tname},
            }
        )


def _rank_events(
    trace_events: List[Dict[str, Any]],
    records: List[Dict[str, Any]],
    *,
    pid: int,
    t0: float,
) -> None:
    """Render one rank's records (emissions, latency samples,
    heartbeats, payload + achieved-GB/s counters) onto process
    ``pid``. Shared by the single-run and merged-serving exports."""
    # latency -> emission join keys for the achieved-GB/s counter
    # (cid is exact; seq is the fallback for older latency logs)
    by_cid: Dict[str, Dict[str, Any]] = {}
    by_seq: Dict[Any, Dict[str, Any]] = {}
    for rec in records:
        if rec.get("kind") in ("emission", "recorder"):
            if rec.get("cid"):
                by_cid.setdefault(rec["cid"], rec)
            if rec.get("seq") is not None:
                by_seq.setdefault(rec["seq"], rec)

    cumulative_bytes = 0
    for rec in records:
        kind = rec.get("kind")
        t = rec.get("t")
        if not isinstance(t, (int, float)):
            continue
        if kind in ("emission", "recorder"):
            args = {
                k: rec[k]
                for k in ("seq", "cid", "bytes", "dtype", "world",
                          "trace", "job")
                if rec.get(k) is not None
            }
            if rec.get("axes"):
                args["axes"] = ",".join(str(a) for a in rec["axes"])
            trace_events.append(
                {
                    "name": rec.get("op", "?"),
                    "ph": "i",
                    "s": "t",  # thread-scoped instant
                    "pid": pid,
                    "tid": TID_EMISSIONS,
                    "ts": _micros(t, t0),
                    "args": args,
                }
            )
            cumulative_bytes += int(rec.get("bytes") or 0)
            trace_events.append(
                {
                    "name": "payload bytes",
                    "ph": "C",
                    "pid": pid,
                    "ts": _micros(t, t0),
                    "args": {"cumulative": cumulative_bytes},
                }
            )
        elif kind == "latency":
            seconds = rec.get("seconds")
            if not isinstance(seconds, (int, float)) or seconds < 0:
                continue
            args = {
                k: rec[k]
                for k in ("seq", "cid", "trace", "job")
                if rec.get(k) is not None
            }
            trace_events.append(
                {
                    "name": rec.get("op", "?"),
                    "ph": "X",
                    "pid": pid,
                    "tid": TID_RUNTIME,
                    "ts": _micros(t - seconds, t0),
                    "dur": round(seconds * 1e6, 1),
                    "args": args,
                }
            )
            emission = by_cid.get(rec.get("cid") or "") or by_seq.get(
                rec.get("seq")
            )
            if emission is not None and seconds > 0:
                gbps = costmodel.achieved_gbps(
                    costmodel.record_cost(emission), seconds
                )
                if gbps is not None:
                    trace_events.append(
                        {
                            "name": "achieved GB/s",
                            "ph": "C",
                            "pid": pid,
                            "ts": _micros(t, t0),
                            "args": {"gbps": round(gbps, 6)},
                        }
                    )
        elif kind == "heartbeat":
            trace_events.append(
                {
                    "name": "heartbeat",
                    "ph": "i",
                    "s": "t",
                    "pid": pid,
                    "tid": TID_HEARTBEAT,
                    "ts": _micros(t, t0),
                    "args": {
                        k: rec[k]
                        for k in ("source", "n", "job")
                        if rec.get(k) is not None
                    },
                }
            )


def _occupancy_events(
    trace_events: List[Dict[str, Any]],
    records: List[Dict[str, Any]],
    *,
    pid: int,
    t0: float,
) -> None:
    """Overlap-observatory tracks for one rank (armed runs only — a
    stream without ``step`` records emits nothing, which keeps the
    committed goldens byte-identical): each step span becomes a slice
    on the "steps" thread, and its exact interval-algebra decomposition
    (``overlap.decompose``) becomes a stacked "occupancy (s)" counter —
    compute-only / overlapped / exposed / idle seconds per step, so a
    step whose communication fell out from behind compute shows as a
    rising "comm_exposed" band right in the timeline."""
    from . import overlap

    steps = overlap.span_records(records, "step")
    if not steps:
        return
    trace_events.append(
        {
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": TID_STEPS,
            "args": {"name": "steps"},
        }
    )
    compute = overlap.merge(
        (r["t0"], r["t1"]) for r in overlap.span_records(records, "compute")
    )
    comm = overlap.merge(iv for iv, _rec in overlap.comm_samples(records))
    for rec in steps:
        d = overlap.decompose(rec["t0"], rec["t1"], compute, comm)
        args: Dict[str, Any] = {
            k: d[f"{k}_s"]
            for k in ("compute_only", "comm_overlapped", "comm_exposed",
                      "idle")
        }
        ratio = overlap.occupancy_ratio(d)
        if rec.get("step") is not None:
            args["step"] = rec["step"]
        if ratio is not None:
            args["overlap_ratio"] = round(ratio, 6)
        trace_events.append(
            {
                "name": f"step {rec.get('step', '?')}",
                "ph": "X",
                "pid": pid,
                "tid": TID_STEPS,
                "ts": _micros(rec["t0"], t0),
                "dur": round((rec["t1"] - rec["t0"]) * 1e6, 1),
                "args": args,
            }
        )
        trace_events.append(
            {
                "name": "occupancy (s)",
                "ph": "C",
                "pid": pid,
                "ts": _micros(rec["t0"], t0),
                "args": {
                    k: round(d[f"{k}_s"], 6)
                    for k in ("compute_only", "comm_overlapped",
                              "comm_exposed", "idle")
                },
            }
        )


def _link_counter_events(
    trace_events: List[Dict[str, Any]],
    by_rank: Dict[int, List[Dict[str, Any]]],
    *,
    pid: int,
    t0: float,
) -> bool:
    """Per-link achieved-GB/s counters: each latency sample that joins
    its emission by cid is decomposed onto the directed edges the
    collective's algorithm rides (``costmodel.record_edge_phases``);
    the recording rank's outgoing-edge bytes over the measured seconds
    is that link's achieved GB/s at that instant. One counter series
    per edge on a dedicated "links" process, so the per-rank tracks
    stay clean. Returns whether anything was emitted (the caller only
    then labels the process)."""
    emitted = False
    for rank in sorted(by_rank):
        by_cid: Dict[str, Dict[str, Any]] = {}
        for rec in by_rank[rank]:
            if rec.get("kind") in ("emission", "recorder") and rec.get("cid"):
                by_cid.setdefault(rec["cid"], rec)
        for rec in by_rank[rank]:
            if rec.get("kind") != "latency":
                continue
            seconds = rec.get("seconds")
            t = rec.get("t")
            if not isinstance(seconds, (int, float)) or seconds <= 0:
                continue
            if not isinstance(t, (int, float)):
                continue
            emission = by_cid.get(rec.get("cid") or "")
            if emission is None:
                continue
            outgoing: Dict[Any, int] = {}
            for phase in costmodel.record_edge_phases(emission):
                for src, dst in phase["edges"]:
                    if src == rank:
                        outgoing[(src, dst)] = (
                            outgoing.get((src, dst), 0)
                            + int(phase["per_edge_bytes"])
                        )
            for (src, dst), nbytes in sorted(outgoing.items()):
                if nbytes <= 0:
                    continue
                trace_events.append(
                    {
                        "name": f"link {src}->{dst} GB/s",
                        "ph": "C",
                        "pid": pid,
                        "ts": _micros(t, t0),
                        "args": {
                            "gbps": round(nbytes / seconds / 1e9, 6)
                        },
                    }
                )
                emitted = True
    return emitted


def build_trace(by_rank: Dict[int, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Build the single-run Chrome trace-event object from
    rank-grouped records (the
    :func:`mpi4jax_tpu.observability.doctor.load` output)."""
    times = [
        rec["t"]
        for recs in by_rank.values()
        for rec in recs
        if isinstance(rec.get("t"), (int, float))
    ]
    t0 = min(times) if times else 0.0

    trace_events: List[Dict[str, Any]] = []
    for rank in sorted(by_rank):
        _process_meta(
            trace_events, rank, f"rank {rank}", rank, _THREAD_NAMES
        )
        _rank_events(trace_events, by_rank[rank], pid=rank, t0=t0)
        _occupancy_events(trace_events, by_rank[rank], pid=rank, t0=t0)
    links_pid = (max(by_rank) + 1) if by_rank else 0
    link_events: List[Dict[str, Any]] = []
    if _link_counter_events(link_events, by_rank, pid=links_pid, t0=t0):
        _process_meta(trace_events, links_pid, "links", links_pid, {})
        trace_events.extend(link_events)
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "mpi4jax_tpu.observability.trace",
            "ranks": sorted(by_rank),
        },
    }


# ---------------------------------------------------------------------
# merged serving trace (--serve SPOOL)
# ---------------------------------------------------------------------


def load_serve(spool_root: str) -> Dict[str, Any]:
    """Collect one spool's jobs for :func:`build_serve_trace`: spans
    and tenant identity from ``serving.jsonl``, each job's per-rank
    records from its attempt dirs and the (trace-filtered) warm-pool
    sinks (``spans.collect_job_records``)."""
    import os

    from . import events as _events
    from . import spans as _spans

    spool_root = os.path.abspath(spool_root)
    audit_path = os.path.join(spool_root, "serving.jsonl")
    records = list(_events.iter_records(audit_path))
    spans_by_job = _spans.chains(records)
    tenants: Dict[str, str] = {}
    order: Dict[str, float] = {}
    for rec in records:
        if rec.get("kind") == "serving" and rec.get("job"):
            job = str(rec["job"])
            if rec.get("tenant"):
                tenants.setdefault(job, str(rec["tenant"]))
    for job, spans in spans_by_job.items():
        order[job] = min(
            (float(s.get("t0") or 0.0) for s in spans), default=0.0
        )
    jobs = []
    for job in sorted(
        spans_by_job,
        key=lambda j: (tenants.get(j, "default"), order.get(j, 0.0), j),
    ):
        spans = spans_by_job[job]
        trace_id = next(
            (s.get("trace") for s in spans if s.get("trace")), None
        )
        jobs.append({
            "id": job,
            "tenant": tenants.get(job, "default"),
            "trace": trace_id,
            "spans": spans,
            "by_rank": _spans.collect_job_records(
                spool_root, job, trace_id
            ),
        })
    from ..serving import profile as _cp_profile

    return {"jobs": jobs, "cp": _cp_profile.load_cp(spool_root)}


def _cp_track_key(rec: Dict[str, Any]) -> str:
    """Which control-plane track a cp micro-span renders on: the
    serving loop that recorded it, a pool worker's mailbox plane, or
    the submit side (client-process records carry neither id)."""
    if rec.get("server"):
        return f"server {rec['server']}"
    if rec.get("worker") is not None:
        return f"pool worker {rec['worker']}"
    return "submit"


def build_serve_trace(serve_data: Dict[str, Any]) -> Dict[str, Any]:
    """Render the multi-job, multi-plane trace: per-tenant process
    groups, one lifecycle track per job, and the job's per-rank
    collective slices keyed by (job, rank)."""
    from . import spans as _spans

    jobs = serve_data.get("jobs") or []
    cp_records = serve_data.get("cp") or []
    times: List[float] = []
    for job in jobs:
        for span in job.get("spans") or []:
            for key in ("t0", "t1"):
                if isinstance(span.get(key), (int, float)):
                    times.append(float(span[key]))
        for recs in (job.get("by_rank") or {}).values():
            times.extend(
                float(r["t"]) for r in recs
                if isinstance(r.get("t"), (int, float))
            )
    for rec in cp_records:
        t = rec.get("t")
        if isinstance(t, (int, float)):
            times.append(float(t) - float(rec.get("dur_s") or 0.0))
    t0 = min(times) if times else 0.0

    trace_events: List[Dict[str, Any]] = []
    for i, job in enumerate(jobs):
        base = i * JOB_PID_STRIDE
        label = f"{job.get('tenant', 'default')}/{job.get('id')}"
        _process_meta(
            trace_events, base, f"{label} · lifecycle", base,
            {TID_LIFECYCLE: "lifecycle", TID_ATTEMPTS: "attempts"},
        )
        for span in job.get("spans") or []:
            s0, s1 = span.get("t0"), span.get("t1")
            if not isinstance(s0, (int, float)) or not isinstance(
                s1, (int, float)
            ):
                continue
            args = {
                k: span[k]
                for k in ("trace", "attempt", "exit_code", "outcome",
                          "reason", "world", "workers", "passed",
                          "resume_step", "from_world", "to_world")
                if span.get(k) is not None
            }
            trace_events.append(
                {
                    "name": span.get("span", "?"),
                    "ph": "X",
                    "pid": base,
                    "tid": (
                        TID_ATTEMPTS
                        if _spans.is_child(span.get("span", ""))
                        else TID_LIFECYCLE
                    ),
                    "ts": _micros(float(s0), t0),
                    "dur": round(max(0.0, float(s1) - float(s0)) * 1e6, 1),
                    "args": args,
                }
            )
        by_rank = job.get("by_rank") or {}
        for rank in sorted(by_rank):
            # the (job, rank) key: pid is unique per job AND per rank,
            # so two jobs' rank-0 streams render on separate tracks
            pid = base + 1 + int(rank)
            _process_meta(
                trace_events, pid, f"{label} · rank {rank}", pid,
                _THREAD_NAMES,
            )
            _rank_events(trace_events, by_rank[rank], pid=pid, t0=t0)

    # control-plane tracks (M4T_CP_PROFILE micro-spans): one process
    # per serving loop / pool worker / the submit side, rendered after
    # the job blocks so the data plane stays on top
    cp_by_track: Dict[str, List[Dict[str, Any]]] = {}
    for rec in cp_records:
        if isinstance(rec.get("t"), (int, float)):
            cp_by_track.setdefault(_cp_track_key(rec), []).append(rec)
    cp_base = len(jobs) * JOB_PID_STRIDE
    cp_tracks: List[Dict[str, Any]] = []
    for i, track in enumerate(sorted(cp_by_track)):
        pid = cp_base + i
        _process_meta(
            trace_events, pid, f"controlplane · {track}", pid,
            {0: "micro-spans"},
        )
        cp_tracks.append({"track": track, "pid": pid,
                          "records": len(cp_by_track[track])})
        for rec in cp_by_track[track]:
            dur = max(0.0, float(rec.get("dur_s") or 0.0))
            args = {
                k: rec[k]
                for k in ("job", "tenant", "server", "worker", "item",
                          "useful", "picked", "depth", "n", "epoch",
                          "outcome", "items", "actions", "by")
                if rec.get(k) is not None
            }
            trace_events.append(
                {
                    "name": rec.get("phase", "?"),
                    "ph": "X",
                    "pid": pid,
                    "tid": 0,
                    "ts": _micros(float(rec["t"]) - dur, t0),
                    "dur": round(dur * 1e6, 1),
                    "args": args,
                }
            )
    other: Dict[str, Any] = {
        "producer": "mpi4jax_tpu.observability.trace",
        "jobs": [
            {
                "job": job.get("id"),
                "tenant": job.get("tenant"),
                "trace": job.get("trace"),
                "pid": i * JOB_PID_STRIDE,
                "ranks": sorted(job.get("by_rank") or {}),
            }
            for i, job in enumerate(jobs)
        ],
    }
    if cp_tracks:
        # armed-only key: an unarmed spool's export stays byte-identical
        # to the PR 12 golden (tests/data/serve_trace_golden.json)
        other["controlplane"] = cp_tracks
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def export(
    inputs: Iterable[str], out_path: str
) -> Optional[Dict[str, Any]]:
    """Load rank logs (files/dirs) and write the trace JSON; returns
    the trace object, or None when the inputs held no records."""
    from . import doctor

    by_rank = doctor.load(inputs)
    if not by_rank:
        return None
    obj = build_trace(by_rank)
    with open(out_path, "w") as f:
        json.dump(obj, f, sort_keys=True)
    return obj


def export_serve(
    spool_root: str, out_path: str
) -> Optional[Dict[str, Any]]:
    """Merge one spool's spans + per-job telemetry into a single
    Perfetto file; None when the spool holds no spans."""
    serve_data = load_serve(spool_root)
    if not serve_data["jobs"]:
        return None
    obj = build_serve_trace(serve_data)
    with open(out_path, "w") as f:
        json.dump(obj, f, sort_keys=True)
    return obj


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.observability.trace",
        description="Export merged per-rank telemetry logs to Chrome "
        "trace-event JSON (Perfetto-loadable).",
    )
    parser.add_argument(
        "inputs", nargs="*",
        help="per-rank .jsonl files or directories",
    )
    parser.add_argument(
        "--serve", metavar="SPOOL", default=None,
        help="merged serving trace: render every job in the spool as "
        "its own process group (lifecycle spans + per-rank collective "
        "slices joined by trace id) instead of a single-run export",
    )
    parser.add_argument(
        "-o", "--output", required=True, metavar="OUT.json",
        help="trace file to write",
    )
    args = parser.parse_args(argv)
    if args.serve:
        if args.inputs:
            parser.error("--serve takes the spool root, not inputs")
        obj = export_serve(args.serve, args.output)
        if obj is None:
            print(
                f"trace: no span records in {args.serve} (is it a "
                "spool root with serving.jsonl?)",
                file=sys.stderr,
            )
            return 2
        meta = obj["otherData"]["jobs"]
        print(
            f"# {len(obj['traceEvents'])} trace events from "
            f"{len(meta)} job(s) -> {args.output}",
            file=sys.stderr,
        )
        return 0
    if not args.inputs:
        parser.error("inputs required (or use --serve SPOOL)")
    obj = export(args.inputs, args.output)
    if obj is None:
        print("trace: no usable records in the given inputs", file=sys.stderr)
        return 2
    print(
        f"# {len(obj['traceEvents'])} trace events from "
        f"{len(obj['otherData']['ranks'])} rank(s) -> {args.output}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
