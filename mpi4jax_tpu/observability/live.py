"""Live telemetry plane: tail per-rank sinks while the run is alive.

Everything the offline stack (doctor / perf / trace) knows, it learns
from the per-rank fsync'd JSONL artifacts — *after* the world is dead.
This module reads the same artifacts **while they are being written**:

- :class:`TailReader` — torn-line-safe incremental reader for one
  JSONL sink. Bytes after the last newline are never parsed (a rank
  may be mid-``write``); they are picked up — exactly once — on the
  poll after the line completes. Rotated segments
  (``events.EventLog(max_bytes=...)``: ``.1``/``.2`` suffixes) are
  drained across the rename, so a capped sink still reads as one
  continuous stream.
- :class:`LiveAggregator` — discovers the per-rank sinks in a run
  directory (the ``launch --events-dir`` layout), polls every reader,
  and maintains rolling state: per-rank last seq / heartbeat age /
  emission age, cross-rank seq skew, per-(op, impl, plan-key)
  emission + byte counters and windowed throughput, and the full
  per-rank record lists in the exact shape ``doctor.load`` produces —
  so the streaming doctor (:mod:`.stream_doctor`) reuses the offline
  analyses verbatim and its verdicts agree with the post-mortem ones
  by construction.
- :class:`LiveMonitor` — the launcher-side daemon thread: poll, run
  the streaming doctor, refresh the OpenMetrics snapshot
  (:mod:`.export`), optionally serve it over localhost HTTP and
  print a one-line dashboard; expose a confirmed hang/mismatch as an
  *escalation* the launcher acts on before its blunt
  ``--hang-timeout`` would.

File-tail only, no network between ranks and monitor — the whole
plane is device-free-testable (``python -m
mpi4jax_tpu.observability.live --selftest``) and works post-mortem
too: pointed at a finished run directory it renders the final state.

CLI::

    python -m mpi4jax_tpu.observability.live RUNDIR          # snapshot
    python -m mpi4jax_tpu.observability.live RUNDIR --follow # dashboard
    python -m mpi4jax_tpu.observability.live RUNDIR --json
    python -m mpi4jax_tpu.observability.live --selftest
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import config

#: sink basenames in a run directory that are *about* the run rather
#: than from a rank: never tailed into the per-rank record state
NON_RANK_SINKS = frozenset({"live.jsonl", "supervisor.jsonl"})

#: throughput window for the rolling rates (seconds)
DEFAULT_WINDOW_S = 30.0

#: rolling per-rank interval buffers for the overlap view (step spans,
#: compute spans, comm intervals): enough for a long tail view, bounded
#: so an unbounded run cannot grow the aggregator without limit
OVERLAP_SPANS = 512


# ---------------------------------------------------------------------
# torn-line-safe file tailing
# ---------------------------------------------------------------------


class TailReader:
    """Incremental JSONL reader for one (possibly rotating) sink.

    ``poll()`` returns the records of every line *completed* since the
    last poll. The invariants the streaming doctor depends on:

    - a torn final line (no trailing newline yet) is never parsed; the
      read offset stays at the last newline, so the line is consumed
      exactly once, on the poll after the writer finishes it;
    - rotation (``EventLog`` renames ``path`` to ``path.1``, ``.1`` to
      ``.2``) never loses or duplicates a record that is still on
      disk: per-generation read offsets are keyed by *inode* (renames
      preserve it), and every poll walks the segment chain oldest
      first — a generation read halfway as the live file is resumed
      from the same offset at its rotated name. Only data rotated
      past ``.2`` *and deleted* between two polls is gone, which is
      the writer's retention decision, not a reader bug;
    - a missing file is not an error (the rank may not have started
      yet) — ``poll()`` just returns nothing.
    """

    #: generation-identity prefix length: rotation recycles inodes
    #: (the unlinked ``.2``'s inode often becomes the next live file),
    #: so a generation is (inode, first bytes), not inode alone
    HEAD_LEN = 64

    def __init__(self, path: str):
        self.path = os.fspath(path)
        #: inode -> (first bytes seen, bytes consumed) per generation
        self._gens: Dict[int, Tuple[bytes, int]] = {}

    def _parse(self, data: bytes) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for line in data.split(b"\n"):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            if isinstance(rec, dict):
                out.append(rec)
        return out

    def poll(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        new_gens: Dict[int, Tuple[bytes, int]] = {}
        for p in (self.path + ".2", self.path + ".1", self.path):
            is_live = p == self.path
            try:
                f = open(p, "rb")
            except OSError:
                continue
            with f:
                # fstat the open fd, not the path: the identity and
                # the bytes we read are then of the *same* file even
                # if the writer rotates mid-poll
                ino = os.fstat(f.fileno()).st_ino
                head = f.read(self.HEAD_LEN)
                stored_head, offset = self._gens.get(ino, (b"", 0))
                if stored_head and not head.startswith(stored_head):
                    offset = 0  # recycled inode: a brand-new generation
                if f.seek(0, os.SEEK_END) < offset:
                    offset = 0  # truncated in place: start over
                f.seek(offset)
                data = f.read()
            if is_live:
                # only the live file can end in a torn line; rotated
                # segments are complete by construction
                cut = data.rfind(b"\n")
                data = data[: cut + 1] if cut >= 0 else b""
            out.extend(self._parse(data))
            # `head` is always the current file's first bytes: right
            # for a new generation, and a superset of the stored
            # prefix for a growing one
            new_gens[ino] = (head, offset + len(data))
        # generations no longer on disk drop out of the state map
        self._gens = new_gens
        return out


class HeartbeatTail:
    """Bounded-memory liveness tracker over one sink.

    A :class:`TailReader` that keeps only *timestamps*, not records —
    the tail a process that lives for hours can afford to run against
    a sink that grows for hours. The serving plane's resident worker
    pool (``serving/pool.py``) runs one per worker: the pool doctor's
    quarantine deadline is "no fresh heartbeat for N seconds", and
    freshness here is **arrival time** (when this poll first saw the
    completed line), so a respawned worker appending to the same sink
    can never look alive on its dead predecessor's heartbeats —
    arrival times only move forward.
    """

    def __init__(self, path: str, *, clock: Callable[[], float] = time.monotonic):
        self.reader = TailReader(path)
        self.clock = clock
        #: arrival time (clock) of the newest heartbeat / any record
        self.last_heartbeat_t: Optional[float] = None
        self.last_record_t: Optional[float] = None
        self.records = 0

    def poll(self) -> int:
        """Drain the sink once; returns how many new records arrived."""
        recs = self.reader.poll()
        if not recs:
            return 0
        now = self.clock()
        self.records += len(recs)
        self.last_record_t = now
        if any(r.get("kind") == "heartbeat" for r in recs):
            self.last_heartbeat_t = now
        return len(recs)

    def heartbeat_age(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds since a heartbeat last *arrived* (None before any)."""
        if self.last_heartbeat_t is None:
            return None
        return max(0.0, (self.clock() if now is None else now) - self.last_heartbeat_t)


# ---------------------------------------------------------------------
# run-directory aggregation
# ---------------------------------------------------------------------


def _rank_of(record: Dict[str, Any], path: str) -> Optional[int]:
    from . import doctor

    return doctor._rank_of(record, path)


class LiveAggregator:
    """Rolling cross-rank state over a run directory's sinks.

    ``by_rank`` accumulates the raw records per rank — byte-compatible
    with ``doctor.load`` output, the contract that lets the streaming
    doctor call the offline analyses unchanged. On top of it, cheap
    incremental state the dashboard/exporter read without re-scanning:
    per-rank seq / liveness, per-(op, impl, plan-key) totals, and a
    windowed byte-rate.

    ``clock`` is injectable (monotonic seconds) so stall timing is
    testable without sleeping.
    """

    def __init__(
        self,
        rundir: str,
        *,
        platform: Optional[str] = None,
        window_s: float = DEFAULT_WINDOW_S,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.rundir = os.fspath(rundir)
        self.platform = platform or config.PLATFORM_CLASS or "cpu"
        self.window_s = float(window_s)
        self.clock = clock
        self._readers: Dict[str, TailReader] = {}
        #: rank -> raw records, the doctor.load shape
        self.by_rank: Dict[int, List[Dict[str, Any]]] = {}
        #: rank -> last collective seq seen
        self.last_seq: Dict[int, int] = {}
        #: rank -> wall-clock t of the last heartbeat / emission record
        self.last_heartbeat_t: Dict[int, float] = {}
        self.last_emission_t: Dict[int, float] = {}
        #: (op, impl) -> [emissions, payload bytes]
        self.totals: Dict[Tuple[str, str], List[int]] = {}
        #: plan key -> [emissions, payload bytes] (plannable ops only)
        self.key_totals: Dict[str, List[int]] = {}
        #: rolling (mono_t, op, impl, nbytes) for windowed rates
        self._window: deque = deque()
        #: monotonic time of the last *progress* record (emission /
        #: exec / latency — heartbeats are liveness, not progress)
        self.progress_t: Optional[float] = None
        #: monotonic time of the first/last poll that saw anything
        self.started_t: Optional[float] = None
        self.records_total = 0
        self.anomalies_total = 0
        #: anomaly records new since the last drain (stream doctor's
        #: retune feed)
        self._fresh_anomalies: List[Dict[str, Any]] = []
        #: overlap observatory (armed runs only — step/compute span
        #: records appear on the sinks only under M4T_STEP_SPAN):
        #: rank -> bounded deques of (t0, t1) intervals
        self.step_spans: Dict[int, deque] = {}
        self.compute_spans: Dict[int, deque] = {}
        self.comm_spans: Dict[int, deque] = {}

    # -- discovery ----------------------------------------------------

    def discover(self) -> List[str]:
        """Current sink files: per-rank event sinks and flight-recorder
        dumps (which appear mid-death). The monitor's own outputs and
        the supervisor audit are excluded; rotated segments are
        handled inside each reader, not listed separately."""
        paths = []
        for p in sorted(glob.glob(os.path.join(self.rundir, "*.jsonl"))):
            if os.path.basename(p) in NON_RANK_SINKS:
                continue
            paths.append(p)
        for p in paths:
            if p not in self._readers:
                self._readers[p] = TailReader(p)
        return paths

    # -- ingestion ----------------------------------------------------

    def _ingest(self, rec: Dict[str, Any], path: str, now: float) -> None:
        rank = _rank_of(rec, path)
        if rank is None:
            return
        self.by_rank.setdefault(rank, []).append(rec)
        self.records_total += 1
        kind = rec.get("kind")
        t = rec.get("t") if isinstance(rec.get("t"), (int, float)) else None
        if kind == "heartbeat":
            if t is not None:
                self.last_heartbeat_t[rank] = max(
                    self.last_heartbeat_t.get(rank, 0.0), t
                )
            return
        if kind == "anomaly":
            self.anomalies_total += 1
            self._fresh_anomalies.append(dict(rec, rank=rank))
            return
        if kind in ("step", "compute"):
            # overlap observatory spans (observability/overlap.py):
            # a closed step is progress too
            self.progress_t = now
            t0, t1 = rec.get("t0"), rec.get("t1")
            if isinstance(t0, (int, float)) and isinstance(t1, (int, float)):
                spans = (self.step_spans if kind == "step"
                         else self.compute_spans)
                spans.setdefault(
                    rank, deque(maxlen=OVERLAP_SPANS)
                ).append((float(t0), float(t1)))
            return
        if kind in ("emission", "recorder", "exec", "latency"):
            self.progress_t = now
        if kind == "latency":
            # each latency sample measured the comm interval
            # [t - seconds, t] — the overlap view's comm side
            s = rec.get("seconds")
            if (t is not None and isinstance(s, (int, float)) and s > 0):
                self.comm_spans.setdefault(
                    rank, deque(maxlen=OVERLAP_SPANS)
                ).append((float(t) - float(s), float(t)))
        if kind in ("emission", "recorder"):
            seq = rec.get("seq")
            if isinstance(seq, int):
                self.last_seq[rank] = max(self.last_seq.get(rank, 0), seq)
            if t is not None:
                self.last_emission_t[rank] = max(
                    self.last_emission_t.get(rank, 0.0), t
                )
            if kind != "emission":
                # flight-recorder dumps replay emissions the sink
                # already carries (the doctor dedupes by seq; these
                # meters must not double-count the traffic) — they
                # still feed seq/liveness above, which is what a rank
                # whose sink never flushed needs
                return
            op = str(rec.get("op", "?"))
            impl = str(rec.get("impl") or "-")
            nbytes = int(rec.get("bytes") or 0)
            tot = self.totals.setdefault((op, impl), [0, 0])
            tot[0] += 1
            tot[1] += nbytes
            key = self.plan_key_of(rec)
            if key is not None:
                ktot = self.key_totals.setdefault(key, [0, 0])
                ktot[0] += 1
                ktot[1] += nbytes
            self._window.append((now, op, impl, nbytes))

    def plan_key_of(self, rec: Dict[str, Any]) -> Optional[str]:
        """The plan key of one emission record, for plannable ops."""
        from ..planner import plan as _plan

        op = rec.get("op")
        if op == "QuantizedAllReduce":
            rec = dict(rec, op="AllReduce")
            op = "AllReduce"
        if op not in _plan.AVAILABLE:
            return None
        return _plan.key_from_record(rec, self.platform)

    def poll(self) -> int:
        """Drain every reader once; returns how many new records were
        ingested (0 = no movement — the stall signal)."""
        now = self.clock()
        if self.started_t is None:
            self.started_t = now
        n = 0
        for path in self.discover():
            for rec in self._readers[path].poll():
                self._ingest(rec, path, now)
                n += 1
        # age out the rate window
        horizon = now - self.window_s
        while self._window and self._window[0][0] < horizon:
            self._window.popleft()
        return n

    def drain_anomalies(self) -> List[Dict[str, Any]]:
        """Anomaly records that arrived since the previous drain (the
        streaming doctor turns them into retune recommendations)."""
        fresh, self._fresh_anomalies = self._fresh_anomalies, []
        return fresh

    # -- reading ------------------------------------------------------

    def stalled_s(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds since the last progress record (None before any)."""
        if self.progress_t is None:
            return None
        return max(0.0, (self.clock() if now is None else now) - self.progress_t)

    def rates(self) -> Dict[Tuple[str, str], Dict[str, float]]:
        """Windowed per-(op, impl) emission and byte rates."""
        now = self.clock()
        horizon = now - self.window_s
        span = min(
            self.window_s,
            max(1e-9, now - (self.started_t if self.started_t else now)),
        )
        acc: Dict[Tuple[str, str], List[float]] = {}
        for t, op, impl, nbytes in self._window:
            if t < horizon:
                continue
            a = acc.setdefault((op, impl), [0.0, 0.0])
            a[0] += 1
            a[1] += nbytes
        return {
            k: {"emissions_per_s": v[0] / span, "bytes_per_s": v[1] / span}
            for k, v in acc.items()
        }

    def snapshot(self, *, attribute: bool = False) -> Dict[str, Any]:
        """Plain-JSON live state (the dashboard / exporter input).
        ``attribute=True`` additionally joins the accumulated records
        against the cost model (``perf.attribute``) for achieved-GB/s
        rows — heavier, so only done at refresh cadence."""
        now_wall = time.time()
        ranks = sorted(self.by_rank)
        seqs = {r: self.last_seq.get(r, 0) for r in ranks}
        front = max(seqs.values(), default=0)
        snap: Dict[str, Any] = {
            "rundir": self.rundir,
            "platform": self.platform,
            "ranks": ranks,
            "records": self.records_total,
            "seqs": {str(r): seqs[r] for r in ranks},
            "seq_skew": (front - min(seqs.values())) if seqs else 0,
            "stalled_s": self.stalled_s(),
            "heartbeat_age_s": {
                str(r): max(0.0, now_wall - t)
                for r, t in sorted(self.last_heartbeat_t.items())
            },
            "emission_age_s": {
                str(r): max(0.0, now_wall - t)
                for r, t in sorted(self.last_emission_t.items())
            },
            "totals": {
                f"{op}|{impl}": {"emissions": v[0], "payload_bytes": v[1]}
                for (op, impl), v in sorted(self.totals.items())
            },
            "plan_keys": {
                k: {"emissions": v[0], "payload_bytes": v[1]}
                for k, v in sorted(self.key_totals.items())
            },
            "rates": {
                f"{op}|{impl}": v
                for (op, impl), v in sorted(self.rates().items())
            },
            "anomalies": self.anomalies_total,
        }
        if self.step_spans:
            # overlap observatory rollup (armed runs only — the key is
            # absent otherwise, so the exporter's families only appear
            # when step spans exist)
            from . import overlap as _overlap

            per_rank: Dict[str, Any] = {}
            agg = {"steps": 0, "comm_exposed_s": 0.0,
                   "comm_overlapped_s": 0.0}
            for r in sorted(self.step_spans):
                tot = _overlap.occupancy_totals(
                    list(self.step_spans[r]),
                    list(self.compute_spans.get(r, ())),
                    list(self.comm_spans.get(r, ())),
                )
                per_rank[str(r)] = tot
                agg["steps"] = max(agg["steps"], tot["steps"])
                agg["comm_exposed_s"] += tot["comm_exposed_s"]
                agg["comm_overlapped_s"] += tot["comm_overlapped_s"]
            comm = agg["comm_exposed_s"] + agg["comm_overlapped_s"]
            snap["overlap"] = {
                **agg,
                "overlap_ratio": (
                    agg["comm_overlapped_s"] / comm if comm > 0 else None
                ),
                "per_rank": per_rank,
            }
        if attribute and self.by_rank:
            from . import perf

            try:
                snap["attribution"] = perf.attribute(self.by_rank)
            except Exception:  # pragma: no cover — best-effort join
                snap["attribution"] = None
        return snap


# ---------------------------------------------------------------------
# dashboard rendering
# ---------------------------------------------------------------------


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}B"


def _fmt_age(s: Optional[float]) -> str:
    if s is None:
        return "-"
    return f"{s:.1f}s"


def render_dashboard(
    snap: Dict[str, Any], verdicts: Optional[List[Dict[str, Any]]] = None
) -> str:
    """Multi-line terminal view of one snapshot."""
    lines = [
        f"m4t live: {snap['rundir']}  "
        f"[{len(snap['ranks'])} rank(s), {snap['records']} records, "
        f"skew {snap['seq_skew']}, stalled {_fmt_age(snap['stalled_s'])}]"
    ]
    if snap["ranks"]:
        lines.append(f"{'rank':>5} {'seq':>7} {'emit age':>9} {'hb age':>8}")
        for r in snap["ranks"]:
            k = str(r)
            lines.append(
                f"{r:>5} {snap['seqs'].get(k, 0):>7} "
                f"{_fmt_age(snap['emission_age_s'].get(k)):>9} "
                f"{_fmt_age(snap['heartbeat_age_s'].get(k)):>8}"
            )
    else:
        lines.append("(no per-rank sinks yet)")
    if snap["totals"]:
        lines.append(
            f"{'op|impl':<28} {'emits':>7} {'payload':>10} {'rate':>12}"
        )
        for key, tot in sorted(snap["totals"].items()):
            rate = snap["rates"].get(key, {})
            rate_txt = (
                f"{_fmt_bytes(rate['bytes_per_s'])}/s"
                if rate.get("bytes_per_s")
                else "-"
            )
            lines.append(
                f"{key:<28} {tot['emissions']:>7} "
                f"{_fmt_bytes(tot['payload_bytes']):>10} {rate_txt:>12}"
            )
    attribution = snap.get("attribution")
    if attribution and attribution.get("rows"):
        lines.append(
            f"{'op':<20} {'payload':>9} {'GB/s':>8} {'%peak':>6} {'slow':>6}"
        )
        for row in attribution["rows"]:
            gbps = row.get("achieved_gbps")
            pct = row.get("pct_of_peak")
            slow = row.get("slowdown")
            op_txt = row["op"] + (f"+{row['impl']}" if row.get("impl") else "")
            lines.append(
                f"{op_txt:<20} {_fmt_bytes(row['bytes']):>9} "
                + (f"{gbps:>8.3g}" if gbps is not None else f"{'-':>8}")
                + (f" {pct:>5.1f}%" if pct is not None else f" {'-':>6}")
                + (f" {slow:>5.1f}x" if slow is not None else f" {'-':>6}")
            )
    ov = snap.get("overlap")
    if ov:
        ratio = ov.get("overlap_ratio")
        ratio_txt = (f"{ratio * 100.0:.0f}% of comm hidden"
                     if ratio is not None else "no comm inside steps")
        lines.append(
            f"overlap: {ratio_txt}, exposed "
            f"{ov['comm_exposed_s']:.3f}s across {ov['steps']} step(s)"
        )
    if snap.get("anomalies"):
        lines.append(f"anomalies: {snap['anomalies']}")
    for v in (verdicts or [])[-5:]:
        f = v.get("finding", {})
        lines.append(
            f"VERDICT [{v.get('klass', '?')}] {f.get('kind', '?')}: "
            + json.dumps(
                {k: f[k] for k in ("rank", "seq", "op", "verdict",
                                   "stuck_before") if k in f},
                default=str,
            )
        )
    return "\n".join(lines)


def status_line(
    snap: Dict[str, Any], verdicts: Optional[List[Dict[str, Any]]] = None
) -> str:
    """One-line launcher-side dashboard (children share the tty)."""
    seqs = " ".join(f"r{r}:{snap['seqs'][str(r)]}" for r in snap["ranks"])
    rate = sum(v.get("bytes_per_s", 0.0) for v in snap["rates"].values())
    txt = (
        f"live: {seqs or 'no sinks yet'} skew {snap['seq_skew']} "
        f"stalled {_fmt_age(snap['stalled_s'])} "
        f"{_fmt_bytes(rate)}/s"
    )
    ov = snap.get("overlap")
    if ov and ov.get("overlap_ratio") is not None:
        txt += f" ovl {ov['overlap_ratio'] * 100.0:.0f}%"
    if snap.get("anomalies"):
        txt += f" anomalies {snap['anomalies']}"
    if verdicts:
        txt += f" VERDICTS {len(verdicts)}"
    return txt


# ---------------------------------------------------------------------
# launcher-side monitor thread
# ---------------------------------------------------------------------


class LiveMonitor:
    """Poll + stream-doctor + export loop beside a spawned world.

    The launcher starts one per attempt (``launch --live``); the spawn
    loop checks :meth:`escalation` and tears the world down with the
    streaming diagnosis the moment a hang/mismatch is *confirmed* —
    seconds after the wedge, instead of at ``--hang-timeout``.
    """

    def __init__(
        self,
        rundir: str,
        *,
        interval_s: Optional[float] = None,
        grace_s: Optional[float] = None,
        platform: Optional[str] = None,
        prom_path: Optional[str] = None,
        http_port: Optional[int] = None,
        dashboard: bool = False,
        dashboard_every_s: float = 2.0,
        out=None,
    ):
        from .stream_doctor import StreamDoctor

        self.interval_s = float(
            config.LIVE_INTERVAL_S if interval_s is None else interval_s
        )
        self.aggregator = LiveAggregator(rundir, platform=platform)
        self.doctor = StreamDoctor(
            self.aggregator,
            grace_s=grace_s,
            verdict_log=os.path.join(rundir, "live.jsonl"),
        )
        self.prom_path = prom_path
        self.http_port = http_port
        self.dashboard = bool(dashboard)
        self.dashboard_every_s = float(dashboard_every_s)
        self.out = out if out is not None else sys.stderr
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._server = None

    def escalation(self) -> Optional[Dict[str, Any]]:
        """The confirmed hang/mismatch report (``m4t-doctor/1``), or
        None while the world looks healthy."""
        return self.doctor.escalation_report

    def _refresh(self, *, attribute: bool = False) -> Dict[str, Any]:
        snap = self.aggregator.snapshot(attribute=attribute)
        if self.prom_path:
            from . import export

            try:
                export.write_prom(
                    self.prom_path,
                    export.render_openmetrics(
                        snap, verdicts=self.doctor.confirmed
                    ),
                )
            except OSError:
                pass
        return snap

    def _loop(self) -> None:
        last_dash = 0.0
        while not self._stop.wait(self.interval_s):
            try:
                self.doctor.check()
                now = time.monotonic()
                if now - last_dash >= self.dashboard_every_s:
                    last_dash = now
                    snap = self._refresh()
                    if self.dashboard:
                        self.out.write(
                            status_line(snap, self.doctor.confirmed) + "\n"
                        )
                        self.out.flush()
            except Exception:  # pragma: no cover — monitoring is
                pass  # best-effort; it must never kill the launcher

    def start(self) -> "LiveMonitor":
        if self.http_port is not None:
            from . import export

            try:
                self._server = export.serve(
                    lambda: export.render_openmetrics(
                        self.aggregator.snapshot(),
                        verdicts=self.doctor.confirmed,
                    ),
                    port=self.http_port,
                )
                self.out.write(
                    "live: serving OpenMetrics on "
                    f"http://127.0.0.1:{self._server.server_port}/metrics\n"
                )
            except OSError as exc:
                self.out.write(f"live: metrics endpoint failed: {exc}\n")
        self._thread = threading.Thread(
            target=self._loop, name="m4t-live-monitor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._server is not None:
            try:
                self._server.shutdown()
            except Exception:
                pass
        # final pass so post-teardown records (flight-recorder dumps,
        # last fsync'd lines) land in the snapshot and verdict log
        try:
            self.doctor.check(final=True)
            self._refresh(attribute=True)
        except Exception:
            pass


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def _clear_screen(out) -> None:
    out.write("\x1b[2J\x1b[H")


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--selftest" in argv:
        return selftest()
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.observability.live",
        description=(
            "Tail a run directory's per-rank telemetry sinks and show "
            "the live cross-rank state: seqs, liveness, throughput, "
            "streaming-doctor verdicts. `--selftest` runs the "
            "device-free synthetic-stream smoke."
        ),
    )
    parser.add_argument(
        "rundir", help="run directory (the launcher's --events-dir)"
    )
    parser.add_argument(
        "--follow", action="store_true",
        help="keep polling and re-render until interrupted",
    )
    parser.add_argument(
        "--interval", type=float, default=1.0, metavar="S",
        help="refresh period under --follow (default %(default)s)",
    )
    parser.add_argument(
        "--grace", type=float, default=None, metavar="S",
        help="streaming-doctor stall grace before confirming a hang "
        "(default M4T_LIVE_GRACE)",
    )
    parser.add_argument(
        "--prom", default=None, metavar="PATH",
        help="also write an OpenMetrics snapshot to PATH each refresh",
    )
    parser.add_argument(
        "--port", type=int, default=None, metavar="N",
        help="serve the OpenMetrics text on http://127.0.0.1:N/metrics "
        "(0 picks a free port)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="print the snapshot (and confirmed verdicts) as JSON",
    )
    args = parser.parse_args(argv)

    from .stream_doctor import StreamDoctor

    agg = LiveAggregator(args.rundir)
    sdoc = StreamDoctor(agg, grace_s=args.grace, verdict_log=None)
    server = None
    if args.port is not None:
        from . import export

        server = export.serve(
            lambda: export.render_openmetrics(
                agg.snapshot(), verdicts=sdoc.confirmed
            ),
            port=args.port,
        )
        print(
            f"# serving http://127.0.0.1:{server.server_port}/metrics",
            file=sys.stderr,
        )

    def refresh() -> Dict[str, Any]:
        sdoc.check()
        snap = agg.snapshot(attribute=True)
        if args.prom:
            from . import export

            export.write_prom(
                args.prom,
                export.render_openmetrics(snap, verdicts=sdoc.confirmed),
            )
        return snap

    try:
        if not args.follow:
            snap = refresh()
            if args.json:
                print(json.dumps(
                    {"snapshot": snap, "verdicts": sdoc.confirmed},
                    indent=1, default=str,
                ))
            else:
                print(render_dashboard(snap, sdoc.confirmed))
            return 0
        while True:
            snap = refresh()
            if args.json:
                print(json.dumps(
                    {"snapshot": snap, "verdicts": sdoc.confirmed},
                    default=str,
                ), flush=True)
            else:
                _clear_screen(sys.stdout)
                print(render_dashboard(snap, sdoc.confirmed), flush=True)
            time.sleep(max(0.05, args.interval))
    except KeyboardInterrupt:
        return 0
    finally:
        if server is not None:
            server.shutdown()


# ---------------------------------------------------------------------
# selftest (device-free; wired into CI's `live` job and tier-1)
# ---------------------------------------------------------------------


def selftest() -> int:  # noqa: C901 — one linear smoke script
    import tempfile

    from . import export
    from .stream_doctor import StreamDoctor
    from ..planner import autotune, plan as _plan

    def emission(rank, seq, op="AllReduce", nbytes=4096, t=100.0, **kw):
        rec = {
            "kind": "emission", "rank": rank, "seq": seq, "op": op,
            "bytes": nbytes, "dtype": "float32", "axes": ["ranks"],
            "world": 2, "shape": [nbytes // 4], "cid": f"c{rank}x{seq}",
            "t": t,
        }
        rec.update(kw)
        return rec

    with tempfile.TemporaryDirectory() as tmp:
        sink0 = os.path.join(tmp, "events-rank0.jsonl")
        sink1 = os.path.join(tmp, "events-rank1.jsonl")

        # -- torn-line safety ------------------------------------------
        reader = TailReader(sink0)
        with open(sink0, "w") as f:
            f.write(json.dumps(emission(0, 1)) + "\n")
            f.write('{"kind": "emission", "rank": 0, "seq": 2')  # torn
        got = reader.poll()
        assert [r["seq"] for r in got] == [1], got
        assert reader.poll() == []  # the torn tail stays buffered
        with open(sink0, "a") as f:
            f.write(', "op": "AllReduce", "bytes": 16}\n')
        got = reader.poll()
        assert [r["seq"] for r in got] == [2], "completed line parses once"
        assert reader.poll() == []

        # -- rotation: capped sink, reader sees every record once ------
        from . import events as _events

        rot_dir = os.path.join(tmp, "rot")  # out of the aggregated dir
        os.makedirs(rot_dir)
        rot_path = os.path.join(rot_dir, "rot.jsonl")
        log = _events.EventLog(rot_path, max_bytes=512)
        rreader = TailReader(rot_path)
        seen: List[int] = []
        for i in range(40):
            log.append({"kind": "emission", "rank": 0, "seq": i + 1,
                        "op": "AllReduce", "bytes": 64})
            if i % 7 == 0:
                seen.extend(r["seq"] for r in rreader.poll())
        log.close()
        seen.extend(r["seq"] for r in rreader.poll())
        assert seen == list(range(1, 41)), f"lost/duped across rotation: {seen}"
        assert os.path.exists(rot_path + ".1"), "cap must have rotated"
        merged = [r["seq"] for r in _events.read(rot_path)]
        assert merged == sorted(merged) and merged[-1] == 40

        # -- aggregation + wedge verdict (equal seqs, exec tiebreak) ---
        clock = {"now": 0.0}
        agg = LiveAggregator(tmp, platform="cpu", clock=lambda: clock["now"])
        sdoc = StreamDoctor(
            agg, grace_s=2.0,
            verdict_log=os.path.join(tmp, "live.jsonl"),
            clock=lambda: clock["now"],
        )
        with open(sink0, "w") as f:
            for s in (1, 2, 3):
                f.write(json.dumps(emission(0, s)) + "\n")
            for s in (1, 2, 3):  # rank 0 entered all three
                f.write(json.dumps({"kind": "exec", "rank": 0, "seq": s,
                                    "op": "AllReduce", "t": 100.0 + s}) + "\n")
        with open(sink1, "w") as f:
            for s in (1, 2, 3):
                f.write(json.dumps(emission(1, s)) + "\n")
            for s in (1, 2):  # rank 1 never began executing seq 3
                f.write(json.dumps({"kind": "exec", "rank": 1, "seq": s,
                                    "op": "AllReduce", "t": 100.0 + s}) + "\n")
            f.write(json.dumps({"kind": "heartbeat", "rank": 1,
                                "source": "hb", "t": 180.0}) + "\n")
        sdoc.check()
        assert sdoc.escalation_report is None, "no confirmation before grace"
        clock["now"] += 5.0  # world stalls past the grace
        sdoc.check()
        rep = sdoc.escalation_report
        assert rep is not None and rep["schema"] == "m4t-doctor/1"
        (hang,) = [f for f in rep["findings"] if f["kind"] == "hang"]
        assert hang["rank"] == 1 and hang["wedged"] and hang["verdict"] == "hung"
        assert hang["stuck_before"].startswith("AllReduce"), hang

        # parity: the offline doctor sees the identical finding
        from . import doctor as _doctor

        offline = _doctor.diagnose([tmp])
        assert [
            f for f in offline["findings"] if f.get("kind") == "hang"
        ] == [hang], "streaming and offline doctor must agree"

        # -- straggler -> retune -> autotune accepts the keys ----------
        with open(sink0, "a") as f:
            for i in range(6):
                f.write(json.dumps({"kind": "latency", "rank": 0,
                                    "op": "AllReduce", "seconds": 0.001,
                                    "t": 104.0 + i}) + "\n")
        with open(sink1, "a") as f:
            for i in range(6):
                f.write(json.dumps({"kind": "latency", "rank": 1,
                                    "op": "AllReduce", "seconds": 0.05,
                                    "t": 104.0 + i}) + "\n")
        sdoc.check()
        retunes = [v for v in _events.read(os.path.join(tmp, "live.jsonl"))
                   if v["kind"] == "retune"]
        assert retunes and retunes[0]["reason"] == "straggler", retunes
        keys = autotune.keys_from_verdicts([tmp], platform="cpu")
        assert keys, "retune events must yield plan keys"
        for k in keys:
            _plan.parse_key(k)  # every recommended key is well-formed
        planobj, _report = autotune.sweep(keys)
        assert set(planobj.entries) == set(keys)

        # -- overlap view (step spans on the live sinks) ---------------
        snap = agg.snapshot()
        assert "overlap" not in snap, "unarmed snapshot carries no overlap"
        with open(sink0, "a") as f:
            # one step [100, 110): compute [100, 107); the six latency
            # samples above land at [104-eps, 109] — part hidden, part
            # exposed
            f.write(json.dumps({"kind": "step", "rank": 0, "step": 0,
                                "t0": 100.0, "t1": 110.0, "t": 110.0})
                    + "\n")
            f.write(json.dumps({"kind": "compute", "rank": 0, "step": 0,
                                "t0": 100.0, "t1": 107.0, "t": 107.0})
                    + "\n")
        agg.poll()
        snap = agg.snapshot(attribute=True)
        ov = snap.get("overlap")
        assert ov and ov["steps"] == 1, ov
        assert ov["comm_exposed_s"] > 0 and ov["comm_overlapped_s"] > 0
        assert 0.0 < ov["overlap_ratio"] < 1.0, ov
        assert "0" in ov["per_rank"], ov

        # -- dashboard + OpenMetrics render ----------------------------
        dash = render_dashboard(snap, sdoc.confirmed)
        assert "rank" in dash and "VERDICT" in dash
        assert "overlap:" in dash, dash
        assert "ovl" in status_line(snap)
        text = export.render_openmetrics(snap, verdicts=sdoc.confirmed)
        assert text.endswith("# EOF\n"), "OpenMetrics must end with # EOF"
        assert 'm4t_rank_last_seq{rank="1"} 3' in text, text
        assert "m4t_verdicts_total" in text
        assert "m4t_overlap_ratio" in text, text
        assert 'm4t_comm_exposed_seconds_total{rank="0"}' in text, text
        export.write_prom(os.path.join(tmp, "metrics.prom"), text)
        assert open(os.path.join(tmp, "metrics.prom")).read() == text

        # -- HTTP endpoint ---------------------------------------------
        import urllib.request

        server = export.serve(lambda: text, port=0)
        try:
            url = f"http://127.0.0.1:{server.server_port}/metrics"
            with urllib.request.urlopen(url, timeout=5) as resp:
                assert resp.status == 200
                assert resp.read().decode() == text
                assert "openmetrics" in resp.headers.get("Content-Type", "")
        finally:
            server.shutdown()

    print("live selftest ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
