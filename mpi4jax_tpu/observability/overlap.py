"""Per-step compute/communication occupancy attribution.

The telemetry stack can say how long a collective took
(``metrics.mark_runtime_start/end`` cid pairs -> ``exec``/``latency``
records) but not whether that time was *hidden* behind compute or sat
on the step's critical path. This module adds the missing coordinate:
training-step and compute-phase **interval records** on the existing
JSONL sinks, and the exact interval algebra that decomposes each
step's wall clock into

    compute_only + comm_exposed + comm_overlapped + idle  ==  span

(telescoping within :data:`SUM_TOLERANCE_S`, the ``serving/profile``
coverage idiom: every decomposition self-checks and carries an ``ok``
flag plus a named-coverage fraction).

Span records (armed only)::

    {"kind": "step",    "step": N, "t0": ..., "t1": ..., "t": t1}
    {"kind": "compute", "step": N, "t0": ..., "t1": ..., "t": t1}

Arming: ``M4T_STEP_SPAN=1`` (``launch --overlap`` sets it for every
rank) or :func:`arm`. Unarmed, :func:`step_span`/:func:`compute_span`
are no-ops behind one falsy check, no records are written, and every
pre-existing record schema stays byte-identical (drift-pinned in
``tests/test_overlap.py``). Armed, ``exec``/``latency``/``emission``
records additionally carry the current ``step`` — the route-level join
key — stamped at callback time (``metrics``) and trace time
(``ops/_core``).

Comm intervals need no new instrumentation: a ``latency`` record at
wall time ``t`` with duration ``seconds`` *is* the execution interval
``[t - seconds, t]`` of its collective (the cid pair measured it).
Compute intervals come from :func:`compute_span`; both are clipped to
each step window and merged into disjoint unions before the
decomposition, so overlapping compute phases or concurrent collectives
never double-count.

Offline report (schema ``m4t-overlap/1``)::

    python -m mpi4jax_tpu.observability.overlap RUNDIR [--json]

per-step and per-(op, impl, plan-key) exposed-vs-hidden time, achieved
GB/s *during compute* vs standalone (the perf attribution join
restricted to overlapped intervals), the occupancy ratio, and the cost
model's predicted overlappable fraction vs achieved. ``doctor --perf``
appends the "exposed communication" section; ``live``/``export``
surface the rolling ratio; ``perf gate --variant overlap`` tracks the
``benchmarks/overlap_probe.py`` trajectory. See
``docs/observability.md`` "Overlap attribution".
"""

from __future__ import annotations

import json
import sys
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .. import config
from . import events

#: report schema tag
SCHEMA = "m4t-overlap/1"

#: decomposition self-check: the four phases must telescope to the
#: step span within this (float-arithmetic) tolerance
SUM_TOLERANCE_S = 1e-6

#: named-coverage floor: below this fraction of the step span covered
#: by compute/comm intervals the decomposition is mostly "idle" and
#: the report flags it (instrumentation gap, not an overlap verdict)
COVERAGE_MIN = 0.90

#: a latency sample counts as "during compute" when at least this
#: fraction of its interval intersects the rank's compute union
DURING_COMPUTE_FRAC = 0.5


# ---------------------------------------------------------------------
# arming + span API
# ---------------------------------------------------------------------

_armed = bool(config.STEP_SPAN)
_counter = 0
_current: Optional[int] = None


def armed() -> bool:
    """Is step-span instrumentation on (``M4T_STEP_SPAN`` /
    :func:`arm`)? The single falsy check every unarmed call site pays."""
    return _armed


def arm(on: bool = True) -> None:
    """Programmatic arming (analog of ``metrics.enable``)."""
    global _armed
    _armed = bool(on)


def current_step() -> Optional[int]:
    """The step number of the step span currently open in this
    process, or None (unarmed / outside a span). Read by
    ``metrics.mark_runtime_start/end`` and ``ops/_core`` to stamp
    ``step`` onto runtime and emission records — module-global, not
    thread-local, on purpose: latency callbacks fire on runtime
    threads, not the thread that opened the span."""
    return _current if _armed else None


@contextmanager
def step_span(step: Optional[int] = None, **fields: Any):
    """Mark one training step's wall-clock boundaries.

    Armed: opens the process-wide step context (``current_step``),
    emits a ``step`` interval record through the default event sink at
    exit, and yields the step number. Unarmed: yields None, writes
    nothing, costs one falsy check. Steps auto-number from 0 when
    ``step`` is not given; exceptions propagate but the record is
    still written (the span genuinely ended)."""
    global _counter, _current
    if not _armed:
        yield None
        return
    if step is None:
        step = _counter
    n = int(step)
    _counter = n + 1
    prev = _current
    _current = n
    t0 = time.time()
    try:
        yield n
    finally:
        t1 = time.time()
        _current = prev
        events.emit(
            {"kind": "step", "step": n, "t0": t0, "t1": t1, "t": t1,
             **fields}
        )


@contextmanager
def compute_span(step: Optional[int] = None, **fields: Any):
    """Mark a compute phase inside the current step (the intervals the
    decomposition intersects comm time against). Same arming contract
    as :func:`step_span`; ``step`` defaults to the enclosing step."""
    if not _armed:
        yield None
        return
    n = _current if step is None else int(step)
    t0 = time.time()
    try:
        yield n
    finally:
        t1 = time.time()
        rec: Dict[str, Any] = {"kind": "compute", "t0": t0, "t1": t1,
                               "t": t1, **fields}
        if n is not None:
            rec["step"] = n
        events.emit(rec)


# ---------------------------------------------------------------------
# interval algebra
# ---------------------------------------------------------------------

Interval = Tuple[float, float]


def merge(intervals: Iterable[Interval]) -> List[Interval]:
    """Disjoint sorted union of arbitrary (possibly overlapping,
    possibly empty/inverted) intervals."""
    ivs = sorted(
        (float(s), float(e)) for s, e in intervals if float(e) > float(s)
    )
    out: List[Interval] = []
    for s, e in ivs:
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def total(intervals: Iterable[Interval]) -> float:
    """Total measure of a disjoint interval list."""
    return sum(e - s for s, e in intervals)


def clip(intervals: Iterable[Interval], t0: float, t1: float) -> List[Interval]:
    """Intervals intersected with the window ``[t0, t1]``."""
    out = []
    for s, e in intervals:
        s, e = max(float(s), t0), min(float(e), t1)
        if e > s:
            out.append((s, e))
    return out


def intersect(a: Sequence[Interval], b: Sequence[Interval]) -> List[Interval]:
    """Intersection of two disjoint sorted interval lists (two-pointer
    sweep)."""
    out: List[Interval] = []
    i = j = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            out.append((s, e))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


def decompose(
    t0: float,
    t1: float,
    compute: Iterable[Interval],
    comm: Iterable[Interval],
) -> Dict[str, Any]:
    """Exact decomposition of the window ``[t0, t1]``.

    Clips both interval families to the window, merges each into a
    disjoint union, and returns the four phases plus the telescoping
    self-check::

        compute_only + comm_exposed + comm_overlapped + idle == span

    (``residual_s`` is the float error; ``ok`` iff it is within
    :data:`SUM_TOLERANCE_S`). ``coverage`` is the named fraction of
    the span (non-idle); ``covered`` flags it against
    :data:`COVERAGE_MIN`. By inclusion-exclusion the identity is exact
    over the reals — the residual only measures float round-off, which
    is the point of carrying it."""
    t0, t1 = float(t0), float(t1)
    span = max(0.0, t1 - t0)
    cset = merge(clip(compute, t0, t1))
    mset = merge(clip(comm, t0, t1))
    overlapped = total(intersect(cset, mset))
    compute_s = total(cset)
    comm_s = total(mset)
    union = compute_s + comm_s - overlapped
    parts = {
        "compute_only_s": compute_s - overlapped,
        "comm_exposed_s": comm_s - overlapped,
        "comm_overlapped_s": overlapped,
        "idle_s": span - union,
    }
    sum_s = sum(parts.values())
    residual = abs(span - sum_s)
    coverage = (union / span) if span > 0 else 0.0
    return {
        "t0": t0,
        "t1": t1,
        "span_s": span,
        **parts,
        "comm_s": comm_s,
        "compute_s": compute_s,
        "sum_s": sum_s,
        "residual_s": residual,
        "ok": residual <= SUM_TOLERANCE_S,
        "coverage": coverage,
        "covered": coverage >= COVERAGE_MIN,
    }


def occupancy_ratio(d: Dict[str, Any]) -> Optional[float]:
    """Fraction of a decomposition's communication time hidden behind
    compute (None when the window moved no comm time)."""
    comm = d.get("comm_overlapped_s", 0.0) + d.get("comm_exposed_s", 0.0)
    if comm <= 0:
        return None
    return d["comm_overlapped_s"] / comm


# ---------------------------------------------------------------------
# record extraction
# ---------------------------------------------------------------------


def span_records(
    records: Iterable[Dict[str, Any]], kind: str
) -> List[Dict[str, Any]]:
    """The well-formed ``step``/``compute`` interval records of one
    rank's stream, ordered by start time."""
    out = []
    for rec in records:
        if rec.get("kind") != kind:
            continue
        t0, t1 = rec.get("t0"), rec.get("t1")
        if isinstance(t0, (int, float)) and isinstance(t1, (int, float)):
            out.append(rec)
    out.sort(key=lambda r: (r["t0"], r["t1"]))
    return out


def comm_samples(
    records: Iterable[Dict[str, Any]]
) -> List[Tuple[Interval, Dict[str, Any]]]:
    """Per-execution comm intervals of one rank: each ``latency``
    record at wall time ``t`` with duration ``seconds`` measured the
    interval ``[t - seconds, t]``."""
    out = []
    for rec in records:
        if rec.get("kind") != "latency":
            continue
        t, s = rec.get("t"), rec.get("seconds")
        if (
            isinstance(t, (int, float))
            and isinstance(s, (int, float))
            and s > 0
        ):
            out.append(((float(t) - float(s), float(t)), rec))
    return out


def _compute_intervals(records: Iterable[Dict[str, Any]]) -> List[Interval]:
    return [
        (r["t0"], r["t1"]) for r in span_records(records, "compute")
    ]


def occupancy_totals(
    steps: Sequence[Interval],
    compute: Iterable[Interval],
    comm: Iterable[Interval],
) -> Dict[str, Any]:
    """Aggregate decomposition over a set of step windows (the live
    plane's rolling summary): sums the four phases across the given
    steps and reports the overall occupancy ratio."""
    cset = merge(compute)
    mset = merge(comm)
    agg = {
        "steps": 0,
        "compute_only_s": 0.0,
        "comm_exposed_s": 0.0,
        "comm_overlapped_s": 0.0,
        "idle_s": 0.0,
        "ok": True,
    }
    for t0, t1 in steps:
        d = decompose(t0, t1, cset, mset)
        agg["steps"] += 1
        for k in (
            "compute_only_s",
            "comm_exposed_s",
            "comm_overlapped_s",
            "idle_s",
        ):
            agg[k] += d[k]
        agg["ok"] = agg["ok"] and d["ok"]
    agg["overlap_ratio"] = occupancy_ratio(agg)
    return agg


# ---------------------------------------------------------------------
# report (m4t-overlap/1)
# ---------------------------------------------------------------------


def _median(values: List[float]) -> Optional[float]:
    if not values:
        return None
    ordered = sorted(values)
    return ordered[len(ordered) // 2]


def _route_of(
    lat: Dict[str, Any], cid_rec: Dict[str, Dict[str, Any]]
) -> Tuple[str, str, str, Optional[Dict[str, Any]]]:
    emission = cid_rec.get(lat.get("cid") or "")
    op = lat.get("op") or (emission or {}).get("op") or "?"
    impl = (emission or {}).get("impl") or "-"
    plan = (emission or {}).get("plan") or "-"
    return str(op), str(impl), str(plan), emission


def analyze_rank(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One rank's per-step decompositions and per-sample overlap
    classification (the building block of :func:`build_report`)."""
    steps = span_records(records, "step")
    compute = merge(_compute_intervals(records))
    samples = comm_samples(records)
    comm = merge(iv for iv, _ in samples)
    cid_rec = {
        r["cid"]: r
        for r in records
        if r.get("kind") in ("emission", "recorder") and r.get("cid")
    }
    rows = []
    for rec in steps:
        d = decompose(rec["t0"], rec["t1"], compute, comm)
        d["step"] = rec.get("step")
        d["overlap_ratio"] = occupancy_ratio(d)
        rows.append(d)
    # per-sample overlap fraction against the rank's compute union
    per_sample = []
    for (s, e), lat in samples:
        dur = e - s
        frac = (
            total(intersect([(s, e)], compute)) / dur if dur > 0 else 0.0
        )
        per_sample.append(((s, e), lat, frac))
    return {
        "steps": rows,
        "compute": compute,
        "comm": comm,
        "samples": per_sample,
        "cid_rec": cid_rec,
    }


def build_report(
    by_rank: Dict[int, List[Dict[str, Any]]],
    *,
    gbps: Optional[float] = None,
    alpha: Optional[float] = None,
    top: int = 0,
) -> Dict[str, Any]:
    """The ``m4t-overlap/1`` report over a doctor-loaded run
    (``doctor.load(inputs)``): per-step rows aggregated across ranks,
    per-(op, impl, plan-key) route rows with exposed-vs-hidden time
    and during-compute vs standalone achieved GB/s, program totals,
    and the cost model's predicted-vs-achieved overlappable fraction."""
    from . import costmodel

    per_rank: Dict[str, Any] = {}
    step_agg: Dict[int, Dict[str, Any]] = {}
    routes: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
    totals = {
        "compute_only_s": 0.0,
        "comm_exposed_s": 0.0,
        "comm_overlapped_s": 0.0,
        "idle_s": 0.0,
    }
    ok = True
    covered = True
    n_steps = 0
    for rank in sorted(by_rank):
        a = analyze_rank(by_rank[rank])
        if not a["steps"] and not a["samples"]:
            continue
        rank_tot = {
            k: sum(d[k] for d in a["steps"]) for k in totals
        }
        rank_tot["steps"] = len(a["steps"])
        rank_tot["overlap_ratio"] = occupancy_ratio(rank_tot)
        per_rank[str(rank)] = {"steps": a["steps"], "totals": rank_tot}
        for k in totals:
            totals[k] += rank_tot[k]
        n_steps += len(a["steps"])
        for d in a["steps"]:
            ok = ok and d["ok"]
            covered = covered and d["covered"]
            if isinstance(d.get("step"), int):
                agg = step_agg.setdefault(
                    d["step"],
                    {
                        "step": d["step"],
                        "ranks": 0,
                        "span_s": 0.0,
                        "compute_only_s": 0.0,
                        "comm_exposed_s": 0.0,
                        "comm_overlapped_s": 0.0,
                        "idle_s": 0.0,
                        "ok": True,
                        "coverage": 1.0,
                    },
                )
                agg["ranks"] += 1
                agg["span_s"] += d["span_s"]
                for k in totals:
                    agg[k] += d[k]
                agg["ok"] = agg["ok"] and d["ok"]
                agg["coverage"] = min(agg["coverage"], d["coverage"])
        for (s, e), lat, frac in a["samples"]:
            key = _route_of(lat, a["cid_rec"])[:3]
            op, impl, plan, emission = _route_of(lat, a["cid_rec"])
            row = routes.setdefault(
                key,
                {
                    "op": op,
                    "impl": impl,
                    "plan": plan,
                    "samples": 0,
                    "comm_s": 0.0,
                    "exposed_s": 0.0,
                    "overlapped_s": 0.0,
                    "_during": [],
                    "_standalone": [],
                    "predicted_frac": costmodel.overlappable_fraction(
                        op, impl if impl != "-" else None
                    ),
                },
            )
            dur = e - s
            row["samples"] += 1
            row["comm_s"] += dur
            row["overlapped_s"] += dur * frac
            row["exposed_s"] += dur * (1.0 - frac)
            if emission is not None:
                g = costmodel.achieved_gbps(
                    costmodel.record_cost(emission), dur
                )
                if g is not None:
                    cohort = (
                        "_during"
                        if frac >= DURING_COMPUTE_FRAC
                        else "_standalone"
                    )
                    row[cohort].append(g)
    route_rows = []
    for row in routes.values():
        during = row.pop("_during")
        standalone = row.pop("_standalone")
        row["during_n"] = len(during)
        row["standalone_n"] = len(standalone)
        row["gbps_during_p50"] = _median(during)
        row["gbps_standalone_p50"] = _median(standalone)
        row["achieved_frac"] = (
            row["overlapped_s"] / row["comm_s"] if row["comm_s"] > 0 else None
        )
        route_rows.append(row)
    route_rows.sort(key=lambda r: -r["exposed_s"])
    if top:
        route_rows = route_rows[:top]
    step_rows = [step_agg[k] for k in sorted(step_agg)]
    for agg in step_rows:
        agg["overlap_ratio"] = occupancy_ratio(agg)
    totals["overlap_ratio"] = occupancy_ratio(totals)
    totals["steps"] = n_steps
    return {
        "schema": SCHEMA,
        "ranks": len(per_rank),
        "ok": ok,
        "covered": covered,
        "steps": step_rows,
        "routes": route_rows,
        "per_rank": per_rank,
        "totals": totals,
    }


# ---------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------


def _fmt_s(s: Optional[float]) -> str:
    if s is None:
        return "-"
    if s < 1e-3:
        return f"{s * 1e6:.0f}us"
    if s < 1:
        return f"{s * 1e3:.1f}ms"
    return f"{s:.2f}s"


def _fmt_ratio(r: Optional[float]) -> str:
    return "-" if r is None else f"{100.0 * r:.0f}%"


def format_report(rep: Dict[str, Any]) -> str:
    """Human-readable overlap report (the CLI's default output)."""
    out = []
    tot = rep["totals"]
    out.append(
        f"overlap report ({rep['ranks']} ranks, {tot['steps']} rank-steps): "
        f"ratio {_fmt_ratio(tot['overlap_ratio'])} hidden — "
        f"exposed {_fmt_s(tot['comm_exposed_s'])}, "
        f"overlapped {_fmt_s(tot['comm_overlapped_s'])}, "
        f"compute-only {_fmt_s(tot['compute_only_s'])}, "
        f"idle {_fmt_s(tot['idle_s'])}"
        + ("" if rep["ok"] else "  [RESIDUAL CHECK FAILED]")
        + ("" if rep["covered"] else "  [coverage < 90%]")
    )
    if rep["steps"]:
        out.append(
            f"{'step':>5} {'ranks':>5} {'span':>9} {'cmp-only':>9} "
            f"{'exposed':>9} {'hidden':>9} {'idle':>9} {'ratio':>6} ok"
        )
        for d in rep["steps"]:
            out.append(
                f"{d['step']:>5} {d['ranks']:>5} {_fmt_s(d['span_s']):>9} "
                f"{_fmt_s(d['compute_only_s']):>9} "
                f"{_fmt_s(d['comm_exposed_s']):>9} "
                f"{_fmt_s(d['comm_overlapped_s']):>9} "
                f"{_fmt_s(d['idle_s']):>9} "
                f"{_fmt_ratio(d['overlap_ratio']):>6} "
                f"{'ok' if d['ok'] else 'RESIDUAL'}"
            )
    if rep["routes"]:
        out.append("")
        out.append(
            f"{'op':<14} {'impl':<12} {'n':>4} {'exposed':>9} "
            f"{'hidden':>9} {'achieved':>8} {'predicted':>9} "
            f"{'GB/s @cmp':>9} {'GB/s alone':>10}"
        )
        for r in rep["routes"]:
            during = r["gbps_during_p50"]
            alone = r["gbps_standalone_p50"]
            during_txt = "-" if during is None else f"{during:.2f}"
            alone_txt = "-" if alone is None else f"{alone:.2f}"
            out.append(
                f"{r['op']:<14} {r['impl']:<12} {r['samples']:>4} "
                f"{_fmt_s(r['exposed_s']):>9} "
                f"{_fmt_s(r['overlapped_s']):>9} "
                f"{_fmt_ratio(r['achieved_frac']):>8} "
                f"{_fmt_ratio(r['predicted_frac']):>9} "
                f"{during_txt:>9} {alone_txt:>10}"
            )
    return "\n".join(out)


def format_exposed(rep: Dict[str, Any], top: int = 5) -> str:
    """The ``doctor --perf`` "exposed communication" section: the top
    critical-path collectives by exposed (unhidden) wall time."""
    rows = [r for r in rep.get("routes", []) if r["exposed_s"] > 0]
    if not rows:
        return (
            "exposed communication: none — every measured collective "
            "was hidden behind compute"
        )
    out = [
        "exposed communication (critical-path collectives, by unhidden "
        "wall time):"
    ]
    for r in rows[:top]:
        out.append(
            f"  {r['op']} [{r['impl']}] exposed {_fmt_s(r['exposed_s'])} "
            f"of {_fmt_s(r['comm_s'])} comm "
            f"({_fmt_ratio(r['achieved_frac'])} hidden vs "
            f"{_fmt_ratio(r['predicted_frac'])} predicted, "
            f"{r['samples']} samples)"
        )
    return "\n".join(out)


# ---------------------------------------------------------------------
# selftest + CLI
# ---------------------------------------------------------------------


def _synthetic_by_rank() -> Dict[int, List[Dict[str, Any]]]:
    """Two ranks, three steps each, known geometry: step spans of 1.0s
    with 0.75s compute and 0.5s comm, 0.3s of which overlaps — a
    40%-exposed workload at 95% named coverage (device-free stub sink
    content)."""
    by_rank: Dict[int, List[Dict[str, Any]]] = {}
    for rank in (0, 1):
        recs: List[Dict[str, Any]] = []
        base = 1000.0 + rank * 0.001
        for n in range(3):
            t0 = base + n * 1.0
            cid = f"r{rank}s{n}"
            recs.append(
                {
                    "kind": "emission",
                    "cid": cid,
                    "op": "AllReduce",
                    "bytes": 1 << 20,
                    "dtype": "float32",
                    "axes": [],
                    "world": 2,
                    "impl": "hlo",
                    "seq": n + 1,
                    "t": t0,
                }
            )
            # compute [t0, t0+0.75); comm [t0+0.45, t0+0.95)
            recs.append(
                {"kind": "compute", "step": n, "t0": t0, "t1": t0 + 0.75,
                 "t": t0 + 0.75}
            )
            recs.append(
                {"kind": "latency", "cid": cid, "op": "AllReduce",
                 "seq": n + 1, "seconds": 0.5, "t": t0 + 0.95,
                 "step": n}
            )
            recs.append(
                {"kind": "step", "step": n, "t0": t0, "t1": t0 + 1.0,
                 "t": t0 + 1.0}
            )
        by_rank[rank] = recs
    return by_rank


def selftest() -> bool:
    """Device-free end-to-end check over stub sinks: span API arming
    contract, exact telescoping on the synthetic geometry, report
    build + both renderers. Exercised by CI (`lint.yml`) and
    ``--selftest``."""
    import io
    import tempfile

    global _counter
    # 1. unarmed: the API is a no-op and writes nothing
    was_armed, was_counter = _armed, _counter
    arm(False)
    with tempfile.TemporaryDirectory() as tmp:
        sink_path = tmp + "/events-rank0.jsonl"
        old_sink = events.get_sink()
        try:
            events.set_sink(sink_path)
            with step_span() as n:
                assert n is None
                with compute_span() as c:
                    assert c is None
            assert events.read(sink_path) == [], "unarmed span wrote records"
            # 2. armed: records land with the interval schema
            arm(True)
            _counter = 0
            with step_span() as n:
                assert n == 0 and current_step() == 0
                with compute_span():
                    pass
            assert current_step() is None
            recs = events.read(sink_path)
            kinds = [r["kind"] for r in recs]
            assert kinds == ["compute", "step"], kinds
            assert all(
                set(("t0", "t1", "t", "step")) <= set(r) for r in recs
            ), recs
        finally:
            arm(was_armed)
            _counter = was_counter
            events.set_sink(old_sink.path if old_sink else None)
    # 3. algebra: synthetic geometry telescopes exactly
    by_rank = _synthetic_by_rank()
    rep = build_report(by_rank)
    assert rep["ok"] and rep["covered"], rep["totals"]
    assert rep["ranks"] == 2 and rep["totals"]["steps"] == 6
    ratio = rep["totals"]["overlap_ratio"]
    assert ratio is not None and abs(ratio - 0.6) < 1e-6, ratio
    assert abs(rep["totals"]["comm_exposed_s"] - 6 * 0.2) < 1e-6
    assert rep["routes"] and rep["routes"][0]["op"] == "AllReduce"
    assert rep["routes"][0]["during_n"] + rep["routes"][0][
        "standalone_n"
    ] == 6
    # 4. renderers never throw and carry the headline numbers
    text = format_report(rep)
    assert "overlap report" in text and "AllReduce" in text
    assert "exposed" in format_exposed(rep)
    buf = io.StringIO()
    json.dump(rep, buf)  # report is plain JSON
    print("overlap selftest: ok (ratio 60% hidden on synthetic geometry)")
    return True


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.observability.overlap",
        description=(
            "Per-step compute/communication occupancy attribution over "
            "a run directory's JSONL telemetry (arm the run with "
            "launch --overlap / M4T_STEP_SPAN=1)."
        ),
    )
    ap.add_argument(
        "inputs", nargs="*", metavar="RUNDIR",
        help="run directory / JSONL files (doctor input convention)",
    )
    ap.add_argument("--json", action="store_true",
                    help="emit the m4t-overlap/1 report as JSON")
    ap.add_argument("--top", type=int, default=0,
                    help="keep only the top-N routes by exposed time")
    ap.add_argument("--selftest", action="store_true",
                    help="device-free self-check (stub sinks), then exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return 0 if selftest() else 1
    if not args.inputs:
        ap.error("RUNDIR required (or --selftest)")
    from . import doctor

    by_rank = doctor.load(args.inputs)
    rep = build_report(by_rank, top=args.top)
    if not rep["ranks"] or not rep["totals"]["steps"]:
        print(
            "no step spans found — arm the run with launch --overlap "
            "(M4T_STEP_SPAN=1 + runtime sampling) and wrap the step "
            "loop in obs.step_span()",
            file=sys.stderr,
        )
        return 2
    if args.json:
        json.dump(rep, sys.stdout, indent=1)
        print()
    else:
        print(format_report(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
