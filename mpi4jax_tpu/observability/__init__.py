"""Comm observability subsystem: metrics + events + flight recorder +
cross-rank doctor + trace export.

Per-process layers over every collective emission (``ops/_core.py``),
sharing one 8-char correlation id per emission:

1. **metrics** (:mod:`.metrics`) — per-op trace-time counters (op
   name, payload bytes, dtype, mesh axes, emission count, monotonic
   seq) and optional runtime latency reservoirs; ``snapshot()`` /
   ``reset()`` / ``report()``.
2. **events** (:mod:`.events`) — structured JSONL records in the
   ``BENCH_r*_probes.jsonl`` schema; rank-templated sinks
   (``{rank}`` in the path), crash-safe fsync mode, heartbeats.
3. **profiler annotations** — every op emission is wrapped in a
   ``m4t.<op>`` named scope (``utils/profiling.emission_scope``) so
   XLA traces attribute ICI time to the mpi4jax-level op; with
   telemetry on, the scope name carries the correlation id
   (``m4t.allreduce.<cid>``).
4. **flight recorder** (:mod:`.recorder`) — always-on in-memory ring
   of the last N emissions, dumped to JSONL on crash/atexit/signal
   for post-mortem analysis even when everything else was off.

Cross-rank (offline, artifact-driven):

5. **doctor** (:mod:`.doctor`) — ``python -m
   mpi4jax_tpu.observability.doctor RUNDIR`` merges per-rank logs and
   names collective mismatches, hung/behind/missing ranks, and
   stragglers.
6. **trace** (:mod:`.trace`) — export merged logs to Chrome
   trace-event JSON (Perfetto): one track per rank, latency slices,
   payload-byte counters.

Layers 1–3 are no-ops unless enabled (``M4T_TELEMETRY=1`` or
:func:`enable`); the flight recorder stays on (one dict append per
trace-time emission) unless ``M4T_FLIGHT_RECORDER=0``. See
``docs/observability.md``.
"""

from . import events  # noqa: F401
from . import metrics  # noqa: F401
from . import recorder  # noqa: F401
from .metrics import (  # noqa: F401
    MetricsRegistry,
    Reservoir,
    disable,
    enable,
    enabled,
    registry,
    report,
    reset,
    runtime_enabled,
    snapshot,
)
from .recorder import FlightRecorder  # noqa: F401
from .recorder import recorder as flight_recorder  # noqa: F401

# doctor/trace are import-light (no jax) but only needed offline;
# imported lazily by their CLIs and by launch.py's watchdog.

from .. import config as _config

if _config.HEARTBEAT_S > 0 and events.get_sink() is not None:
    # M4T_HEARTBEAT=<seconds> with a configured sink: start the
    # liveness stream immediately (the launcher sets both for every
    # rank when --events-dir is given).
    events.start_heartbeat(_config.HEARTBEAT_S)

__all__ = [
    "FlightRecorder",
    "MetricsRegistry",
    "Reservoir",
    "disable",
    "enable",
    "enabled",
    "events",
    "flight_recorder",
    "metrics",
    "recorder",
    "registry",
    "report",
    "reset",
    "runtime_enabled",
    "snapshot",
]
