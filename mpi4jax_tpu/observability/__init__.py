"""Comm observability subsystem: metrics + events + flight recorder +
cross-rank doctor + trace export.

Per-process layers over every collective emission (``ops/_core.py``),
sharing one 8-char correlation id per emission:

1. **metrics** (:mod:`.metrics`) — per-op trace-time counters (op
   name, payload bytes, dtype, mesh axes, emission count, monotonic
   seq) and optional runtime latency reservoirs; ``snapshot()`` /
   ``reset()`` / ``report()``.
2. **events** (:mod:`.events`) — structured JSONL records in the
   ``BENCH_r*_probes.jsonl`` schema; rank-templated sinks
   (``{rank}`` in the path), crash-safe fsync mode, heartbeats.
3. **profiler annotations** — every op emission is wrapped in a
   ``m4t.<op>`` named scope (``utils/profiling.emission_scope``) so
   XLA traces attribute ICI time to the mpi4jax-level op; with
   telemetry on, the scope name carries the correlation id
   (``m4t.allreduce.<cid>``).
4. **flight recorder** (:mod:`.recorder`) — always-on in-memory ring
   of the last N emissions, dumped to JSONL on crash/atexit/signal
   for post-mortem analysis even when everything else was off.

Cross-rank (offline, artifact-driven):

5. **doctor** (:mod:`.doctor`) — ``python -m
   mpi4jax_tpu.observability.doctor RUNDIR`` merges per-rank logs and
   names collective mismatches, hung/behind/missing ranks, and
   stragglers.
6. **trace** (:mod:`.trace`) — export merged logs to Chrome
   trace-event JSON (Perfetto): one track per rank, latency slices,
   payload-byte and achieved-bandwidth counters.

Performance attribution (:mod:`.costmodel` + :mod:`.perf`):

7. **cost model** — analytic per-op expected wire bytes / algorithm
   steps / alpha-beta expected time from the emission fingerprints
   the layers above already record.
8. **perf** — achieved-bandwidth / %-of-peak attribution
   (:func:`perf_report` live, ``python -m
   mpi4jax_tpu.observability.perf report`` offline, ``doctor
   --perf``), a live EWMA+MAD anomaly watch over runtime latency
   samples (``M4T_PERF_WATCH=1``), and the ``perf
   {history,gate,compare}`` bench-trajectory regression CLI.

Live telemetry plane (:mod:`.live` + :mod:`.stream_doctor` +
:mod:`.export`):

9. **live** — a launcher-side aggregator tailing the per-rank sinks
   *while they are written* (torn-line-safe, rotation-aware; no
   network): rolling per-rank liveness, cross-rank seq skew, per-
   (op, impl, plan-key) throughput. ``python -m
   mpi4jax_tpu.observability.live RUNDIR`` is the terminal view.
10. **stream_doctor** — the doctor's verdicts raised incrementally
    (mismatch immediately, hang/wedge after a stall grace), appended
    to ``live.jsonl`` with the supervisor's recovery class, plus
    ``retune`` recommendation events carrying the affected plan keys
    — the evidence ``planner tune --from-verdicts`` re-pins from.
11. **export** — OpenMetrics/Prometheus text: a periodic atomic
    ``metrics.prom`` snapshot and an optional localhost HTTP
    ``/metrics`` endpoint (``launch --metrics-port``).

Long-lived runs: every JSONL sink honors ``M4T_TELEMETRY_MAX_MB``
(size-capped rotation, ``.1``/``.2`` segments) and every reader —
doctor, perf, live — merges the rotated segments transparently.
:func:`heartbeat` / :func:`start_heartbeat` are the library-level
liveness hooks long step loops should call so a compute-heavy phase
does not look dead to the hang analysis.

Layers 1–3 are no-ops unless enabled (``M4T_TELEMETRY=1`` or
:func:`enable`); the flight recorder stays on (one dict append per
trace-time emission) unless ``M4T_FLIGHT_RECORDER=0``. See
``docs/observability.md``.
"""

from . import events  # noqa: F401
from . import metrics  # noqa: F401
from . import recorder  # noqa: F401
from .events import heartbeat, start_heartbeat  # noqa: F401
from .metrics import (  # noqa: F401
    MetricsRegistry,
    Reservoir,
    disable,
    enable,
    enabled,
    registry,
    report,
    reset,
    runtime_enabled,
    snapshot,
)
from .recorder import FlightRecorder  # noqa: F401
from .recorder import recorder as flight_recorder  # noqa: F401


def __getattr__(name):
    # costmodel/perf/live/stream_doctor/export resolve lazily (like
    # doctor/trace they are monitor-side modules; eager import here
    # would also make `python -m mpi4jax_tpu.observability.perf` warn
    # about the module pre-existing in sys.modules)
    if name in ("costmodel", "perf", "live", "stream_doctor", "export",
                "overlap"):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    if name in ("PerfWatch", "perf_report"):
        from . import perf as _perf

        return getattr(_perf, {"PerfWatch": "PerfWatch",
                               "perf_report": "perf_report"}[name])
    if name in ("step_span", "compute_span"):
        # the overlap observatory's step-scoped span API
        # (obs.step_span() around a training step; armed by
        # M4T_STEP_SPAN / launch --overlap, no-op otherwise)
        from . import overlap as _overlap

        return getattr(_overlap, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

# doctor/trace are import-light (no jax) but only needed offline;
# imported lazily by their CLIs and by launch.py's watchdog.

from .. import config as _config

if _config.HEARTBEAT_S > 0 and events.get_sink() is not None:
    # M4T_HEARTBEAT=<seconds> with a configured sink: start the
    # liveness stream immediately (the launcher sets both for every
    # rank when --events-dir is given).
    events.start_heartbeat(_config.HEARTBEAT_S)

__all__ = [
    "FlightRecorder",
    "MetricsRegistry",
    "PerfWatch",
    "Reservoir",
    "costmodel",
    "disable",
    "enable",
    "enabled",
    "events",
    "export",
    "flight_recorder",
    "heartbeat",
    "live",
    "metrics",
    "overlap",
    "perf",
    "perf_report",
    "recorder",
    "registry",
    "report",
    "reset",
    "runtime_enabled",
    "snapshot",
    "start_heartbeat",
    "step_span",
    "compute_span",
    "stream_doctor",
]
