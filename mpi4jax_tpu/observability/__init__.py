"""Comm telemetry subsystem: metrics registry + JSONL events +
profiler annotations.

Three correlated layers over every collective emission
(``ops/_core.py``), sharing one 8-char correlation id per emission:

1. **metrics** (:mod:`.metrics`) — per-op trace-time counters (op
   name, payload bytes, dtype, mesh axes, emission count) and optional
   runtime latency reservoirs; ``snapshot()`` / ``reset()`` /
   ``report()``.
2. **events** (:mod:`.events`) — structured JSONL records in the
   ``BENCH_r*_probes.jsonl`` schema; the bench drivers and the per-op
   emission stream share this one sink format.
3. **profiler annotations** — every op emission is wrapped in a
   ``m4t.<op>`` named scope (``utils/profiling.emission_scope``) so
   XLA traces attribute ICI time to the mpi4jax-level op; with
   telemetry on, the scope name carries the correlation id
   (``m4t.allreduce.<cid>``).

Everything is a no-op unless enabled (``M4T_TELEMETRY=1`` or
:func:`enable`); see ``docs/observability.md``.
"""

from . import events  # noqa: F401
from . import metrics  # noqa: F401
from .metrics import (  # noqa: F401
    MetricsRegistry,
    Reservoir,
    disable,
    enable,
    enabled,
    registry,
    report,
    reset,
    runtime_enabled,
    snapshot,
)

__all__ = [
    "MetricsRegistry",
    "Reservoir",
    "disable",
    "enable",
    "enabled",
    "events",
    "metrics",
    "registry",
    "report",
    "reset",
    "runtime_enabled",
    "snapshot",
]
