"""Structured JSONL event log — one schema, one sink.

Before this module, three places hand-rolled append-a-JSON-line code:
``benchmarks/tpu_watch.py`` (probe/stage forensics,
``BENCH_r*_probes.jsonl``), the round driver's ``PROGRESS.jsonl``, and
``bench.py``'s stdout metric line. They already agreed on the
essentials — one JSON object per line, a ``ts`` field in UTC
``%Y-%m-%dT%H:%M:%SZ`` — so that is the schema this module pins down:

- every record is a flat-ish JSON object on its own line;
- ``ts`` (UTC second resolution) is stamped at append time if absent;
- a ``kind`` field names the record family (``"emission"``,
  ``"probe"``, ``"stage"``, ``"bench"``, ...) so one file can hold
  mixed streams and still be filtered with one ``json.loads`` loop.

Two layers of API:

- :class:`EventLog` — an explicit append-only JSONL file handle, used
  by the bench drivers (``tpu_watch.py`` probe log, ``bench.py``
  results).
- a module default sink (``M4T_TELEMETRY_EVENTS=<path>`` or
  :func:`set_sink`) that :func:`emit` writes through; the op-emission
  telemetry (``debug.py``) uses this, and it is a no-op when no sink
  is configured.

Writes are line-buffered appends under a lock: concurrent writers
(battery stages in subprocesses append to the same probe log) each
write whole lines, which POSIX appends keep intact.

Multi-rank runs should not share one sink at all: a ``{rank}``
placeholder in the sink path (``M4T_TELEMETRY_EVENTS`` or
:func:`set_sink`) is substituted with the process rank
(:func:`current_rank`), giving each rank its own file — the layout the
cross-rank doctor (:mod:`.doctor`) consumes. ``fsync=True`` (or
``M4T_TELEMETRY_FSYNC=1``) additionally fsyncs after every record so
the final pre-hang events of a killed rank actually reach disk.

Long-lived runs can cap the sink (``M4T_TELEMETRY_MAX_MB``, or
``EventLog(max_bytes=...)``): when the live file grows past the cap
it is rotated to ``<path>.1`` (and a previous ``.1`` to ``.2``;
anything older is dropped), so telemetry can never fill the disk.
Readers go through :func:`iter_records`/:func:`read`, which merge the
rotated segments back oldest-first — the doctor, the perf
attribution, and the live tailer (:mod:`.live`) all see one
continuous stream.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

from .. import config


def current_rank() -> int:
    """This process's rank for telemetry purposes.

    ``M4T_RANK`` (set by ``mpi4jax_tpu.launch``) wins; otherwise a
    ``jax.distributed``-initialized process reports
    ``jax.process_index()``; otherwise 0. Never initializes a backend:
    the jax path is only consulted when the distributed client already
    exists, so this is safe to call at import time.
    """
    raw = os.environ.get("M4T_RANK", "")
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    try:
        from jax._src import distributed

        if distributed.global_state.client is not None:
            import jax

            return jax.process_index()
    except Exception:
        pass
    return 0


def expand_rank_template(path: str, rank: Optional[int] = None) -> str:
    """Substitute a literal ``{rank}`` placeholder in a sink path."""
    if "{rank}" not in path:
        return path
    return path.replace(
        "{rank}", str(current_rank() if rank is None else rank)
    )


def current_trace() -> Optional[str]:
    """The distributed trace id this process is serving under
    (``M4T_TRACE_ID``), or None outside a traced job.

    Minted at ``serving.spool.submit`` and threaded through every
    spawn/dispatch seam (``launch.rank_env``, the warm pool's work-item
    overlay), it is the one key every plane's records join on. Read
    from the environment on purpose — the warm pool applies it as a
    per-work-item overlay in a long-lived process, so an import-time
    snapshot would pin the first job's id forever."""
    return os.environ.get("M4T_TRACE_ID") or None


def current_job() -> Optional[str]:
    """The serving-plane job id this process is executing
    (``M4T_JOB_ID``), or None outside a served job. Same dynamic-read
    contract as :func:`current_trace`."""
    return os.environ.get("M4T_JOB_ID") or None

#: the shared timestamp format (BENCH_r*_probes.jsonl / PROGRESS.jsonl)
TS_FORMAT = "%Y-%m-%dT%H:%M:%SZ"


def utc_stamp(t: Optional[float] = None) -> str:
    """UTC timestamp string in the shared schema format."""
    return time.strftime(TS_FORMAT, time.gmtime(t))


def event(kind: str, **fields: Any) -> Dict[str, Any]:
    """Build a schema-shaped record (``ts`` stamped at append time)."""
    record: Dict[str, Any] = {"kind": kind}
    record.update(fields)
    return record


class EventLog:
    """Append-only JSONL sink.

    ``echo=True`` mirrors each line to stdout (the ``tpu_watch.py``
    behavior — its probe log doubles as live console output).

    ``fsync=True`` is the crash-safe flush mode: the file is held open
    line-buffered and ``os.fsync``'d after every record, so the last
    events before a hang-watchdog SIGKILL survive in the file (the
    doctor's evidence). Without it each append opens/flushes/closes —
    whole lines on disk at every return, but an OS crash may still
    lose the tail.

    ``max_bytes`` (default: ``M4T_TELEMETRY_MAX_MB``; 0 = unbounded)
    rotates the file once an append pushes it past the cap: the live
    file becomes ``<path>.1``, a previous ``.1`` becomes ``.2``, and
    an old ``.2`` is dropped — at most ~3x the cap on disk per sink.

    A ``{rank}`` placeholder in ``path`` is expanded via
    :func:`expand_rank_template` at construction.
    """

    def __init__(
        self,
        path: str,
        *,
        echo: bool = False,
        fsync: bool = False,
        max_bytes: Optional[int] = None,
    ):
        self.path = expand_rank_template(os.fspath(path))
        self.echo = bool(echo)
        self.fsync = bool(fsync)
        if max_bytes is None:
            max_bytes = int(config.TELEMETRY_MAX_MB * (1 << 20))
        self.max_bytes = max(0, int(max_bytes))
        self._lock = threading.Lock()
        self._file = None

    def _rotate_locked(self) -> None:
        """Shift ``path`` -> ``path.1`` -> ``path.2`` (oldest segment
        dropped) and recreate an empty live file. Caller holds the
        lock; the open handle (fsync mode) is closed first so the
        rename moves a complete file. The live path always exists
        after an append — the layout contract directory scanners
        (doctor ``*.jsonl`` glob, the live tailer) rely on."""
        if self._file is not None and not self._file.closed:
            self._file.close()
            self._file = None
        for src, dst in (
            (self.path + ".1", self.path + ".2"),
            (self.path, self.path + ".1"),
        ):
            try:
                os.replace(src, dst)
            except OSError:
                pass  # first rotation has no ".1" yet; never fatal
        try:
            open(self.path, "a").close()
        except OSError:
            pass

    def append(self, record: Dict[str, Any]) -> Dict[str, Any]:
        """Stamp ``ts`` (if absent), append one line, return the
        record as written. Non-JSON-able values fall back to ``str``
        so telemetry can never throw from a repr."""
        rec = dict(record)
        rec.setdefault("ts", utc_stamp())
        line = json.dumps(rec, default=str)
        with self._lock:
            if self.fsync:
                if self._file is None or self._file.closed:
                    # buffering=1: line-buffered, one write per record
                    self._file = open(self.path, "a", buffering=1)
                self._file.write(line + "\n")
                os.fsync(self._file.fileno())
                size = self._file.tell()
            else:
                with open(self.path, "a") as f:
                    f.write(line + "\n")
                    size = f.tell()
            if self.max_bytes and size >= self.max_bytes:
                self._rotate_locked()
        if self.echo:
            print(line, flush=True)
        return rec

    def close(self) -> None:
        with self._lock:
            if self._file is not None and not self._file.closed:
                self._file.close()

    def __repr__(self) -> str:
        return f"EventLog({self.path!r})"


def read(path: str) -> List[Dict[str, Any]]:
    """Load every record of a JSONL file (skipping malformed lines —
    a crashed writer may leave a torn final line), including any
    rotated ``.1``/``.2`` segments, oldest first."""
    return list(iter_records(path))


def segment_paths(path: str) -> List[str]:
    """The on-disk segments of one (possibly rotated) sink, in read
    order: ``path.2`` (oldest), ``path.1``, ``path``. The live file is
    always included even if absent (the caller's open handles the
    OSError); rotated segments only when they exist."""
    out = [p for p in (path + ".2", path + ".1") if os.path.exists(p)]
    out.append(path)
    return out


def iter_records(path: str) -> Iterator[Dict[str, Any]]:
    for segment in segment_paths(path):
        try:
            f = open(segment)
        except OSError:
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    yield rec


# -- module default sink (op-emission telemetry) ----------------------

_sink: Optional[EventLog] = (
    EventLog(config.TELEMETRY_EVENTS, fsync=config.TELEMETRY_FSYNC)
    if config.TELEMETRY_EVENTS
    else None
)
_sink_lock = threading.Lock()


def set_sink(
    path: Optional[str], *, fsync: Optional[bool] = None
) -> Optional[EventLog]:
    """Point the default sink at ``path`` (None disables it).
    ``fsync`` defaults to the ``M4T_TELEMETRY_FSYNC`` setting."""
    global _sink
    with _sink_lock:
        if _sink is not None:
            _sink.close()
        _sink = (
            EventLog(
                path,
                fsync=config.TELEMETRY_FSYNC if fsync is None else fsync,
            )
            if path
            else None
        )
        return _sink


def get_sink() -> Optional[EventLog]:
    return _sink


def emit(record: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Append ``record`` to the default sink, stamping the process
    rank (the doctor's merge key); no-op (returns None) when no sink
    is configured. Never raises: a full disk or revoked path must not
    take down the computation being observed."""
    sink = _sink
    if sink is None:
        return None
    try:
        rec = dict(record)
        rec.setdefault("rank", current_rank())
        return sink.append(rec)
    except OSError:
        return None


# -- heartbeats -------------------------------------------------------
#
# Periodic liveness records through the default sink. The doctor uses
# them to separate "rank is hung inside a collective" (heartbeats
# continue long after its last emission) from "rank died" (heartbeats
# stop with the emissions). bench.py and benchmarks/tpu_watch.py start
# one; any long-running rank can too (M4T_HEARTBEAT=<seconds>).

_heartbeat_stop: Optional[threading.Event] = None
_heartbeat_lock = threading.Lock()


def heartbeat(source: str, **fields: Any) -> Optional[Dict[str, Any]]:
    """Emit one ``heartbeat`` record (no-op without a sink)."""
    return emit(event("heartbeat", source=source, t=time.time(), **fields))


def start_heartbeat(
    interval_s: Optional[float] = None,
    *,
    source: str = "heartbeat",
    **fields: Any,
) -> Callable[[], None]:
    """Start a daemon thread emitting a ``heartbeat`` record every
    ``interval_s`` seconds (default ``M4T_HEARTBEAT``, else 5 s);
    returns a zero-argument stopper. Idempotent: a second call
    replaces the previous thread. A no-op stopper is returned when no
    sink is configured — heartbeats without a sink have no reader.

    Extra ``fields`` are stamped on every beat — the serving pool
    restarts its heartbeat with ``job=<id>`` around each work item so
    a staleness verdict is attributable to the job that wedged the
    worker, not just the worker slot.
    """
    global _heartbeat_stop
    if get_sink() is None:
        return lambda: None
    period = float(interval_s or config.HEARTBEAT_S or 5.0)
    with _heartbeat_lock:
        if _heartbeat_stop is not None:
            _heartbeat_stop.set()
        stop = threading.Event()
        _heartbeat_stop = stop

    def run():
        n = 0
        while not stop.wait(period):
            n += 1
            heartbeat(source, n=n, period_s=period, **fields)

    heartbeat(source, n=0, period_s=period, **fields)
    threading.Thread(
        target=run, name="m4t-heartbeat", daemon=True
    ).start()
    return stop.set


def silence_heartbeat() -> None:
    """Stop the daemon heartbeat thread without starting a
    replacement. Used by the ``wedge`` fault action
    (``resilience/faults.py``) to reproduce the failure shape where
    not even the heartbeat thread makes progress (a process wedged in
    native code holding the GIL): emissions stop *and* heartbeats
    stop, but the process never exits — only an external heartbeat
    deadline (the serving pool doctor's) can name it. Idempotent; a
    later :func:`start_heartbeat` re-arms normally."""
    global _heartbeat_stop
    with _heartbeat_lock:
        if _heartbeat_stop is not None:
            _heartbeat_stop.set()
            _heartbeat_stop = None
