"""Structured JSONL event log — one schema, one sink.

Before this module, three places hand-rolled append-a-JSON-line code:
``benchmarks/tpu_watch.py`` (probe/stage forensics,
``BENCH_r*_probes.jsonl``), the round driver's ``PROGRESS.jsonl``, and
``bench.py``'s stdout metric line. They already agreed on the
essentials — one JSON object per line, a ``ts`` field in UTC
``%Y-%m-%dT%H:%M:%SZ`` — so that is the schema this module pins down:

- every record is a flat-ish JSON object on its own line;
- ``ts`` (UTC second resolution) is stamped at append time if absent;
- a ``kind`` field names the record family (``"emission"``,
  ``"probe"``, ``"stage"``, ``"bench"``, ...) so one file can hold
  mixed streams and still be filtered with one ``json.loads`` loop.

Two layers of API:

- :class:`EventLog` — an explicit append-only JSONL file handle, used
  by the bench drivers (``tpu_watch.py`` probe log, ``bench.py``
  results).
- a module default sink (``M4T_TELEMETRY_EVENTS=<path>`` or
  :func:`set_sink`) that :func:`emit` writes through; the op-emission
  telemetry (``debug.py``) uses this, and it is a no-op when no sink
  is configured.

Writes are line-buffered appends under a lock: concurrent writers
(battery stages in subprocesses append to the same probe log) each
write whole lines, which POSIX appends keep intact.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from .. import config

#: the shared timestamp format (BENCH_r*_probes.jsonl / PROGRESS.jsonl)
TS_FORMAT = "%Y-%m-%dT%H:%M:%SZ"


def utc_stamp(t: Optional[float] = None) -> str:
    """UTC timestamp string in the shared schema format."""
    return time.strftime(TS_FORMAT, time.gmtime(t))


def event(kind: str, **fields: Any) -> Dict[str, Any]:
    """Build a schema-shaped record (``ts`` stamped at append time)."""
    record: Dict[str, Any] = {"kind": kind}
    record.update(fields)
    return record


class EventLog:
    """Append-only JSONL sink.

    ``echo=True`` mirrors each line to stdout (the ``tpu_watch.py``
    behavior — its probe log doubles as live console output).
    """

    def __init__(self, path: str, *, echo: bool = False):
        self.path = os.fspath(path)
        self.echo = bool(echo)
        self._lock = threading.Lock()

    def append(self, record: Dict[str, Any]) -> Dict[str, Any]:
        """Stamp ``ts`` (if absent), append one line, return the
        record as written. Non-JSON-able values fall back to ``str``
        so telemetry can never throw from a repr."""
        rec = dict(record)
        rec.setdefault("ts", utc_stamp())
        line = json.dumps(rec, default=str)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")
        if self.echo:
            print(line, flush=True)
        return rec

    def __repr__(self) -> str:
        return f"EventLog({self.path!r})"


def read(path: str) -> List[Dict[str, Any]]:
    """Load every record of a JSONL file (skipping malformed lines —
    a crashed writer may leave a torn final line)."""
    return list(iter_records(path))


def iter_records(path: str) -> Iterator[Dict[str, Any]]:
    try:
        f = open(path)
    except OSError:
        return
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                yield rec


# -- module default sink (op-emission telemetry) ----------------------

_sink: Optional[EventLog] = (
    EventLog(config.TELEMETRY_EVENTS) if config.TELEMETRY_EVENTS else None
)
_sink_lock = threading.Lock()


def set_sink(path: Optional[str]) -> Optional[EventLog]:
    """Point the default sink at ``path`` (None disables it)."""
    global _sink
    with _sink_lock:
        _sink = EventLog(path) if path else None
        return _sink


def get_sink() -> Optional[EventLog]:
    return _sink


def emit(record: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Append ``record`` to the default sink; no-op (returns None)
    when no sink is configured. Never raises: a full disk or revoked
    path must not take down the computation being observed."""
    sink = _sink
    if sink is None:
        return None
    try:
        return sink.append(record)
    except OSError:
        return None
