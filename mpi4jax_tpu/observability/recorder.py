"""Per-rank flight recorder: the last N collective emissions, always.

SPMD failures are diagnosed from *artifacts*, and the artifact that
matters most — what was this rank about to do when it stopped — is
exactly the one a crashed or killed process never got to write. This
module keeps it in memory the whole time: an always-on, always-cheap
ring buffer of the most recent collective emissions (one small dict
appended per primitive bind, trace-time only — no device work, no
callbacks, no I/O), dumped to JSONL only when something goes wrong.

Each entry carries

- ``seq`` — a per-process monotonic sequence number. Token ordering
  (``token.py``) serializes emissions in program order, so in a
  deadlock-free SPMD program every rank's seq-k entry must describe
  *the same collective*; the cross-rank doctor (:mod:`.doctor`) keys
  its mismatch/hang analysis on it.
- ``op``, ``cid``, payload ``bytes``/``dtype``/``shape``, communicator
  ``axes``/``world`` — the op fingerprint (:func:`fingerprint`)
  compared across ranks at equal seq.
- ``t`` — a ``time.time()`` stamp (when the emission was *traced*).

Dumping is armed by pointing ``M4T_FLIGHT_RECORDER_DIR`` at a
directory (``mpi4jax_tpu.launch --events-dir`` does this for every
rank): :func:`arm` installs atexit / unhandled-exception / signal
hooks that write ``recorder-rank{rank}.jsonl`` there on the way down.
SIGUSR1 dumps without dying (poke a live-but-suspect rank from
outside). The recorder deliberately does not depend on the telemetry
flag: it is the post-mortem layer that survives even when the event
sink was off.

The ring itself stays enabled unless ``M4T_FLIGHT_RECORDER=0``.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from .. import config

#: recorder dump file name pattern inside the armed directory
DUMP_NAME = "recorder-rank{rank}.jsonl"


def fingerprint(record: Dict[str, Any]) -> str:
    """Compact op identity compared across ranks at equal seq:
    ``Op[shape:dtype]@axes``. Collectives whose fingerprints differ at
    the same sequence number have diverged — the SPMD bug class this
    subsystem exists to name."""
    shape = record.get("shape")
    if shape is not None:
        shape_txt = "x".join(str(d) for d in shape) or "scalar"
    elif record.get("bytes"):
        shape_txt = f"{record['bytes']}B"
    else:
        shape_txt = "scalar"
    dtype = record.get("dtype") or "?"
    axes = record.get("axes") or []
    axes_txt = ",".join(str(a) for a in axes) if axes else "<none>"
    return f"{record.get('op', '?')}[{shape_txt}:{dtype}]@{axes_txt}"


class FlightRecorder:
    """Bounded in-memory ring of recent collective emissions."""

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        self._ring: deque = deque(
            maxlen=int(capacity or config.FLIGHT_RECORDER_SIZE)
        )
        self._seq = 0
        self._enabled = bool(config.FLIGHT_RECORDER)
        self._armed_dir: Optional[str] = None
        self._dumped_reason: Optional[str] = None

    # -- recording (the hot path: one lock, one dict, one append) ----

    def record(
        self,
        op: str,
        *,
        cid: str,
        nbytes: int = 0,
        dtype: Optional[str] = None,
        shape: Optional[Sequence[int]] = None,
        axes: Optional[Sequence[str]] = None,
        world: Optional[int] = None,
        impl: Optional[str] = None,
        plan: Optional[str] = None,
        trace: Optional[str] = None,
        job: Optional[str] = None,
    ) -> int:
        """Append one emission; returns its sequence number (0 when
        the recorder is disabled). ``impl``/``plan`` are the planner's
        routing stamp and ``trace``/``job`` the serving plane's
        per-job trace context (only present when armed; none of them
        participate in :func:`fingerprint` — a re-routed or re-traced
        collective is still the *same* collective to the cross-rank
        doctor)."""
        if not self._enabled:
            return 0
        entry = {
            "kind": "recorder",
            "seq": 0,
            "op": op,
            "cid": cid,
            "bytes": int(nbytes),
            "dtype": None if dtype is None else str(dtype),
            "shape": None if shape is None else [int(d) for d in shape],
            "axes": list(axes) if axes else [],
            "world": None if world is None else int(world),
            "t": time.time(),
        }
        if impl is not None:
            entry["impl"] = str(impl)
            if plan is not None:
                entry["plan"] = str(plan)
        if trace is not None:
            entry["trace"] = str(trace)
        if job is not None:
            entry["job"] = str(job)
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._ring.append(entry)
            return self._seq

    # -- reading ------------------------------------------------------

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._ring]

    @property
    def seq(self) -> int:
        return self._seq

    def enabled(self) -> bool:
        return self._enabled

    def enable(self, on: bool = True) -> None:
        self._enabled = bool(on)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._dumped_reason = None

    # -- dumping ------------------------------------------------------

    def dump(
        self, path: Optional[str] = None, *, reason: str = "manual"
    ) -> Optional[str]:
        """Write the ring to ``path`` as JSONL (a ``recorder_meta``
        header line, then one line per entry, oldest first). Returns
        the path written, or None when there was nowhere to write.
        Overwrites: the latest state is the post-mortem truth. Never
        raises — dumping happens on the way down, where a secondary
        failure must not mask the primary one."""
        try:
            from . import events

            rank = events.current_rank()
            if path is None:
                directory = self._armed_dir or config.FLIGHT_RECORDER_DIR
                if not directory:
                    return None
                path = os.path.join(directory, DUMP_NAME.format(rank=rank))
            # best-effort lock: a signal handler must not deadlock on
            # a lock the interrupted thread was holding mid-record
            acquired = self._lock.acquire(timeout=1.0)
            try:
                entries = [dict(r) for r in list(self._ring)]
                last_seq = self._seq
                self._dumped_reason = reason
            finally:
                if acquired:
                    self._lock.release()
            meta = {
                "kind": "recorder_meta",
                "rank": rank,
                "pid": os.getpid(),
                "reason": reason,
                "last_seq": last_seq,
                "entries": len(entries),
                "ts": events.utc_stamp(),
                "t": time.time(),
            }
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(json.dumps(meta, default=str) + "\n")
                for rec in entries:
                    rec.setdefault("rank", rank)
                    f.write(json.dumps(rec, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return path
        except Exception:
            return None

    # -- arming (atexit / crash / signal hooks) -----------------------

    def arm(self, directory: str) -> None:
        """Arm post-mortem dumps into ``directory``: atexit (clean or
        unclean interpreter exit), sys.excepthook (unhandled
        exception, dumped with the exception named), SIGTERM (the
        launcher watchdog's kill — dump, then die with the default
        disposition), and SIGUSR1 (dump and keep running)."""
        os.makedirs(directory, exist_ok=True)
        first = self._armed_dir is None
        self._armed_dir = directory
        if not first:
            return

        atexit.register(self._atexit_dump)

        prev_hook = sys.excepthook

        def hook(exc_type, exc, tb):
            self.dump(reason=f"crash:{exc_type.__name__}")
            prev_hook(exc_type, exc, tb)

        sys.excepthook = hook

        def on_term(signum, frame):
            self.dump(reason=f"signal:{signal.Signals(signum).name}")
            # restore the default disposition and re-deliver so the
            # exit status still says "killed by signal"
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        def on_usr1(signum, frame):
            self.dump(reason="signal:SIGUSR1")

        try:
            if threading.current_thread() is threading.main_thread():
                signal.signal(signal.SIGTERM, on_term)
                signal.signal(signal.SIGUSR1, on_usr1)
        except (ValueError, OSError):  # non-main thread / exotic host
            pass

    def _atexit_dump(self) -> None:
        # A dump that already happened (crash/signal path) is newer
        # truth than the atexit state; keep the reason that killed us.
        if self._dumped_reason is None:
            self.dump(reason="atexit")


#: process-global recorder fed by ops/_core.py's telemetry prologue
recorder = FlightRecorder()

if config.FLIGHT_RECORDER_DIR:
    recorder.arm(config.FLIGHT_RECORDER_DIR)


def record(op: str, **kwargs: Any) -> int:
    """Module-level shorthand for :meth:`FlightRecorder.record`."""
    return recorder.record(op, **kwargs)
