"""Streaming doctor: the offline verdicts, raised while the run lives.

The post-mortem doctor (:mod:`.doctor`) names MISMATCH / HANG /
STRAGGLER from artifacts alone — but only once the world is dead.
This module runs the *same analyses* over a :class:`..live.
LiveAggregator`'s rolling state (the aggregator's ``by_rank`` is
byte-compatible with ``doctor.load`` output, so verdict parity with
the offline doctor holds by construction) and adds the one thing an
offline pass cannot have: **time**.

Confirmation policy (what turns an analysis finding into a verdict):

- ``mismatch`` — confirmed immediately. A divergence on disk is
  deterministic evidence; waiting adds nothing.
- ``hang`` (gap-based or the equal-seq *wedged* tiebreaker) —
  confirmed only after the whole world has made no progress (no new
  emission / exec / latency record from any rank) for ``grace_s``
  seconds. In-flight seq skew is normal; a persistent global stall is
  not. A new record from anyone resets the clock.
- ``straggler`` — confirmed immediately (the offline analysis already
  has a min-samples floor), once per (op, rank).

Every confirmed verdict is appended to the run's ``live.jsonl`` as a
``verdict`` event stamped with the recovery class the resilience
supervisor would assign (``resilience.supervisor.classify_findings``
— transient vs deterministic), and a confirmed hang/mismatch exposes
an ``m4t-doctor/1`` report as :attr:`StreamDoctor.escalation_report`
for the launcher to act on.

The closed loop: confirmed STRAGGLER verdicts and live ``anomaly``
events (the perf watch, ``M4T_PERF_WATCH``) additionally emit
``retune`` recommendation events carrying the affected plan keys::

    {"kind": "retune", "reason": "straggler" | "anomaly",
     "op": "AllReduce", "rank": 1,
     "plan_keys": ["AllReduce|b23|float32|w2|ranks|cpu", ...],
     "detail": {...}, "t": ...}

``planner tune --from-verdicts RUNDIR`` (and ``launch --tune``) feed
those keys through ``autotune.sweep`` so the plan cache is re-pinned
from the evidence — the ROADMAP's "doctor verdicts trigger re-tuning
automatically" loop.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import config
from . import doctor as _doctor
from . import events as _events
from .live import LiveAggregator


def _finding_key(f: Dict[str, Any]) -> Tuple:
    """Stable identity of a finding across re-analyses (the dedupe /
    debounce key)."""
    kind = f.get("kind")
    if kind == "mismatch":
        return (kind, f.get("seq"))
    if kind == "hang":
        return (kind, f.get("rank"), f.get("last_seq"), f.get("verdict"))
    if kind == "missing_rank":
        return (kind, f.get("rank"))
    if kind == "straggler":
        return (kind, f.get("op"), f.get("rank"))
    return (kind, repr(sorted(f.items())))


class StreamDoctor:
    """Incremental verdicts over a live aggregator.

    ``check()`` is the only entry point: poll the aggregator, re-run
    the offline analyses when anything moved, apply the confirmation
    policy, write verdict / retune events. Cheap when idle: no new
    records means no re-analysis — only the stall clock is consulted.
    """

    def __init__(
        self,
        aggregator: LiveAggregator,
        *,
        grace_s: Optional[float] = None,
        hang_gap: int = _doctor.DEFAULT_HANG_GAP,
        straggler_ratio: float = _doctor.DEFAULT_STRAGGLER_RATIO,
        straggler_min_samples: int = _doctor.DEFAULT_STRAGGLER_MIN_SAMPLES,
        verdict_log: Optional[str] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.aggregator = aggregator
        self.grace_s = float(
            config.LIVE_GRACE_S if grace_s is None else grace_s
        )
        self.hang_gap = int(hang_gap)
        self.straggler_ratio = float(straggler_ratio)
        self.straggler_min_samples = int(straggler_min_samples)
        self.clock = clock or aggregator.clock
        self._log = (
            _events.EventLog(verdict_log) if verdict_log else None
        )
        #: confirmed verdict events, in confirmation order
        self.confirmed: List[Dict[str, Any]] = []
        #: the launcher's escalation trigger: an ``m4t-doctor/1``
        #: report containing the confirmed hang/mismatch finding(s)
        self.escalation_report: Optional[Dict[str, Any]] = None
        self._confirmed_keys: set = set()
        self._retuned: set = set()
        self._last_report: Optional[Dict[str, Any]] = None

    # -- verdict/retune event emission --------------------------------

    def _append(self, record: Dict[str, Any]) -> None:
        if self._log is None:
            return
        try:
            self._log.append(record)
        except OSError:
            pass  # the verdict log must never take the monitor down

    def _confirm(self, finding: Dict[str, Any]) -> Dict[str, Any]:
        from ..resilience.supervisor import classify_findings

        verdict = {
            "kind": "verdict",
            "finding": finding,
            "klass": classify_findings([finding])["klass"],
            "t": time.time(),
        }
        self.confirmed.append(verdict)
        self._append(verdict)
        return verdict

    def _plan_keys_for(
        self, op: str, rank: Optional[int]
    ) -> List[str]:
        """Plan keys of the emissions behind a (op, rank) verdict —
        the key set a re-tune should sweep."""
        keys: Dict[str, None] = {}
        ranks = (
            [rank] if rank is not None else sorted(self.aggregator.by_rank)
        )
        for r in ranks:
            for rec in self.aggregator.by_rank.get(r, []):
                if rec.get("kind") not in ("emission", "recorder"):
                    continue
                rec_op = rec.get("op")
                if rec_op == "QuantizedAllReduce":
                    rec_op = "AllReduce"
                if rec_op != op:
                    continue
                key = self.aggregator.plan_key_of(rec)
                if key is not None:
                    keys.setdefault(key)
        return list(keys)

    def _retune(
        self,
        reason: str,
        *,
        op: str,
        rank: Optional[int],
        plan_keys: List[str],
        detail: Dict[str, Any],
    ) -> Optional[Dict[str, Any]]:
        if not plan_keys:
            return None
        dedupe = (reason, op, rank, tuple(sorted(plan_keys)))
        if dedupe in self._retuned:
            return None
        self._retuned.add(dedupe)
        record = {
            "kind": "retune",
            "reason": reason,
            "op": op,
            "rank": rank,
            "plan_keys": plan_keys,
            "detail": detail,
            "t": time.time(),
        }
        self._append(record)
        return record

    # -- the check loop -----------------------------------------------

    def _analyze(self) -> Dict[str, Any]:
        return _doctor.analyze(
            self.aggregator.by_rank,
            hang_gap=self.hang_gap,
            straggler_ratio=self.straggler_ratio,
            straggler_min_samples=self.straggler_min_samples,
        )

    def check(self, *, final: bool = False) -> Optional[Dict[str, Any]]:
        """One monitor tick: poll, analyze, confirm. Returns the
        latest analysis report (None before any records). ``final``
        marks the post-teardown pass: the world is dead, so hang
        findings no longer wait out the grace (there is no more
        progress to wait for)."""
        moved = self.aggregator.poll()
        if not self.aggregator.by_rank:
            return None
        if moved or self._last_report is None:
            self._last_report = self._analyze()
        report = self._last_report

        stalled = self.aggregator.stalled_s()
        stall_confirmed = final or (
            stalled is not None and stalled >= self.grace_s
        )
        escalate: List[Dict[str, Any]] = []
        for f in report.get("findings", []):
            key = _finding_key(f)
            kind = f.get("kind")
            if kind in ("hang", "missing_rank") and not stall_confirmed:
                continue  # transient skew until the world truly stalls
            if key not in self._confirmed_keys:
                self._confirmed_keys.add(key)
                self._confirm(f)
                if kind == "straggler":
                    self._retune(
                        "straggler",
                        op=f.get("op", "?"),
                        rank=f.get("rank"),
                        plan_keys=self._plan_keys_for(
                            f.get("op", "?"), f.get("rank")
                        ),
                        detail={
                            k: f.get(k)
                            for k in ("ratio", "mean_s", "peer_median_s",
                                      "samples")
                        },
                    )
            if kind in ("mismatch", "hang"):
                escalate.append(f)

        # live anomaly events (perf watch) -> retune recommendations
        for rec in self.aggregator.drain_anomalies():
            op = rec.get("op")
            if not op:
                continue
            key = (
                self.aggregator.plan_key_of(dict(rec, kind="emission"))
                if rec.get("bytes") is not None
                else None
            )
            self._retune(
                "anomaly",
                op=str(op),
                rank=rec.get("rank"),
                plan_keys=(
                    [key] if key is not None
                    else self._plan_keys_for(str(op), rec.get("rank"))
                ),
                detail={
                    k: rec.get(k)
                    for k in ("key", "seconds", "baseline_s", "z")
                },
            )

        if escalate and self.escalation_report is None:
            self.escalation_report = dict(report, findings=escalate)
        return report

    def format_escalation(self) -> str:
        """Human-readable streaming diagnosis (the launcher prints
        this when it tears a confirmed-hung world down)."""
        if self.escalation_report is None:
            return "stream doctor: no confirmed verdict"
        return _doctor.format_report(self.escalation_report)


def watch_directory(
    rundir: str,
    *,
    grace_s: Optional[float] = None,
    platform: Optional[str] = None,
    verdict_log: Optional[str] = None,
) -> StreamDoctor:
    """Convenience constructor: a stream doctor over a fresh
    aggregator for ``rundir`` (offline harnesses, tests)."""
    agg = LiveAggregator(rundir, platform=platform)
    if verdict_log is None:
        verdict_log = os.path.join(rundir, "live.jsonl")
    return StreamDoctor(agg, grace_s=grace_s, verdict_log=verdict_log)
