"""Per-op comm metrics registry (trace-time counters + runtime samples).

The reference's only telemetry is the per-call ``DebugTimer`` log line
(``mpi_ops_common.h:154-206``) — unstructured text that cannot answer
"how many bytes moved per collective, per mesh axis, per step". This
registry is the structured replacement: every op emission
(``ops/_core.py:emit`` / ``emit_shm``) records

- op name, payload bytes, dtype, communicator mesh axes, world size,
- the emission correlation id (shared with the ``debug.py`` log line
  and the ``m4t.<op>`` profiler annotation),

and, when runtime sampling is enabled
(``M4T_TELEMETRY_RUNTIME``), per-execution latency samples captured
through ``jax.debug.callback`` pairs land in a fixed-size reservoir so
memory and report cost stay bounded no matter how long the program
runs.

Everything in this module is plain host-side Python: recording happens
at trace time (one dict update per ``bind``) or inside host callbacks,
never on the device. The whole layer is inert unless enabled
(``M4T_TELEMETRY=1`` or :func:`enable`): the op layer checks
:func:`enabled` before doing any telemetry work, so the disabled path
adds a single attribute read per emission and zero runtime callbacks.

Usage::

    from mpi4jax_tpu import observability as obs

    obs.enable()                  # or M4T_TELEMETRY=1
    ... run jitted collectives ...
    snap = obs.snapshot()         # plain-JSON dict
    print(obs.report())           # pretty per-op table
    obs.reset()
"""

from __future__ import annotations

import io
import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from .. import config

#: how many of the most recent per-emission records are retained for
#: correlation (cid <-> op <-> annotation); counters are exact forever,
#: this ring only bounds the per-record detail
EMISSION_RING = 1024


class Reservoir:
    """Fixed-capacity uniform sample of a float stream (Vitter's
    algorithm R). Exact count/sum/min/max over the full stream;
    quantiles are estimated from the reservoir."""

    __slots__ = ("capacity", "count", "total", "minimum", "maximum", "samples")

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self.count = 0
        self.total = 0.0
        self.minimum: Optional[float] = None
        self.maximum: Optional[float] = None
        self.samples: List[float] = []

    def add(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.total += value
        self.minimum = value if self.minimum is None else min(self.minimum, value)
        self.maximum = value if self.maximum is None else max(self.maximum, value)
        if len(self.samples) < self.capacity:
            self.samples.append(value)
        else:
            j = random.randrange(self.count)
            if j < self.capacity:
                self.samples[j] = value

    def quantile(self, q: float) -> Optional[float]:
        if not self.samples:
            return None
        ordered = sorted(self.samples)
        idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
        return ordered[idx]

    def summary(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "mean": (self.total / self.count) if self.count else None,
            "min": self.minimum,
            "max": self.maximum,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
        }


class OpMetrics:
    """Counters for a single op name (e.g. ``AllReduce``)."""

    __slots__ = (
        "op",
        "emissions",
        "payload_bytes",
        "by_dtype",
        "by_axes",
        "last_cid",
        "seq",
        "latency",
    )

    def __init__(self, op: str, reservoir: int):
        self.op = op
        self.emissions = 0
        self.payload_bytes = 0
        #: dtype str -> [emission count, payload bytes]
        self.by_dtype: Dict[str, List[int]] = {}
        #: mesh-axes key ("dp,tp" / "<none>") -> emission count
        self.by_axes: Dict[str, int] = {}
        self.last_cid = ""
        #: per-op monotonic emission sequence number (1-based; the
        #: doctor's per-op alignment key, zeroed by reset())
        self.seq = 0
        self.latency = Reservoir(reservoir)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "op": self.op,
            "emissions": self.emissions,
            "payload_bytes": self.payload_bytes,
            "by_dtype": {k: list(v) for k, v in self.by_dtype.items()},
            "by_axes": dict(self.by_axes),
            "last_cid": self.last_cid,
            "seq": self.seq,
            "latency_s": self.latency.summary(),
        }


def _axes_key(axes: Optional[Sequence[str]]) -> str:
    if not axes:
        return "<none>"
    return ",".join(str(a) for a in axes)


class MetricsRegistry:
    """Thread-safe accumulator for every op emission and runtime sample.

    One process-global instance (:data:`registry`) backs the module-
    level helpers; independent instances are constructible for tests.
    """

    def __init__(self, reservoir: Optional[int] = None):
        self._lock = threading.Lock()
        self._reservoir = int(reservoir or config.TELEMETRY_RESERVOIR)
        self._ops: Dict[str, OpMetrics] = {}
        self._emissions: deque = deque(maxlen=EMISSION_RING)
        #: global monotonic emission counter across all ops (the
        #: cross-rank alignment key: rank A's k-th emission must match
        #: rank B's k-th in deadlock-free SPMD programs)
        self._seq = 0
        #: cid -> host-clock start mark for in-flight runtime samples
        self._inflight: Dict[str, float] = {}
        #: cid -> emission record, bounded alongside the emission
        #: ring, so runtime latency samples inherit their emission's
        #: alignment key (seq) in the event stream and the perf watch
        #: can key its baseline by the full fingerprint
        self._cid_rec: Dict[str, Dict[str, Any]] = {}
        self._created = time.time()

    # -- recording ---------------------------------------------------

    def record_emission(
        self,
        op: str,
        *,
        nbytes: int,
        dtype: Optional[str],
        axes: Optional[Sequence[str]],
        world: Optional[int],
        cid: str,
        annotation: Optional[str] = None,
        shape: Optional[Sequence[int]] = None,
        impl: Optional[str] = None,
        plan: Optional[str] = None,
        trace: Optional[str] = None,
        job: Optional[str] = None,
        step: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Count one trace-time op emission; returns the record stored
        in the emission ring (shared schema with the JSONL event log).

        The record carries two monotonic sequence numbers: ``seq``
        (global across ops — the doctor's cross-rank alignment key)
        and ``op_seq`` (per op, also exposed as ``snapshot()['ops']
        [op]['seq']``); both restart from 1 after :meth:`reset`.
        ``impl``/``plan`` (the planner's routing stamp) and
        ``trace``/``job`` (the serving plane's per-job trace context,
        ``M4T_TRACE_ID``/``M4T_JOB_ID``) and ``step`` (the overlap
        observatory's step context, ``M4T_STEP_SPAN``) are recorded
        only when given — unarmed emissions stay schema-identical to
        pre-planner / pre-tracing / pre-overlap records.
        """
        record = {
            "kind": "emission",
            "cid": cid,
            "op": op,
            "bytes": int(nbytes),
            "dtype": None if dtype is None else str(dtype),
            "axes": list(axes) if axes else [],
            "world": None if world is None else int(world),
            "annotation": annotation,
            "shape": None if shape is None else [int(d) for d in shape],
            "t": time.time(),
        }
        if impl is not None:
            record["impl"] = str(impl)
            if plan is not None:
                record["plan"] = str(plan)
        if trace is not None:
            record["trace"] = str(trace)
        if job is not None:
            record["job"] = str(job)
        if step is not None:
            record["step"] = int(step)
        key = _axes_key(axes)
        with self._lock:
            m = self._ops.get(op)
            if m is None:
                m = self._ops[op] = OpMetrics(op, self._reservoir)
            m.emissions += 1
            m.payload_bytes += int(nbytes)
            per_dtype = m.by_dtype.setdefault(record["dtype"] or "<none>", [0, 0])
            per_dtype[0] += 1
            per_dtype[1] += int(nbytes)
            m.by_axes[key] = m.by_axes.get(key, 0) + 1
            m.last_cid = cid
            m.seq += 1
            self._seq += 1
            record["seq"] = self._seq
            record["op_seq"] = m.seq
            if len(self._emissions) == self._emissions.maxlen:
                evicted = self._emissions[0]
                self._cid_rec.pop(evicted["cid"], None)
            self._cid_rec[cid] = record
            self._emissions.append(record)
        return record

    def mark_runtime_start(self, cid: str) -> None:
        """Host-callback hook: an op with correlation id ``cid`` began
        executing (first callback of the pair).

        When a sink is configured, the start is also mirrored as an
        ``exec`` event carrying the emission's alignment key (``seq``).
        This is the doctor's *wedge* evidence: a rank whose last
        emission has no matching ``exec`` record, while a peer's does,
        stalled between tracing a collective and executing it — the
        hang signature no seq gap can show (both ranks record the
        emission; only one enters the collective)."""
        with self._lock:
            self._inflight[cid] = time.perf_counter()
            rec = self._cid_rec.get(cid)
        from . import events, overlap

        if events.get_sink() is not None:
            exec_rec = {
                "kind": "exec",
                "cid": cid,
                "op": rec["op"] if rec else None,
                "seq": rec["seq"] if rec else None,
                "t": time.time(),
            }
            # trace context is inherited from the emission record so
            # exec/latency rows join the same per-job trace; absent
            # (unarmed) the schema is byte-identical to before
            if rec and rec.get("trace") is not None:
                exec_rec["trace"] = rec["trace"]
            if rec and rec.get("job") is not None:
                exec_rec["job"] = rec["job"]
            # the step open *now* (callback time, not trace time):
            # an emission traced once at step 0 executes every step,
            # and this stamp is what attributes each execution
            step = overlap.current_step()
            if step is not None:
                exec_rec["step"] = step
            events.emit(exec_rec)

    def mark_runtime_end(self, cid: str, op: str) -> Optional[float]:
        """Host-callback hook: the op finished; records the latency
        sample and returns it (None when the start mark is missing or
        the callbacks arrived out of order). The sample is mirrored as
        a ``latency`` event through the default sink (no-op without
        one) so the doctor can see per-rank runtime behavior —
        straggler detection — from the log files alone. The sample
        also feeds the live perf anomaly watch (inert unless
        ``M4T_PERF_WATCH``), keyed by the emission's fingerprint."""
        now = time.perf_counter()
        with self._lock:
            start = self._inflight.pop(cid, None)
            if start is None or now < start:
                return None
            sample = now - start
            m = self._ops.get(op)
            if m is None:
                m = self._ops[op] = OpMetrics(op, self._reservoir)
            m.latency.add(sample)
            rec = self._cid_rec.get(cid)
        from . import events, overlap, perf

        lat_rec = {
            "kind": "latency",
            "cid": cid,
            "op": op,
            "seq": rec["seq"] if rec else None,
            "seconds": sample,
            "t": time.time(),
        }
        if rec and rec.get("trace") is not None:
            lat_rec["trace"] = rec["trace"]
        if rec and rec.get("job") is not None:
            lat_rec["job"] = rec["job"]
        step = overlap.current_step()
        if step is not None:
            lat_rec["step"] = step
        events.emit(lat_rec)
        perf.observe_runtime(op, sample, record=rec, cid=cid)
        return sample

    def record_latency(self, op: str, seconds: float) -> None:
        """Direct latency sample (bench drivers measuring externally)."""
        with self._lock:
            m = self._ops.get(op)
            if m is None:
                m = self._ops[op] = OpMetrics(op, self._reservoir)
            m.latency.add(seconds)

    def latency_samples(self) -> Dict[str, List[float]]:
        """Per-op copies of the latency reservoirs (the attribution
        join input for :func:`..perf.perf_report`)."""
        with self._lock:
            return {
                op: list(m.latency.samples)
                for op, m in self._ops.items()
                if m.latency.count
            }

    # -- reading -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Plain-JSON state: per-op counters plus the emission ring."""
        with self._lock:
            return {
                "since": self._created,
                "ops": {name: m.as_dict() for name, m in self._ops.items()},
                "emissions": [dict(r) for r in self._emissions],
                "totals": {
                    "emissions": sum(m.emissions for m in self._ops.values()),
                    "payload_bytes": sum(
                        m.payload_bytes for m in self._ops.values()
                    ),
                    "seq": self._seq,
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._ops.clear()
            self._emissions.clear()
            self._inflight.clear()
            self._cid_rec.clear()
            self._seq = 0
            self._created = time.time()

    def report(self, file=None) -> str:
        """Human-readable per-op table; returns the string (and writes
        it to ``file`` when given)."""
        snap = self.snapshot()
        out = io.StringIO()
        ops = sorted(snap["ops"].values(), key=lambda m: -m["payload_bytes"])
        out.write(
            f"comm telemetry: {snap['totals']['emissions']} emissions, "
            f"{_fmt_bytes(snap['totals']['payload_bytes'])} total payload\n"
        )
        if ops:
            out.write(
                f"{'op':<16} {'emits':>6} {'payload':>10} "
                f"{'dtypes':<18} {'axes':<14} {'lat p50/p99':>16}\n"
            )
        for m in ops:
            lat = m["latency_s"]
            lat_txt = (
                f"{_fmt_s(lat['p50'])}/{_fmt_s(lat['p99'])}"
                if lat["count"]
                else "-"
            )
            out.write(
                f"{m['op']:<16} {m['emissions']:>6} "
                f"{_fmt_bytes(m['payload_bytes']):>10} "
                f"{','.join(m['by_dtype']):<18} "
                f"{';'.join(m['by_axes']):<14} {lat_txt:>16}\n"
            )
        text = out.getvalue()
        if file is not None:
            file.write(text)
        return text


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def _fmt_s(s: Optional[float]) -> str:
    if s is None:
        return "-"
    if s < 1e-3:
        return f"{s * 1e6:.0f}us"
    if s < 1:
        return f"{s * 1e3:.1f}ms"
    return f"{s:.2f}s"


#: process-global registry backing the module-level API
registry = MetricsRegistry()

#: dynamic on/off switch, seeded from M4T_TELEMETRY
_enabled = bool(config.TELEMETRY)
_runtime_enabled = bool(config.TELEMETRY_RUNTIME)


def enabled() -> bool:
    """Is the telemetry layer on? The single gate every op-emission
    call site checks before doing any telemetry work."""
    return _enabled


def runtime_enabled() -> bool:
    """Are runtime latency callbacks requested (implies :func:`enabled`)?"""
    return _enabled and _runtime_enabled


def enable(*, runtime: Optional[bool] = None) -> None:
    """Turn the telemetry registry on at runtime (analog of
    ``set_logging``). ``runtime=True`` additionally samples per-op
    device latency via host callbacks in subsequently traced programs."""
    global _enabled, _runtime_enabled
    _enabled = True
    if runtime is not None:
        _runtime_enabled = bool(runtime)


def disable() -> None:
    global _enabled
    _enabled = False


def snapshot() -> Dict[str, Any]:
    return registry.snapshot()


def reset() -> None:
    registry.reset()


def report(file=None) -> str:
    return registry.report(file=file)
