"""Topology observatory: measured link maps, per-link attribution,
and link-localized straggler diagnosis.

Every other layer of the perf stack prices every link at one uniform
``M4T_PEAK_GBPS`` (:mod:`.costmodel`), but real meshes are
heterogeneous — the Cloud Collectives observation (arXiv:2105.14088)
is that rank-reordering and algorithm-selection wins come precisely
from *measuring* which links are slow. This module is the data plane
that measurement rides on:

1. **Active probe harness** — pairwise and ring ``sendrecv`` sweeps
   at a few payload sizes over :class:`..comm.CartComm` edges, run
   inside a launched world (``launch --probe-topology`` spawns a
   short probe world before the workload; the elastic supervisor
   re-probes after a shrink). Each rank times its own sweeps, writes
   a partial ``topo-rank{k}.json``, and rank 0 merges the partials
   into ``DIR/topology.json``: a versioned ``m4t-topo/1`` map with
   per-rank host/device_kind and directed edges carrying a fitted
   per-link alpha/beta (least squares over ``t = alpha +
   nbytes / (beta * 1e9)``) plus sweep provenance.

2. **Per-link attribution** — :func:`attribute_links` joins cid-keyed
   runtime latency records with the cost model's directed-edge
   decomposition (:func:`..costmodel.edge_phases` — ring/tree/
   pairwise built-ins plus PR 15's proven ``algo:*`` round schedules)
   to compute achieved GB/s *per link*. The doctor consumes the map
   to classify a confirmed straggler as ``rank-bound`` vs
   ``link-bound`` (:func:`classify_rank`, joined in
   ``doctor.attach_link_classification``), the exporter publishes
   ``m4t_topo_link_gbps{src=,dst=}`` gauges, and the Perfetto export
   grows a per-link counter track.

3. **Planner consumption** — ``planner tune --topo TOPO.json``
   replaces the uniform-peak analytic seed with the map's per-edge
   betas (``costmodel.expected_time_topo``), so a skewed topology can
   flip impl choices (e.g. flat ring -> hierarchical when a flat
   ring's wrap link is slow); pinned by ``tests/test_topology.py``.

A collective synchronizes its ranks, so attributed per-link GB/s from
collective latency is a *lower bound* shaped by the slowest
participant — the probe map is the authoritative per-link truth, and
attribution is the "what did this run actually see" overlay.

Map schema (``m4t-topo/1``)::

    {"schema": "m4t-topo/1",
     "world": 4,
     "platform": "cpu",
     "ranks": {"0": {"host": "node-a", "device_kind": "cpu"}},
     "edges": {"0->1": {"alpha_s": 2.1e-06, "beta_gbps": 18.7,
                        "samples": 9, "payloads": [4096, 65536, 1048576],
                        "provenance": "probe:ring+pairwise"}},
     "provenance": {"method": "sendrecv-sweep", "source": "probe",
                    "payloads": [...], "repeats": 3}}

Import-light on purpose (stdlib + costmodel): the report/diff/selftest
CLI and every offline consumer run without jax. The probe entry —
and only the probe entry — imports the op layer lazily.

CLI::

    python -m mpi4jax_tpu.observability.topology probe --out DIR
        [--payloads 4096,65536,1048576] [--repeats 3]
        [--synthetic SPEC --world N]       # device-free map synthesis
    python -m mpi4jax_tpu.observability.topology report TOPO.json
        [RUNDIR] [--prom OUT.prom]
    python -m mpi4jax_tpu.observability.topology diff A.json B.json
    python -m mpi4jax_tpu.observability.topology --selftest
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from . import costmodel

#: topology-map schema tag; bump on any incompatible layout change
SCHEMA = "m4t-topo/1"

#: payload sizes the sweep times, bytes (small / medium / large: the
#: small size anchors alpha, the large one anchors beta)
DEFAULT_PAYLOADS = (1 << 12, 1 << 16, 1 << 20)

#: timed repetitions per (edge, payload) after one untimed warmup
DEFAULT_REPEATS = 3

#: a link is "slow" when its fitted beta is below this fraction of
#: the fleet-median beta (the doctor's link-bound threshold)
SLOW_LINK_FACTOR = 0.5

Edge = Tuple[int, int]


def edge_key(src: int, dst: int) -> str:
    """The JSON key of one directed edge: ``"src->dst"``."""
    return f"{int(src)}->{int(dst)}"


def parse_edge(key: str) -> Edge:
    src, _, dst = str(key).partition("->")
    return (int(src), int(dst))


# ---------------------------------------------------------------------
# alpha/beta fitting
# ---------------------------------------------------------------------


def fit_alpha_beta(
    samples: Sequence[Tuple[int, float]],
) -> Tuple[float, float]:
    """Least-squares fit of ``t = alpha + nbytes / (beta * 1e9)`` over
    ``(nbytes, seconds)`` samples; returns ``(alpha_s, beta_gbps)``.

    Degenerate inputs degrade instead of crashing: with a single
    payload size (or a non-physical negative slope from timing noise)
    alpha is pinned at 0 and beta falls back to the mean measured
    throughput — finite and positive whenever any sample moved bytes
    in nonzero time."""
    pts = [
        (float(n), float(t))
        for n, t in samples
        if t > 0 and n >= 0
    ]
    if not pts:
        raise ValueError("fit_alpha_beta: no usable samples")
    n = len(pts)
    mean_x = sum(p[0] for p in pts) / n
    mean_y = sum(p[1] for p in pts) / n
    sxx = sum((p[0] - mean_x) ** 2 for p in pts)
    sxy = sum((p[0] - mean_x) * (p[1] - mean_y) for p in pts)
    slope = sxy / sxx if sxx > 0 else 0.0
    alpha = mean_y - slope * mean_x
    if slope > 0:
        return (max(0.0, alpha), 1.0 / (slope * 1e9))
    # single payload size / noise-dominated: mean throughput, no alpha
    thru = [p[0] / p[1] for p in pts if p[0] > 0]
    if not thru:
        # pure-latency samples (zero-byte payloads): all alpha
        return (mean_y, costmodel.DEFAULT_PEAK_GBPS)
    return (0.0, (sum(thru) / len(thru)) / 1e9)


# ---------------------------------------------------------------------
# synthetic link models (device-free probe backend)
# ---------------------------------------------------------------------


class SyntheticLinkModel:
    """An injectable per-edge alpha/beta model: the device-free probe
    backend the selftest and the test matrix sweep against (and the
    seam a simulator could implement). ``links`` overrides the default
    per directed edge: ``{(src, dst): {"alpha_s": ..,
    "beta_gbps": ..}}`` (either field optional)."""

    def __init__(
        self,
        world: int,
        *,
        alpha_s: float = 2e-6,
        beta_gbps: float = 20.0,
        links: Optional[Dict[Edge, Dict[str, float]]] = None,
    ):
        if int(world) < 2:
            raise ValueError("SyntheticLinkModel needs world >= 2")
        self.world = int(world)
        self.alpha_s = float(alpha_s)
        self.beta_gbps = float(beta_gbps)
        self.links = {
            (int(s), int(d)): dict(v) for (s, d), v in (links or {}).items()
        }

    def params(self, src: int, dst: int) -> Tuple[float, float]:
        over = self.links.get((int(src), int(dst)), {})
        return (
            float(over.get("alpha_s", self.alpha_s)),
            float(over.get("beta_gbps", self.beta_gbps)),
        )

    def time_s(self, src: int, dst: int, nbytes: int) -> float:
        alpha, beta = self.params(src, dst)
        return alpha + max(0, int(nbytes)) / (beta * 1e9)

    def samples(
        self,
        *,
        payloads: Sequence[int] = DEFAULT_PAYLOADS,
        repeats: int = DEFAULT_REPEATS,
    ) -> Dict[Edge, List[Tuple[int, float]]]:
        """Deterministic sweep transcript over every directed edge
        (what the real probe would have measured under this model)."""
        out: Dict[Edge, List[Tuple[int, float]]] = {}
        for src in range(self.world):
            for dst in range(self.world):
                if src == dst:
                    continue
                rows = []
                for nbytes in payloads:
                    for _ in range(max(1, int(repeats))):
                        rows.append((int(nbytes), self.time_s(src, dst, nbytes)))
                out[(src, dst)] = rows
        return out


def parse_synthetic_spec(spec: str, *, world: int) -> SyntheticLinkModel:
    """Build a :class:`SyntheticLinkModel` from a compact CLI spec:
    ``"beta=20,alpha_us=2,2->3=1.5,3->2=1.5"`` — a default beta
    (GB/s), a default alpha (us), and per-edge beta overrides."""
    alpha_s, beta, links = 2e-6, 20.0, {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition("=")
        if not value:
            raise ValueError(f"--synthetic: malformed entry {part!r}")
        if key == "beta":
            beta = float(value)
        elif key == "alpha_us":
            alpha_s = float(value) * 1e-6
        elif "->" in key:
            links[parse_edge(key)] = {"beta_gbps": float(value)}
        else:
            raise ValueError(f"--synthetic: unknown field {key!r}")
    return SyntheticLinkModel(world, alpha_s=alpha_s, beta_gbps=beta, links=links)


# ---------------------------------------------------------------------
# map construction / persistence
# ---------------------------------------------------------------------


def build_map(
    world: int,
    samples_by_edge: Dict[Edge, List[Tuple[int, float]]],
    *,
    ranks: Optional[Dict[int, Dict[str, Any]]] = None,
    platform: str = "cpu",
    provenance: Optional[Dict[str, Any]] = None,
    edge_provenance: str = "probe:ring+pairwise",
) -> Dict[str, Any]:
    """Fit every edge's sweep transcript and assemble the versioned
    ``m4t-topo/1`` document."""
    edges: Dict[str, Any] = {}
    for (src, dst), samples in sorted(samples_by_edge.items()):
        if not samples:
            continue
        alpha, beta = fit_alpha_beta(samples)
        edges[edge_key(src, dst)] = {
            "alpha_s": alpha,
            "beta_gbps": beta,
            "samples": len(samples),
            "payloads": sorted({int(n) for n, _ in samples}),
            "provenance": edge_provenance,
        }
    rank_meta = {
        str(r): {
            "host": str((ranks or {}).get(r, {}).get("host", "")),
            "device_kind": str(
                (ranks or {}).get(r, {}).get("device_kind", platform)
            ),
        }
        for r in range(int(world))
    }
    return {
        "schema": SCHEMA,
        "world": int(world),
        "platform": platform,
        "ranks": rank_meta,
        "edges": edges,
        "provenance": dict(provenance or {"method": "sendrecv-sweep",
                                          "source": "probe"}),
    }


def synthetic_map(
    model: SyntheticLinkModel,
    *,
    payloads: Sequence[int] = DEFAULT_PAYLOADS,
    repeats: int = DEFAULT_REPEATS,
    platform: str = "cpu",
) -> Dict[str, Any]:
    """Probe a synthetic link model device-free into a full map."""
    return build_map(
        model.world,
        model.samples(payloads=payloads, repeats=repeats),
        platform=platform,
        provenance={
            "method": "sendrecv-sweep",
            "source": "synthetic",
            "payloads": [int(p) for p in payloads],
            "repeats": int(repeats),
        },
        edge_provenance="synthetic",
    )


def validate(topo: Any) -> Dict[str, Any]:
    """Schema-check one loaded document; raises ``ValueError`` on
    anything that must not be trusted as a topology map."""
    if not isinstance(topo, dict) or topo.get("schema") != SCHEMA:
        got = topo.get("schema") if isinstance(topo, dict) else type(topo).__name__
        raise ValueError(f"expected a {SCHEMA!r} map (got {got!r})")
    world = topo.get("world")
    if not isinstance(world, int) or world < 1:
        raise ValueError(f"{SCHEMA}: bad world {world!r}")
    for key, edge in (topo.get("edges") or {}).items():
        src, dst = parse_edge(key)  # raises on malformed keys
        if not (0 <= src < world and 0 <= dst < world and src != dst):
            raise ValueError(f"{SCHEMA}: edge {key!r} outside world {world}")
        beta = edge.get("beta_gbps")
        if not isinstance(beta, (int, float)) or beta <= 0:
            raise ValueError(f"{SCHEMA}: edge {key!r} has no positive beta")
    return topo


def save(path: str, topo: Dict[str, Any]) -> str:
    """Atomic write (tmp + rename, the repo's commit idiom)."""
    validate(topo)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".topo-", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(topo, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return validate(json.load(f))


#: the per-run map filename ``launch --probe-topology`` persists and
#: the doctor auto-detects beside its inputs
MAP_BASENAME = "topology.json"


def find(inputs: Iterable[str]) -> Optional[Dict[str, Any]]:
    """Auto-detect a persisted map beside run artifacts: the first
    readable ``topology.json`` in (or next to) the given inputs. The
    parent directory is consulted too — a supervised run probes into
    the run root while the doctor reads per-attempt subdirectories."""
    for item in inputs:
        base = item if os.path.isdir(item) else (
            os.path.dirname(item) or "."
        )
        for d in (base, os.path.dirname(os.path.abspath(base))):
            candidate = os.path.join(d, MAP_BASENAME)
            if os.path.isfile(candidate):
                try:
                    return load(candidate)
                except (OSError, ValueError, json.JSONDecodeError):
                    continue
    return None


# ---------------------------------------------------------------------
# map queries
# ---------------------------------------------------------------------


def edge_betas(topo: Dict[str, Any]) -> Dict[Edge, float]:
    """``{(src, dst): beta_gbps}`` — the shape
    ``costmodel.expected_time_topo`` and the autotune sweep consume.
    Entries without a positive numeric beta (a partial probe that
    failed some edges) are skipped, not a KeyError: consumers treat an
    absent edge as unmeasured."""
    out: Dict[Edge, float] = {}
    for k, v in (topo.get("edges") or {}).items():
        beta = (v or {}).get("beta_gbps")
        if isinstance(beta, (int, float)) and beta > 0:
            out[parse_edge(k)] = float(beta)
    return out


def fleet_median_beta(topo: Dict[str, Any]) -> Optional[float]:
    betas = sorted(edge_betas(topo).values())
    return statistics.median(betas) if betas else None


def slow_links(
    topo: Dict[str, Any], *, factor: float = SLOW_LINK_FACTOR
) -> List[Dict[str, Any]]:
    """Directed edges whose fitted beta is below ``factor`` x the
    fleet median, slowest first."""
    median = fleet_median_beta(topo)
    if not median:
        return []
    out = []
    for (src, dst), beta in sorted(edge_betas(topo).items()):
        if beta < factor * median:
            out.append({
                "edge": edge_key(src, dst),
                "src": src,
                "dst": dst,
                "beta_gbps": beta,
                "fleet_median_gbps": median,
                "ratio": beta / median,
            })
    out.sort(key=lambda r: r["beta_gbps"])
    return out


def classify_rank(
    topo: Dict[str, Any], rank: int, *, factor: float = SLOW_LINK_FACTOR
) -> Optional[Dict[str, Any]]:
    """Is a straggling rank's slowness explained by one of its links?

    Looks at every measured edge incident to ``rank`` (both
    directions): if the slowest one sits below ``factor`` x the
    fleet-median beta the verdict is ``link-bound`` (naming the
    directed edge and its measured-vs-fleet-median beta), else
    ``rank-bound`` (its links look like everyone else's — the rank
    itself is slow). ``None`` when the map has no edges at this
    rank."""
    median = fleet_median_beta(topo)
    if not median:
        return None
    rank = int(rank)
    incident = [
        (beta, (src, dst))
        for (src, dst), beta in sorted(edge_betas(topo).items())
        if rank in (src, dst)
    ]
    if not incident:
        return None
    beta, (src, dst) = min(incident)
    result = {
        "fleet_median_gbps": median,
        "slowest_edge": edge_key(src, dst),
        "slowest_edge_gbps": beta,
        "ratio": beta / median,
        "factor": float(factor),
    }
    result["klass"] = "link-bound" if beta < factor * median else "rank-bound"
    return result


# ---------------------------------------------------------------------
# per-link attribution (measured achieved GB/s per directed edge)
# ---------------------------------------------------------------------


def attribute_links(
    by_rank: Dict[int, List[Dict[str, Any]]],
    *,
    topo: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Join cid-keyed runtime latency samples against the cost model's
    directed-edge decomposition: for each latency sample a rank
    recorded, the bytes its *outgoing* edges carried during that
    collective (``costmodel.edge_phases`` — ring/tree/pairwise
    built-ins and proven ``algo:*`` round schedules) divided by the
    measured seconds give that link's achieved GB/s for the sample.

    Returns ``{"links": {"src->dst": {"src", "dst", "samples",
    "gbps_p50", "bytes"}}}``, with ``"beta_gbps"``/``"vs_probe"``
    joined in when a probe map is given. A decomposed edge the probe
    map does not cover (partial probe, shrunk world, failed fit) is a
    warned skip counted in ``"missing_edges"`` — never a KeyError.
    ``by_rank`` is the ``doctor.load`` shape."""
    from . import doctor

    per_edge: Dict[Edge, List[float]] = {}
    bytes_edge: Dict[Edge, int] = {}
    for rank in sorted(by_rank):
        emissions: Dict[str, Dict[str, Any]] = {}
        for rec in doctor.collective_stream(by_rank[rank]):
            if rec.get("cid"):
                emissions.setdefault(rec["cid"], rec)
        for rec in by_rank[rank]:
            if rec.get("kind") != "latency":
                continue
            seconds = rec.get("seconds")
            if not isinstance(seconds, (int, float)) or seconds <= 0:
                continue
            emission = emissions.get(rec.get("cid") or "")
            if emission is None:
                continue
            phases = costmodel.record_edge_phases(emission)
            if not phases:
                continue
            outgoing: Dict[Edge, int] = {}
            for phase in phases:
                for (src, dst) in phase["edges"]:
                    if src == rank:
                        e = (src, dst)
                        outgoing[e] = outgoing.get(e, 0) + int(
                            phase["per_edge_bytes"]
                        )
            for e, nbytes in outgoing.items():
                if nbytes <= 0:
                    continue
                per_edge.setdefault(e, []).append(nbytes / seconds / 1e9)
                bytes_edge[e] = bytes_edge.get(e, 0) + nbytes
    betas = edge_betas(topo) if topo else {}
    links: Dict[str, Any] = {}
    missing: List[str] = []
    for e in sorted(per_edge):
        src, dst = e
        p50 = statistics.median(per_edge[e])
        row = {
            "src": src,
            "dst": dst,
            "samples": len(per_edge[e]),
            "gbps_p50": p50,
            "bytes": bytes_edge[e],
        }
        beta = betas.get(e)
        if beta:
            row["beta_gbps"] = beta
            row["vs_probe"] = p50 / beta
        elif topo:
            missing.append(edge_key(src, dst))
        links[edge_key(src, dst)] = row
    out: Dict[str, Any] = {"links": links}
    if topo:
        out["missing_edges"] = len(missing)
        if missing:
            print(
                f"# topology: {len(missing)} attributed edge(s) not in "
                f"the probe map (no vs_probe): {' '.join(missing[:8])}"
                + (" ..." if len(missing) > 8 else ""),
                file=sys.stderr,
            )
    return out


# ---------------------------------------------------------------------
# rendering: heatmap, report, diff
# ---------------------------------------------------------------------

_HEAT_CHARS = " .:-=+*#%@"


def render_heatmap(topo: Dict[str, Any]) -> List[str]:
    """ASCII link heatmap: rows are source ranks, columns destination
    ranks, each cell the edge's beta scaled onto ``' '..'@'`` against
    the fastest measured link (``.`` is slowest, ``@`` fastest,
    ``-`` on the diagonal, ``?`` for unmeasured edges)."""
    world = int(topo.get("world") or 0)
    betas = edge_betas(topo)
    top = max(betas.values(), default=0.0)
    lines = ["link beta heatmap (GB/s; rows=src, cols=dst; "
             f"@ = {top:.3g} GB/s)"]
    header = "     " + " ".join(f"{d:>2}" for d in range(world))
    lines.append(header)
    for src in range(world):
        cells = []
        for dst in range(world):
            if src == dst:
                cells.append(" -")
                continue
            beta = betas.get((src, dst))
            if beta is None:
                cells.append(" ?")
            elif top <= 0:
                cells.append(" ?")
            else:
                idx = min(
                    len(_HEAT_CHARS) - 1,
                    max(1, int(round(beta / top * (len(_HEAT_CHARS) - 1)))),
                )
                cells.append(" " + _HEAT_CHARS[idx])
        lines.append(f"  {src:>2} " + " ".join(cells))
    return lines


def format_report(
    topo: Dict[str, Any],
    *,
    links: Optional[Dict[str, Any]] = None,
    factor: float = SLOW_LINK_FACTOR,
) -> str:
    """The human report: provenance line, heatmap, slow-link table,
    and (when run artifacts joined) the measured per-link overlay."""
    prov = topo.get("provenance") or {}
    median = fleet_median_beta(topo)
    out = [
        f"topology: {SCHEMA} world={topo['world']} "
        f"platform={topo.get('platform', '?')} "
        f"edges={len(topo.get('edges') or {})} "
        f"source={prov.get('source', '?')}"
        + (f" fleet-median={median:.3g}GB/s" if median else ""),
    ]
    out.extend(render_heatmap(topo))
    slow = slow_links(topo, factor=factor)
    if slow:
        out.append(f"slow links (< {factor:g}x fleet median):")
        for row in slow:
            out.append(
                f"  {row['edge']:<8} {row['beta_gbps']:.3g} GB/s "
                f"({row['ratio']:.2f}x median)"
            )
    else:
        out.append(f"no slow links (every edge >= {factor:g}x fleet median)")
    if links:
        out.append(f"{'link':<8} {'probe':>9} {'run p50':>9} "
                   f"{'vs':>6} {'samples':>8}")
        for key in sorted(links, key=parse_edge):
            row = links[key]
            beta = row.get("beta_gbps")
            vs = row.get("vs_probe")
            out.append(
                f"{key:<8} "
                + (f"{beta:>7.3g}GB" if beta else f"{'-':>9}")
                + f" {row['gbps_p50']:>7.3g}GB"
                + (f" {vs:>5.2f}x" if vs else f" {'-':>6}")
                + f" {row['samples']:>8}"
            )
    return "\n".join(out)


def diff_maps(
    a: Dict[str, Any],
    b: Dict[str, Any],
    *,
    threshold: float = 0.2,
) -> List[Dict[str, Any]]:
    """Per-edge beta drift between two maps: edges whose beta moved by
    more than ``threshold`` (relative), plus edges only one map has.
    Sorted worst-regression first."""
    ea, eb = edge_betas(a), edge_betas(b)
    rows: List[Dict[str, Any]] = []
    for e in sorted(set(ea) | set(eb)):
        beta_a, beta_b = ea.get(e), eb.get(e)
        if beta_a is None or beta_b is None:
            rows.append({
                "edge": edge_key(*e), "a_gbps": beta_a, "b_gbps": beta_b,
                "change": "added" if beta_a is None else "removed",
            })
            continue
        rel = (beta_b - beta_a) / beta_a
        if abs(rel) >= threshold:
            rows.append({
                "edge": edge_key(*e), "a_gbps": beta_a, "b_gbps": beta_b,
                "change": f"{rel:+.0%}",
            })
    def _sortkey(r):
        if r["change"] in ("added", "removed"):
            return (1, 0.0)
        return (0, (r["b_gbps"] - r["a_gbps"]) / r["a_gbps"])
    rows.sort(key=_sortkey)
    return rows


def format_diff(rows: List[Dict[str, Any]]) -> str:
    if not rows:
        return "topology diff: no edge drifted beyond the threshold"
    out = ["topology diff (worst regression first):"]
    for r in rows:
        a = f"{r['a_gbps']:.3g}" if r.get("a_gbps") else "-"
        b = f"{r['b_gbps']:.3g}" if r.get("b_gbps") else "-"
        out.append(f"  {r['edge']:<8} {a:>8} -> {b:<8} GB/s  [{r['change']}]")
    return "\n".join(out)


# ---------------------------------------------------------------------
# the in-world probe (imports the op layer lazily; jax required)
# ---------------------------------------------------------------------


def _sweep_edges(world: int) -> List[int]:
    """The CartComm shift displacements the sweep times: 1 (the ring)
    plus every other displacement (pairwise — rotation d covers every
    directed edge ``r -> (r+d) % world``)."""
    return list(range(1, world))


def probe_rank(
    out_dir: str,
    *,
    payloads: Sequence[int] = DEFAULT_PAYLOADS,
    repeats: int = DEFAULT_REPEATS,
    merge_timeout_s: float = 60.0,
) -> Optional[str]:
    """Run this rank's share of the sweep inside a launched world.

    Every displacement ``d`` is a periodic :class:`..comm.CartComm`
    shift: rank ``r`` sendrecv's with destination ``(r+d) % n`` and
    source ``(r-d) % n`` — ``d=1`` is the ring sweep, ``d>1`` the
    pairwise rotations, together covering every directed edge. Each
    rank times its own calls (one untimed warmup per payload, then
    ``repeats`` timed ones; the measured wall time is attributed to
    the rank's *outgoing* edge), writes ``topo-rank{k}.json``, and
    rank 0 merges every partial into ``DIR/topology.json`` (returned
    on rank 0; the partial path elsewhere)."""
    import platform as _platform

    import numpy as np

    import mpi4jax_tpu as m4t
    from .. import config
    from ..runtime import shm

    rank, world = shm.rank(), shm.size()
    if world < 2:
        raise RuntimeError("topology probe needs a world of >= 2 ranks")
    cart = m4t.CartComm([world], periods=True)
    samples: Dict[Edge, List[Tuple[int, float]]] = {}
    for disp in _sweep_edges(world):
        source_table, dest_table = cart.shift(0, disp)
        source, dest = source_table[rank], dest_table[rank]
        for nbytes in payloads:
            buf = np.zeros(max(1, int(nbytes)), dtype=np.uint8)
            recv = np.empty_like(buf)
            for i in range(max(1, int(repeats)) + 1):
                t_start = time.perf_counter()
                out = m4t.sendrecv(buf, recv, source, dest,
                                   sendtag=disp, recvtag=disp)
                np.asarray(out)  # force completion before stopping the clock
                elapsed = time.perf_counter() - t_start
                if i == 0:
                    continue  # warmup
                samples.setdefault((rank, dest), []).append(
                    (int(nbytes), elapsed)
                )
    partial = {
        "schema": f"{SCHEMA}-partial",
        "rank": rank,
        "world": world,
        "host": _platform.node(),
        "device_kind": config.PLATFORM_CLASS or "cpu",
        "samples": {
            edge_key(*e): [[n, t] for n, t in rows]
            for e, rows in sorted(samples.items())
        },
    }
    partial_path = os.path.join(out_dir, f"topo-rank{rank}.json")
    fd, tmp = tempfile.mkstemp(prefix=".topo-", dir=out_dir)
    with os.fdopen(fd, "w") as f:
        json.dump(partial, f)
    os.replace(tmp, partial_path)
    # every rank reaches the same collective count above, so a barrier
    # here means "all partials are durably renamed"
    m4t.barrier()
    if rank != 0:
        return partial_path
    return merge_partials(
        out_dir, world, payloads=payloads, repeats=repeats,
        timeout_s=merge_timeout_s,
    )


def merge_partials(
    out_dir: str,
    world: int,
    *,
    payloads: Sequence[int] = DEFAULT_PAYLOADS,
    repeats: int = DEFAULT_REPEATS,
    timeout_s: float = 60.0,
) -> str:
    """Merge per-rank ``topo-rank{k}.json`` partials into the fitted
    ``DIR/topology.json`` map (polls briefly for stragglers so the
    merge also works launcher-side, without a barrier)."""
    deadline = time.monotonic() + max(0.0, float(timeout_s))
    paths = {
        r: os.path.join(out_dir, f"topo-rank{r}.json") for r in range(world)
    }
    while (
        any(not os.path.exists(p) for p in paths.values())
        and time.monotonic() < deadline
    ):
        time.sleep(0.05)
    samples: Dict[Edge, List[Tuple[int, float]]] = {}
    ranks: Dict[int, Dict[str, Any]] = {}
    platform = "cpu"
    for r, path in sorted(paths.items()):
        if not os.path.exists(path):
            raise RuntimeError(
                f"topology probe: rank {r} partial never appeared in "
                f"{out_dir}"
            )
        with open(path) as f:
            partial = json.load(f)
        ranks[r] = {
            "host": partial.get("host", ""),
            "device_kind": partial.get("device_kind", "cpu"),
        }
        platform = partial.get("device_kind", platform)
        for key, rows in (partial.get("samples") or {}).items():
            samples.setdefault(parse_edge(key), []).extend(
                (int(n), float(t)) for n, t in rows
            )
    topo = build_map(
        world, samples,
        ranks=ranks,
        platform=platform,
        provenance={
            "method": "sendrecv-sweep",
            "source": "probe",
            "payloads": [int(p) for p in payloads],
            "repeats": int(repeats),
        },
    )
    return save(os.path.join(out_dir, MAP_BASENAME), topo)


# ---------------------------------------------------------------------
# selftest (device-free)
# ---------------------------------------------------------------------


def selftest() -> int:
    """Device-free proof over injectable synthetic link models: the
    fit recovers planted alpha/beta, a planted slow link is detected
    and localized to the correct directed edge, the doctor-facing
    classifier splits link-bound from rank-bound, and a skewed map
    flips the autotuner's impl choice vs the uniform-peak seed."""
    # 1. the fit recovers a planted alpha/beta from a clean sweep
    model = SyntheticLinkModel(4, alpha_s=3e-6, beta_gbps=18.0)
    alpha, beta = fit_alpha_beta(model.samples()[(0, 1)])
    assert abs(alpha - 3e-6) < 1e-9, alpha
    assert abs(beta - 18.0) < 1e-6, beta

    # degenerate sweeps degrade, not crash
    alpha1, beta1 = fit_alpha_beta([(1 << 20, 1e-3)] * 3)
    assert alpha1 == 0.0 and beta1 > 0, (alpha1, beta1)

    # 2. a planted slow link is detected and localized
    slow_edge = (2, 3)
    skewed = SyntheticLinkModel(
        4, beta_gbps=20.0, links={slow_edge: {"beta_gbps": 1.0}}
    )
    topo = synthetic_map(skewed)
    validate(topo)
    found = slow_links(topo)
    assert len(found) == 1, found
    assert (found[0]["src"], found[0]["dst"]) == slow_edge, found
    assert found[0]["beta_gbps"] < 0.1 * found[0]["fleet_median_gbps"]

    # round-trips through save/load unchanged
    import tempfile as _tempfile

    with _tempfile.TemporaryDirectory() as d:
        path = save(os.path.join(d, MAP_BASENAME), topo)
        assert load(path) == topo
        assert find([d]) == topo

    # 3. the classifier: the slow edge's ranks read link-bound, a
    #    rank with healthy links reads rank-bound
    verdict = classify_rank(topo, 2)
    assert verdict is not None and verdict["klass"] == "link-bound", verdict
    assert verdict["slowest_edge"] == edge_key(*slow_edge), verdict
    verdict0 = classify_rank(topo, 0)
    assert verdict0 is not None and verdict0["klass"] == "rank-bound", verdict0

    # 4. the doctor join mutates straggler findings in place
    from . import doctor

    report = {"findings": [
        {"kind": "straggler", "op": "AllReduce", "rank": 2,
         "mean_s": 0.01, "peer_median_s": 0.002, "ratio": 5.0,
         "samples": 8, "min_samples": 5, "peer_samples": {}},
        {"kind": "hang", "rank": 1, "last_seq": 3},
    ]}
    joined = doctor.attach_link_classification(report, topo)
    assert joined == 1, joined
    diag = report["findings"][0]["link_diagnosis"]
    assert diag["klass"] == "link-bound"
    assert diag["slowest_edge"] == edge_key(*slow_edge)
    txt = doctor._fmt_finding(report["findings"][0])
    assert "link-bound" in txt and edge_key(*slow_edge) in txt, txt

    # 5. per-link attribution joins latency x edge decomposition
    by_rank = {}
    world = 4
    for r in range(world):
        by_rank[r] = [
            {"kind": "emission", "op": "AllReduce", "bytes": 1 << 20,
             "dtype": "float32", "world": world, "axes": ["ranks"],
             "seq": 1, "cid": f"c{r}", "t": 1.0},
            {"kind": "latency", "op": "AllReduce", "cid": f"c{r}",
             "seconds": 2e-3, "t": 1.1},
        ]
    attributed = attribute_links(by_rank, topo=topo)
    # a ring AllReduce uses exactly the ring edges, one outgoing per rank
    assert set(attributed["links"]) == {
        edge_key(r, (r + 1) % world) for r in range(world)
    }, attributed
    row = attributed["links"][edge_key(0, 1)]
    expected_gbps = (2 * (world - 1) * (1 << 20) / world) / 2e-3 / 1e9
    assert abs(row["gbps_p50"] - expected_gbps) < 1e-9, row
    assert row["vs_probe"] > 0

    # 6. rendering is total: heatmap marks the slow edge colder than
    #    its healthy mirror, report and diff never crash
    heat = render_heatmap(topo)
    assert len(heat) == 2 + world
    row2 = heat[2 + slow_edge[0]]
    cells = row2.split()[1:]
    assert _HEAT_CHARS.index(cells[slow_edge[1]]) < _HEAT_CHARS.index(
        cells[(slow_edge[1] + 1) % world]
    ), heat
    assert "slow links" in format_report(topo, links=attributed["links"])
    uniform = synthetic_map(SyntheticLinkModel(4, beta_gbps=20.0))
    drift = diff_maps(uniform, topo)
    assert [r["edge"] for r in drift] == [edge_key(*slow_edge)], drift
    assert format_diff(drift)

    # 7. planner consumption: the skewed map flips an impl choice the
    #    uniform-peak seed would have made (the acceptance flip —
    #    tests/test_topology.py pins the same scenario end to end)
    from ..planner import autotune, plan as _plan

    key = _plan.plan_key(
        "AllReduce", nbytes=12 << 20, dtype="float32", world=8,
        axes=("a", "b"), platform="cpu",
    )
    mesh = {"a": 2, "b": 4}
    plan_uniform, _ = autotune.sweep([key], mesh=mesh, gbps=20.0)
    crossing = SyntheticLinkModel(
        8, beta_gbps=20.0,
        links={(0, 4): {"beta_gbps": 0.5}, (4, 0): {"beta_gbps": 0.5}},
    )
    plan_topo, _ = autotune.sweep(
        [key], mesh=mesh, gbps=20.0, topo=synthetic_map(crossing)
    )
    assert plan_uniform.entries[key].impl != plan_topo.entries[key].impl, (
        plan_uniform.entries[key], plan_topo.entries[key],
    )
    assert plan_topo.entries[key].beta_source == "topo-probe"

    # 8. the OpenMetrics gauge family renders per-link samples
    from . import export

    text = export.render_openmetrics(
        {"ranks": [0, 1], "records": 0},
        topo_links=attributed["links"],
    )
    assert "m4t_topo_link_gbps" in text
    assert 'src="0"' in text and 'dst="1"' in text

    # 9. the CLI spec parser round-trips the planted skew
    parsed = parse_synthetic_spec("beta=20,alpha_us=2,2->3=1", world=4)
    assert parsed.params(2, 3) == (2e-6, 1.0)
    assert parsed.params(3, 2) == (2e-6, 20.0)

    print("topology selftest ok")
    return 0


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def _parse_payloads(text: str) -> List[int]:
    out = [int(p) for p in str(text).split(",") if p.strip()]
    if not out or any(p <= 0 for p in out):
        raise argparse.ArgumentTypeError(
            f"--payloads must be positive byte counts (got {text!r})"
        )
    return out


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.observability.topology",
        description="Measured link maps: probe, report, diff.",
    )
    parser.add_argument(
        "--selftest", action="store_true",
        help="run the device-free synthetic-link selftest and exit",
    )
    sub = parser.add_subparsers(dest="cmd")

    p_probe = sub.add_parser(
        "probe",
        help="sweep sendrecv over CartComm edges (inside a launched "
        "world) or synthesize a map from a link model (device-free)",
    )
    p_probe.add_argument(
        "--out", required=True, metavar="DIR_OR_FILE",
        help="run directory the map is merged into (in-world probe) "
        "or the output file (--synthetic)",
    )
    p_probe.add_argument(
        "--payloads", type=_parse_payloads,
        default=list(DEFAULT_PAYLOADS), metavar="N,N,...",
        help="payload sizes to sweep, bytes (default "
        f"{','.join(str(p) for p in DEFAULT_PAYLOADS)})",
    )
    p_probe.add_argument(
        "--repeats", type=int, default=DEFAULT_REPEATS, metavar="K",
        help="timed repetitions per (edge, payload) after one warmup "
        "(default %(default)s)",
    )
    p_probe.add_argument(
        "--synthetic", default=None, metavar="SPEC",
        help="device-free: synthesize the map from a link model spec "
        "('beta=20,alpha_us=2,2->3=1.5' — default beta GB/s, default "
        "alpha us, per-edge beta overrides); requires --world",
    )
    p_probe.add_argument(
        "--world", type=int, default=None, metavar="N",
        help="world size for --synthetic",
    )

    p_report = sub.add_parser(
        "report",
        help="render a map: heatmap + slow links, optionally joined "
        "with a run's measured per-link attribution",
    )
    p_report.add_argument("topo", help="topology.json (m4t-topo/1)")
    p_report.add_argument(
        "rundir", nargs="?", default=None,
        help="run artifacts to overlay measured per-link GB/s from "
        "(launch --events-dir layout)",
    )
    p_report.add_argument(
        "--prom", default=None, metavar="OUT.prom",
        help="additionally write the m4t_topo_link_gbps gauges as an "
        "OpenMetrics exposition",
    )
    p_report.add_argument(
        "--factor", type=float, default=SLOW_LINK_FACTOR, metavar="F",
        help="slow-link threshold as a fraction of the fleet-median "
        "beta (default %(default)s)",
    )

    p_diff = sub.add_parser(
        "diff", help="per-edge beta drift between two maps",
    )
    p_diff.add_argument("a", help="older topology.json")
    p_diff.add_argument("b", help="newer topology.json")
    p_diff.add_argument(
        "--threshold", type=float, default=0.2, metavar="F",
        help="relative beta change worth reporting (default "
        "%(default)s)",
    )
    p_diff.add_argument(
        "--fail-on-drift", action="store_true",
        help="exit 1 when any edge drifted (a CI tripwire)",
    )

    args = parser.parse_args(argv)
    if args.selftest:
        return selftest()
    if args.cmd is None:
        parser.error("missing command (probe/report/diff/--selftest)")

    if args.cmd == "probe":
        if args.synthetic is not None:
            if not args.world or args.world < 2:
                parser.error("--synthetic requires --world >= 2")
            model = parse_synthetic_spec(args.synthetic, world=args.world)
            topo = synthetic_map(
                model, payloads=args.payloads, repeats=args.repeats
            )
            out = args.out
            if os.path.isdir(out):
                out = os.path.join(out, MAP_BASENAME)
            save(out, topo)
            print(f"topology: synthetic map ({args.world} ranks, "
                  f"{len(topo['edges'])} edges) written to {out}")
            return 0
        if os.environ.get("M4T_RANK") is None:
            parser.error(
                "probe must run inside a launched world (launch "
                "--probe-topology / launch -n N -m "
                "mpi4jax_tpu.observability.topology probe --out DIR) — "
                "or pass --synthetic for a device-free map"
            )
        os.makedirs(args.out, exist_ok=True)
        path = probe_rank(
            args.out, payloads=args.payloads, repeats=args.repeats
        )
        if path and os.path.basename(path) == MAP_BASENAME:
            topo = load(path)
            print(f"topology: probed {topo['world']} ranks, "
                  f"{len(topo['edges'])} edges -> {path}")
            print(format_report(topo))
        return 0

    if args.cmd == "report":
        topo = load(args.topo)
        links = None
        if args.rundir:
            from . import doctor

            by_rank = doctor.load([args.rundir])
            if by_rank:
                links = attribute_links(by_rank, topo=topo).get("links")
        print(format_report(topo, links=links, factor=args.factor))
        if args.prom:
            from . import export

            gauges = links if links is not None else {
                edge_key(*e): {"gbps_p50": beta}
                for e, beta in edge_betas(topo).items()
            }
            export.write_prom(
                args.prom,
                export.render_openmetrics(
                    {"ranks": [], "records": 0}, topo_links=gauges
                ),
            )
            print(f"# m4t_topo_link_gbps exposition written to {args.prom}")
        return 0

    if args.cmd == "diff":
        rows = diff_maps(load(args.a), load(args.b), threshold=args.threshold)
        print(format_diff(rows))
        return 1 if rows and args.fail_on_drift else 0

    return 2  # pragma: no cover — argparse exhausts the commands


if __name__ == "__main__":
    sys.exit(main())
