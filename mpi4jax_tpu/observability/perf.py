"""Achieved-bandwidth attribution, live anomaly watch, and the perf
history/regression CLI.

Three layers, all built on the analytic cost model
(:mod:`.costmodel`) and the artifacts the telemetry subsystem already
writes:

1. **Attribution** — join expected wire bytes against measured
   latency (runtime-sampling ``latency`` records in event logs, or
   the in-process latency reservoirs) and report per-op /
   per-mesh-axis achieved bandwidth and %-of-peak:
   ``obs.perf_report()`` live, ``perf report RUNDIR`` offline,
   ``doctor --perf`` as a post-mortem section, and an
   "achieved GB/s" counter track in the Perfetto export.

2. **Anomaly watch** (:class:`PerfWatch`) — a streaming EWMA + MAD
   baseline per emission fingerprint, fed from the runtime latency
   callback (``metrics.mark_runtime_end``) when ``M4T_PERF_WATCH=1``.
   A sample more than z (``M4T_PERF_Z``, default 6) robust standard
   deviations *above* its fingerprint's baseline emits an ``anomaly``
   event through the default sink and prints a one-line warning (once
   per fingerprint) — the mid-run "this collective just got slower"
   signal. ``benchmarks/tpu_watch.py`` runs a private instance over
   its probe/stage durations.

3. **History / regression gate** — ``perf {report,compare,history,
   gate}`` parses run event dirs and the repo's ``BENCH_r*.json``
   trajectory (the ``{n, cmd, rc, tail, parsed}`` wrapper schema, or
   bare ``{"metric", "value", ...}`` records), writes
   ``PERF_REPORT.md``, and ``gate`` exits non-zero when the latest
   comparable benchmark regresses beyond a noise band — the CI hook
   for perf PRs.

Everything here is host-side and import-light (no jax); the runtime
paths are inert unless telemetry is enabled.

CLI::

    python -m mpi4jax_tpu.observability.perf report RUNDIR [-o PERF_REPORT.md]
    python -m mpi4jax_tpu.observability.perf history [--dir REPO]
    python -m mpi4jax_tpu.observability.perf compare RUNDIR_A RUNDIR_B
    python -m mpi4jax_tpu.observability.perf gate [--dir REPO] [--tolerance 0.25]
    python -m mpi4jax_tpu.observability.perf --selftest
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence

from .. import config
from . import costmodel, events
from .recorder import fingerprint

#: default noise band for the regression gate: the BENCH trajectory
#: mixes container-CPU runs whose wall clock wobbles with host load
DEFAULT_TOLERANCE = 0.25

#: prior comparable rounds required before the gate may fail anything
DEFAULT_MIN_HISTORY = 2

_BENCH_RE = re.compile(r"BENCH_r(\d+)(?:_([A-Za-z0-9_]+))?\.json$")


# ---------------------------------------------------------------------
# attribution: cost model x measured latency
# ---------------------------------------------------------------------


def _axes_key(axes: Optional[Sequence[str]]) -> str:
    if not axes:
        return "<none>"
    return ",".join(str(a) for a in axes)


def attribute(
    by_rank: Dict[int, List[Dict[str, Any]]],
    *,
    peak: Optional[float] = None,
    alpha: Optional[float] = None,
    extra_latency_by_op: Optional[Dict[str, List[float]]] = None,
) -> Dict[str, Any]:
    """Join emission fingerprints to latency samples and the cost
    model. ``by_rank`` is the :func:`..doctor.load` shape (rank ->
    records); pass ``{0: snapshot["emissions"]}`` for in-process use.

    Returns ``{"peak_gbps", "alpha_s", "rows": [...]}`` where each row
    describes one (op, axes, world, payload, dtype) fingerprint group:
    emission count, modelled wire bytes / steps / expected time, and —
    when latency samples joined (by correlation id, else op-level) —
    sample count, p50 latency, achieved GB/s, %-of-peak, and the
    measured/expected slowdown factor.
    """
    from . import doctor  # local: doctor imports perf only lazily

    peak = costmodel.peak_gbps() if peak is None else float(peak)
    alpha = costmodel.alpha_s() if alpha is None else float(alpha)

    groups: Dict[tuple, Dict[str, Any]] = {}
    cid_to_key: Dict[str, tuple] = {}
    for rank in sorted(by_rank):
        for rec in doctor.collective_stream(by_rank[rank]):
            key = (
                rec.get("op", "?"),
                _axes_key(rec.get("axes")),
                rec.get("world"),
                int(rec.get("bytes") or 0),
                rec.get("dtype"),
                # planner impl stamp (armed runs only): two emissions
                # of the same fingerprint routed through different
                # implementations must attribute separately — that is
                # the per-impl bandwidth the autotuner refines on
                rec.get("impl"),
            )
            g = groups.get(key)
            if g is None:
                g = groups[key] = {"emissions": 0, "samples": []}
            g["emissions"] += 1
            cid = rec.get("cid")
            if cid:
                cid_to_key[cid] = key

    def _op_fallback_key(op: Optional[str]) -> Optional[tuple]:
        cands = [k for k in groups if k[0] == op]
        if not cands:
            return None
        # dominant fingerprint: most emissions wins
        return max(cands, key=lambda k: groups[k]["emissions"])

    for rank in sorted(by_rank):
        for rec in by_rank[rank]:
            if rec.get("kind") != "latency":
                continue
            seconds = rec.get("seconds")
            if not isinstance(seconds, (int, float)) or seconds < 0:
                continue
            key = cid_to_key.get(rec.get("cid") or "")
            if key is None:
                key = _op_fallback_key(rec.get("op"))
            if key is not None:
                groups[key]["samples"].append(float(seconds))

    for op, samples in (extra_latency_by_op or {}).items():
        key = _op_fallback_key(op)
        if key is not None:
            groups[key]["samples"].extend(float(s) for s in samples)

    rows: List[Dict[str, Any]] = []
    for (op, axes, world, nbytes, dtype, impl), g in groups.items():
        c = costmodel.cost(
            op, nbytes=nbytes, world=world, dtype=dtype, impl=impl
        )
        expected = costmodel.expected_time_s(c, gbps=peak, alpha=alpha)
        row = {
            "op": op,
            "axes": axes,
            "world": world,
            "bytes": nbytes,
            "dtype": dtype,
            "impl": impl,
            "emissions": g["emissions"],
            "wire_bytes": c["wire_bytes"],
            "steps": c["steps"],
            "algorithm": c["algorithm"],
            "expected_s": expected,
        }
        if g["samples"]:
            p50 = statistics.median(g["samples"])
            gbps = costmodel.achieved_gbps(c, p50)
            row.update(
                samples=len(g["samples"]),
                lat_p50_s=p50,
                achieved_gbps=gbps,
                pct_of_peak=(
                    None if gbps is None else 100.0 * gbps / peak
                ),
                slowdown=(p50 / expected) if expected > 0 else None,
            )
        rows.append(row)
    rows.sort(key=lambda r: -(r["wire_bytes"] * r["emissions"]))
    return {"peak_gbps": peak, "alpha_s": alpha, "rows": rows}


def _fmt_bytes(n: Optional[int]) -> str:
    if n is None:
        return "-"
    value = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(value) < 1024 or unit == "GiB":
            return f"{value:.1f}{unit}" if unit != "B" else f"{int(value)}B"
        value /= 1024
    return f"{value}B"


def _fmt_s(s: Optional[float]) -> str:
    if s is None:
        return "-"
    if s < 1e-3:
        return f"{s * 1e6:.1f}us"
    if s < 1:
        return f"{s * 1e3:.2f}ms"
    return f"{s:.2f}s"


def format_table(result: Dict[str, Any]) -> str:
    """Human-readable attribution table (also the ``doctor --perf``
    section body)."""
    lines = [
        f"perf attribution vs peak {result['peak_gbps']:g} GB/s "
        f"(alpha {result['alpha_s'] * 1e6:g} us/step; "
        "M4T_PEAK_GBPS / M4T_ALPHA_US to retarget)"
    ]
    if not result["rows"]:
        lines.append("no collective emissions to attribute")
        return "\n".join(lines)
    lines.append(
        f"{'op':<20} {'axes':<8} {'n':>3} {'payload':>9} {'emits':>5} "
        f"{'wire/emit':>10} {'expect':>8} {'p50':>8} "
        f"{'GB/s':>8} {'%peak':>6} {'slow':>6}"
    )
    for r in result["rows"]:
        gbps = r.get("achieved_gbps")
        pct = r.get("pct_of_peak")
        slow = r.get("slowdown")
        op_txt = r["op"] + (f"+{r['impl']}" if r.get("impl") else "")
        lines.append(
            f"{op_txt:<20} {r['axes']:<8} "
            f"{r['world'] if r['world'] else '-':>3} "
            f"{_fmt_bytes(r['bytes']):>9} {r['emissions']:>5} "
            f"{_fmt_bytes(r['wire_bytes']):>10} "
            f"{_fmt_s(r['expected_s']):>8} "
            f"{_fmt_s(r.get('lat_p50_s')):>8} "
            f"{f'{gbps:.3g}' if gbps is not None else '-':>8} "
            f"{f'{pct:.1f}' if pct is not None else '-':>6} "
            f"{f'{slow:.1f}x' if slow is not None else '-':>6}"
        )
    return "\n".join(lines)


def perf_report(
    *,
    peak: Optional[float] = None,
    alpha: Optional[float] = None,
    file=None,
) -> str:
    """Attribution table for the *live* process: the metrics
    registry's emission ring joined against its latency reservoirs
    (runtime sampling) through the cost model. Returns the table text
    (and writes it to ``file`` when given)."""
    from . import metrics

    snap = metrics.registry.snapshot()
    result = attribute(
        {0: snap["emissions"]},
        peak=peak,
        alpha=alpha,
        extra_latency_by_op=metrics.registry.latency_samples(),
    )
    text = format_table(result)
    if file is not None:
        file.write(text + "\n")
    return text


def write_markdown(
    path: str,
    result: Dict[str, Any],
    *,
    inputs: Sequence[str] = (),
    history_rows: Optional[List[Dict[str, Any]]] = None,
) -> str:
    """Write the attribution (and optionally the bench trajectory) as
    ``PERF_REPORT.md``."""
    lines = [
        "# Performance report",
        "",
        f"Generated by `python -m mpi4jax_tpu.observability.perf report"
        f"{' ' + ' '.join(inputs) if inputs else ''}`.",
        "",
        f"Peak link bandwidth: **{result['peak_gbps']:g} GB/s** "
        f"(`M4T_PEAK_GBPS` to retarget); alpha "
        f"{result['alpha_s'] * 1e6:g} us/step.",
        "",
        "## Achieved bandwidth by collective",
        "",
        "| op | axes | world | payload | emits | wire/emit | steps | "
        "algorithm | expected | p50 | GB/s | % peak |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in result["rows"]:
        gbps = r.get("achieved_gbps")
        pct = r.get("pct_of_peak")
        lines.append(
            f"| {r['op'] + ('+' + r['impl'] if r.get('impl') else '')} "
            f"| {r['axes']} | {r['world'] or '-'} "
            f"| {_fmt_bytes(r['bytes'])} | {r['emissions']} "
            f"| {_fmt_bytes(r['wire_bytes'])} | {r['steps']} "
            f"| {r['algorithm']} | {_fmt_s(r['expected_s'])} "
            f"| {_fmt_s(r.get('lat_p50_s'))} "
            f"| {f'{gbps:.3g}' if gbps is not None else '-'} "
            f"| {f'{pct:.1f}' if pct is not None else '-'} |"
        )
    if history_rows:
        lines += [
            "",
            "## Benchmark trajectory",
            "",
            "| round | file | value (s) | vs_baseline | nproc | rc |",
            "|---|---|---|---|---|---|",
        ]
        for row in history_rows:
            lines.append(
                f"| {row['round']} | {os.path.basename(row['file'])} "
                f"| {row['value']} | {row.get('vs_baseline') or '-'} "
                f"| {row.get('nproc') or '-'} | {row.get('rc')} |"
            )
    text = "\n".join(lines) + "\n"
    with open(path, "w") as f:
        f.write(text)
    return text


# ---------------------------------------------------------------------
# live anomaly watch (EWMA + MAD per fingerprint)
# ---------------------------------------------------------------------


class PerfWatch:
    """Streaming per-key latency baseline: exponentially weighted mean
    plus exponentially weighted mean absolute deviation (a streaming
    stand-in for the MAD). A sample more than ``z`` robust sigmas
    (``1.4826 * ewmad``) *above* the mean after ``warmup`` samples is
    an anomaly — slow regressions only; getting faster is never
    flagged. The baseline keeps updating through anomalies, so a
    legitimate step change re-baselines instead of alarming forever.
    """

    def __init__(
        self,
        *,
        z: Optional[float] = None,
        warmup: Optional[int] = None,
        smoothing: float = 0.1,
        emit: bool = True,
    ):
        self.z = float(z if z is not None else config.PERF_Z)
        self.warmup = int(warmup if warmup is not None else config.PERF_WARMUP)
        self.smoothing = float(smoothing)
        self.emit = bool(emit)
        self._lock = threading.Lock()
        #: key -> [count, ewma, ewmad]
        self._state: Dict[str, List[float]] = {}
        self._warned: set = set()
        self.anomalies: List[Dict[str, Any]] = []

    def observe(
        self, key: str, value: float, **context: Any
    ) -> Optional[Dict[str, Any]]:
        """Feed one latency sample; returns the anomaly record when
        this sample regressed beyond the z-threshold, else None."""
        value = float(value)
        anomaly = None
        warn = False
        with self._lock:
            st = self._state.get(key)
            if st is None:
                self._state[key] = [1, value, 0.0]
                return None
            count, ewma, ewmad = st
            dev = abs(value - ewma)
            if count >= self.warmup and value > ewma:
                # robust sigma with a 1%-of-baseline floor: a stream
                # with near-zero jitter must not hair-trigger on the
                # first nanosecond of noise, yet a genuine spike over
                # a flat baseline still scores enormous
                sigma = 1.4826 * ewmad + 0.01 * abs(ewma) + 1e-12
                zscore = dev / sigma
                if zscore >= self.z:
                    anomaly = {
                        "kind": "anomaly",
                        "key": key,
                        "seconds": value,
                        "baseline_s": ewma,
                        "mad_s": ewmad,
                        "z": round(zscore, 2),
                        "n": int(count),
                        "t": time.time(),
                    }
                    anomaly.update(context)
                    self.anomalies.append(anomaly)
                    if len(self.anomalies) > 256:
                        del self.anomalies[:-256]
                    if key not in self._warned:
                        self._warned.add(key)
                        warn = True
            a = self.smoothing
            st[0] = count + 1
            st[1] = (1 - a) * ewma + a * value
            st[2] = (1 - a) * ewmad + a * dev
        if anomaly is not None:
            if self.emit:
                events.emit(dict(anomaly))
            if warn:
                print(
                    f"# m4t perf watch: {key}: {value:.4g}s is "
                    f"{anomaly['z']:g} sigma above its "
                    f"{anomaly['baseline_s']:.4g}s baseline "
                    f"(n={anomaly['n']}); further anomalies for this "
                    "fingerprint go to the event sink only",
                    file=sys.stderr,
                )
        return anomaly

    def reset(self) -> None:
        with self._lock:
            self._state.clear()
            self._warned.clear()
            self.anomalies.clear()


#: process-global watch fed by metrics.mark_runtime_end; None until
#: first enabled observation (no state unless the watch is on)
_watch: Optional[PerfWatch] = None
_watch_lock = threading.Lock()
_enabled = bool(config.PERF_WATCH)


def watch_enabled() -> bool:
    return _enabled


def enable_watch(**kwargs: Any) -> PerfWatch:
    """Turn the live watch on programmatically (analog of
    ``M4T_PERF_WATCH=1``); kwargs go to :class:`PerfWatch`."""
    global _enabled, _watch
    with _watch_lock:
        _enabled = True
        if kwargs or _watch is None:
            _watch = PerfWatch(**kwargs)
        return _watch


def disable_watch() -> None:
    global _enabled
    _enabled = False


def get_watch() -> Optional[PerfWatch]:
    return _watch


def observe_runtime(
    op: str,
    seconds: float,
    *,
    record: Optional[Dict[str, Any]] = None,
    cid: Optional[str] = None,
) -> Optional[Dict[str, Any]]:
    """Runtime-latency hook (called by ``metrics.mark_runtime_end``):
    no-op unless the watch is enabled. Keys the baseline by the
    emission fingerprint when the emission record is known, else by
    op name."""
    if not _enabled:
        return None
    global _watch
    if _watch is None:
        with _watch_lock:
            if _watch is None:
                _watch = PerfWatch()
    key = fingerprint(record) if record else str(op)
    context: Dict[str, Any] = {"op": op}
    if cid:
        context["cid"] = cid
    if record:
        # carry every plan-key field (op/bytes/dtype/axes/world) so an
        # anomaly event is self-sufficient evidence for the streaming
        # doctor's retune recommendations (planner.plan.key_from_record)
        for field in ("bytes", "dtype", "axes", "world", "seq", "impl"):
            if record.get(field) is not None:
                context[field] = record[field]
    return _watch.observe(key, seconds, **context)


# ---------------------------------------------------------------------
# bench history (BENCH_r*.json trajectory)
# ---------------------------------------------------------------------


def parse_bench_file(path: str) -> Optional[Dict[str, Any]]:
    """One BENCH_*.json -> a history row, accepting both the round
    wrapper ``{n, cmd, rc, tail, parsed}`` (the driver's probe
    schema; ``parsed`` holds the benchmark's own JSON line) and a
    bare ``{"metric", "value", ...}`` record. None when unparseable
    or holding no finished measurement."""
    m = _BENCH_RE.search(os.path.basename(path))
    if not m:
        return None
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict):
        return None
    if isinstance(data.get("parsed"), dict):
        rec = data["parsed"]
        rc = data.get("rc")
        rnd = data.get("n")
    elif "metric" in data:
        rec = data
        rc = 0
        rnd = None
    else:
        return None
    value = rec.get("value")
    if not isinstance(value, (int, float)):
        return None
    if rnd is None:
        rnd = int(m.group(1))
    plan = rec.get("plan")
    return {
        "round": int(rnd),
        "variant": m.group(2) or "",
        "file": path,
        "metric": rec.get("metric"),
        "value": float(value),
        "unit": rec.get("unit"),
        "vs_baseline": rec.get("vs_baseline"),
        "nproc": rec.get("nproc"),
        # armed collective-plan id (bench.py "plan" field, PR 7);
        # absent/null = unplanned default routing
        "plan_id": plan.get("id") if isinstance(plan, dict) else None,
        "rc": rc,
    }


def load_history(
    directory: str, *, variant: str = ""
) -> List[Dict[str, Any]]:
    """All parseable BENCH rows of one variant (``""`` = the main
    ``BENCH_rNN.json`` trajectory), ordered by round."""
    rows = []
    for path in sorted(glob.glob(os.path.join(directory, "BENCH_*.json"))):
        row = parse_bench_file(path)
        if row is not None and row["variant"] == variant:
            rows.append(row)
    rows.sort(key=lambda r: r["round"])
    return rows


def _cohort(row: Dict[str, Any]) -> tuple:
    """Comparability key: only rows measuring the same metric under
    the same conditions may gate each other. ``vs_baseline`` is
    non-null exactly for genuine on-chip runs (bench.py), so it
    separates chip windows from CPU-fallback rounds; missing nproc
    (pre-PR1 rows) means single device. The armed plan id (PR 7) is
    part of the key: a round measured under a collective plan must
    not gate — or be gated by — rounds with different routing."""
    return (
        row.get("metric"),
        row.get("vs_baseline") is not None,
        row.get("nproc") or 1,
        row.get("plan_id"),
    )


def gate_history(
    rows: List[Dict[str, Any]],
    *,
    tolerance: float = DEFAULT_TOLERANCE,
    min_history: int = DEFAULT_MIN_HISTORY,
) -> Dict[str, Any]:
    """Regression verdict over a bench trajectory: the latest row is
    compared against the median of the *prior* rows in its cohort
    (same metric / platform class / device count). Verdict "regressed"
    iff latest > median * (1 + tolerance), or the latest run itself
    failed (rc != 0). Fewer than ``min_history`` comparable priors:
    verdict "insufficient_history" (passes — a gate that fails on the
    first run of a new configuration would block every new config)."""
    if not rows:
        return {"verdict": "no_history", "ok": False}
    latest = max(rows, key=lambda r: r["round"])
    if latest.get("rc") not in (0, None):
        return {
            "verdict": "latest_run_failed",
            "ok": False,
            "latest": latest,
        }
    cohort = _cohort(latest)
    prior = [
        r for r in rows
        if r["round"] < latest["round"] and _cohort(r) == cohort
    ]
    result = {
        "latest": latest,
        "cohort": {
            "metric": cohort[0],
            "on_chip": cohort[1],
            "nproc": cohort[2],
            "plan_id": cohort[3],
        },
        "prior_rounds": [r["round"] for r in prior],
        "tolerance": tolerance,
    }
    if len(prior) < min_history:
        result.update(verdict="insufficient_history", ok=True)
        return result
    baseline = statistics.median(r["value"] for r in prior)
    limit = baseline * (1.0 + tolerance)
    result.update(
        baseline=baseline,
        limit=limit,
        verdict=("regressed" if latest["value"] > limit else "ok"),
        ok=latest["value"] <= limit,
    )
    return result


def format_history(rows: List[Dict[str, Any]]) -> str:
    if not rows:
        return "no BENCH_*.json rows found"
    lines = [
        f"{'round':>5} {'file':<24} {'value':>10} {'unit':<4} "
        f"{'vs_base':>8} {'nproc':>5} {'rc':>3}"
    ]
    for r in rows:
        lines.append(
            f"{r['round']:>5} {os.path.basename(r['file']):<24} "
            f"{r['value']:>10.3f} {r['unit'] or '':<4} "
            f"{r['vs_baseline'] if r['vs_baseline'] is not None else '-':>8} "
            f"{r['nproc'] if r['nproc'] is not None else '-':>5} "
            f"{r['rc'] if r['rc'] is not None else '-':>3}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def _load_rank_records(inputs: Iterable[str]) -> Dict[int, List[Dict[str, Any]]]:
    from . import doctor

    return doctor.load(inputs)


def _cmd_report(args: argparse.Namespace) -> int:
    by_rank = _load_rank_records(args.inputs)
    if not by_rank:
        print("perf: no usable records in the given inputs", file=sys.stderr)
        return 2
    result = attribute(by_rank, peak=args.peak_gbps, alpha=args.alpha_s)
    orep = None
    try:
        from . import overlap as _overlap

        orep = _overlap.build_report(
            by_rank, gbps=args.peak_gbps, alpha=args.alpha_s
        )
        if not orep["ranks"]:
            orep = None
    except Exception:  # pragma: no cover — overlap section best-effort
        orep = None
    if args.json:
        if orep is not None:
            # armed runs only (streams with step spans): the overlap
            # observatory's predicted-vs-achieved route rows ride along
            result = dict(
                result,
                overlap={"totals": orep["totals"],
                         "routes": orep["routes"]},
            )
        print(json.dumps(result, indent=1, default=str))
    else:
        print(format_table(result))
        if orep is not None:
            print()
            print(_overlap.format_exposed(orep))
    if args.output:
        history_rows = (
            load_history(args.history_dir) if args.history_dir else None
        )
        write_markdown(
            args.output, result, inputs=args.inputs,
            history_rows=history_rows,
        )
        print(f"# markdown report written to {args.output}", file=sys.stderr)
    return 0


def _cmd_history(args: argparse.Namespace) -> int:
    rows = load_history(args.dir, variant=args.variant)
    if args.json:
        print(json.dumps(rows, indent=1))
    else:
        print(format_history(rows))
    return 0 if rows else 2


def _attribution_or_bench(path: str):
    """compare operand: a BENCH_*.json file -> ("bench", row); a file
    or directory of event logs -> ("events", attribution result)."""
    if os.path.isfile(path) and _BENCH_RE.search(os.path.basename(path)):
        row = parse_bench_file(path)
        if row is not None:
            return "bench", row
    by_rank = _load_rank_records([path])
    if not by_rank:
        return None, None
    return "events", attribute(by_rank)


def _cmd_compare(args: argparse.Namespace) -> int:
    kind_a, a = _attribution_or_bench(args.a)
    kind_b, b = _attribution_or_bench(args.b)
    if a is None or b is None or kind_a != kind_b:
        print(
            "perf compare: operands must both be BENCH_*.json files or "
            "both be event logs/dirs with records",
            file=sys.stderr,
        )
        return 2
    if kind_a == "bench":
        delta = b["value"] - a["value"]
        pct = (100.0 * delta / a["value"]) if a["value"] else 0.0
        print(
            f"{a['metric']}: {a['value']:g}s -> {b['value']:g}s "
            f"({pct:+.1f}%)"
        )
        regressed = b["value"] > a["value"] * (1 + args.tolerance)
        print("verdict:", "REGRESSED" if regressed else "ok")
        return 1 if regressed else 0
    rows_a = {
        (r["op"], r["axes"], r.get("impl")): r for r in a["rows"]
    }
    regressed = False
    for r in b["rows"]:
        prev = rows_a.get((r["op"], r["axes"], r.get("impl")))
        cur_p50, prev_p50 = r.get("lat_p50_s"), (
            prev.get("lat_p50_s") if prev else None
        )
        if prev is None or cur_p50 is None or prev_p50 is None:
            note = "(new)" if prev is None else "(no samples)"
            print(f"{r['op']}@{r['axes']}: {note}")
            continue
        pct = 100.0 * (cur_p50 - prev_p50) / prev_p50 if prev_p50 else 0.0
        worse = cur_p50 > prev_p50 * (1 + args.tolerance)
        regressed |= worse
        print(
            f"{r['op']}@{r['axes']}: p50 {_fmt_s(prev_p50)} -> "
            f"{_fmt_s(cur_p50)} ({pct:+.1f}%)"
            f"{'  REGRESSED' if worse else ''}"
        )
    return 1 if regressed else 0


def _cmd_gate(args: argparse.Namespace) -> int:
    rows = load_history(args.dir, variant=args.variant)
    verdict = gate_history(
        rows, tolerance=args.tolerance, min_history=args.min_history
    )
    if args.json:
        print(json.dumps(verdict, indent=1, default=str))
    else:
        latest = verdict.get("latest")
        if latest:
            print(
                f"gate: latest round {latest['round']} "
                f"({os.path.basename(latest['file'])}) value "
                f"{latest['value']:g}{latest.get('unit') or ''} vs "
                f"prior rounds {verdict.get('prior_rounds')}"
            )
            if "baseline" in verdict:
                print(
                    f"gate: baseline median {verdict['baseline']:g}, "
                    f"limit {verdict['limit']:g} "
                    f"(+{int(args.tolerance * 100)}% noise band)"
                )
        print(f"gate: {verdict['verdict']}")
    if verdict["verdict"] == "no_history":
        return 2
    return 0 if verdict["ok"] else 1


def selftest() -> int:
    """Device-free smoke over synthetic artifacts: attribution from
    synthetic 2-rank event records, markdown writing, history parsing,
    and both gate verdicts (clean passes, synthetic regression fails).
    Invoked by CI (tests/test_perf.py) so the CLI cannot silently rot.
    """
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        # -- synthetic 2-rank run: 3 allreduces + latency samples ------
        for rank in (0, 1):
            path = os.path.join(tmp, f"events-rank{rank}.jsonl")
            with open(path, "w") as f:
                for seq in range(1, 4):
                    cid = f"c{rank}{seq}"
                    f.write(json.dumps({
                        "kind": "emission", "rank": rank, "seq": seq,
                        "op": "AllReduce", "bytes": 4096,
                        "dtype": "float32", "axes": ["ranks"],
                        "world": 2, "cid": cid, "t": 100.0 + seq,
                    }) + "\n")
                    f.write(json.dumps({
                        "kind": "latency", "rank": rank, "op": "AllReduce",
                        "cid": cid, "seq": seq,
                        "seconds": 0.001 * (1 + rank),
                        "t": 100.1 + seq,
                    }) + "\n")
        by_rank = _load_rank_records([tmp])
        assert sorted(by_rank) == [0, 1], by_rank
        result = attribute(by_rank, peak=100.0)
        (row,) = result["rows"]
        assert row["op"] == "AllReduce" and row["emissions"] == 6
        assert row["wire_bytes"] == 4096  # 2*(n-1)/n * 4096, n=2
        assert row["samples"] == 6
        for field in ("achieved_gbps", "pct_of_peak", "lat_p50_s"):
            value = row[field]
            assert isinstance(value, float) and value > 0, (field, value)
        md = os.path.join(tmp, "PERF_REPORT.md")
        write_markdown(md, result, inputs=[tmp])
        assert "Achieved bandwidth" in open(md).read()

        # -- synthetic bench trajectory: clean passes, regression fails -
        hist = os.path.join(tmp, "hist")
        os.makedirs(hist)
        for n, value in ((1, 100.0), (2, 90.0), (3, 85.0)):
            with open(os.path.join(hist, f"BENCH_r{n:02d}.json"), "w") as f:
                json.dump({
                    "n": n, "cmd": "python bench.py", "rc": 0, "tail": "",
                    "parsed": {"metric": "m", "value": value, "unit": "s",
                               "vs_baseline": None, "nproc": 1},
                }, f)
        rows = load_history(hist)
        assert [r["round"] for r in rows] == [1, 2, 3]
        good = gate_history(rows)
        assert good["verdict"] == "ok" and good["ok"], good
        with open(os.path.join(hist, "BENCH_r04.json"), "w") as f:
            json.dump({
                "n": 4, "cmd": "python bench.py", "rc": 0, "tail": "",
                "parsed": {"metric": "m", "value": 400.0, "unit": "s",
                           "vs_baseline": None, "nproc": 1},
            }, f)
        bad = gate_history(load_history(hist))
        assert bad["verdict"] == "regressed" and not bad["ok"], bad

        # -- the watch flags a slow outlier and only that --------------
        watch = PerfWatch(z=6.0, warmup=5, emit=False)
        anomalies = []
        for i in range(20):
            a = watch.observe("AllReduce[1Kx4:f32]@ranks", 0.001)
            assert a is None, a
        anomalies.append(watch.observe("AllReduce[1Kx4:f32]@ranks", 0.5))
        assert anomalies[-1] is not None and anomalies[-1]["z"] >= 6.0
    print("perf selftest ok")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--selftest" in argv:
        return selftest()
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.observability.perf",
        description=(
            "Collective performance attribution (achieved bandwidth vs "
            "the analytic cost model) and bench-history regression "
            "gating. `--selftest` runs a device-free smoke."
        ),
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_report = sub.add_parser(
        "report", help="achieved-bandwidth table from run event logs"
    )
    p_report.add_argument(
        "inputs", nargs="+",
        help="per-rank .jsonl files / directories (launch --events-dir)",
    )
    p_report.add_argument(
        "-o", "--output", default=None, metavar="PERF_REPORT.md",
        help="additionally write a markdown report here",
    )
    p_report.add_argument(
        "--history-dir", default=None, metavar="DIR",
        help="include the BENCH_*.json trajectory from DIR in the "
        "markdown report",
    )
    p_report.add_argument("--json", action="store_true")
    p_report.add_argument(
        "--peak-gbps", type=float, default=None, metavar="G",
        help="peak link bandwidth (default: M4T_PEAK_GBPS, else the "
        "generation table, else the conservative fallback)",
    )
    p_report.add_argument(
        "--alpha-s", type=float, default=None, metavar="S",
        help="per-step latency term in seconds (default: M4T_ALPHA_US)",
    )
    p_report.set_defaults(func=_cmd_report)

    p_hist = sub.add_parser(
        "history", help="parse the BENCH_*.json benchmark trajectory"
    )
    p_hist.add_argument(
        "--dir", default=".", help="directory holding BENCH_*.json "
        "(default: cwd)",
    )
    p_hist.add_argument(
        "--variant", default="", metavar="V",
        help="trajectory variant: '' = BENCH_rNN.json, 'tpu' = "
        "BENCH_rNN_tpu.json, ...",
    )
    p_hist.add_argument("--json", action="store_true")
    p_hist.set_defaults(func=_cmd_history)

    p_cmp = sub.add_parser(
        "compare", help="compare two runs (event dirs or BENCH files)"
    )
    p_cmp.add_argument("a")
    p_cmp.add_argument("b")
    p_cmp.add_argument(
        "--tolerance", type=float, default=DEFAULT_TOLERANCE,
        help="relative noise band (default %(default)s)",
    )
    p_cmp.set_defaults(func=_cmd_compare)

    p_gate = sub.add_parser(
        "gate",
        help="exit 1 when the latest comparable BENCH round regressed "
        "beyond the noise band (the CI hook)",
    )
    p_gate.add_argument("--dir", default=".")
    p_gate.add_argument("--variant", default="", metavar="V")
    p_gate.add_argument(
        "--tolerance", type=float, default=DEFAULT_TOLERANCE,
        help="relative noise band (default %(default)s)",
    )
    p_gate.add_argument(
        "--min-history", type=int, default=DEFAULT_MIN_HISTORY,
        help="prior comparable rounds required before the gate may "
        "fail (default %(default)s)",
    )
    p_gate.add_argument("--json", action="store_true")
    p_gate.set_defaults(func=_cmd_gate)

    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
