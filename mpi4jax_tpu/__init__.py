"""mpi4jax_tpu — TPU-native communication primitives for JAX.

A from-scratch rebuild of the capabilities of mpi4jax (reference:
``mpi4jax/__init__.py:26-41``) designed TPU-first: the twelve
collective / point-to-point operations are JAX primitives whose
lowerings emit **native XLA HLO collectives** (AllReduce, AllGather,
AllToAll, CollectivePermute) over a ``jax.sharding.Mesh`` axis, instead
of MPI custom-calls through a C extension. Communicators map onto mesh
axes; ranks are ``lax.axis_index``; the launch model is
``jax.distributed.initialize()`` + a global mesh rather than ``mpirun``.

Ordering parity: the reference serializes all communication ops with a
JAX ordered effect + XLA token threading (``_src/utils.py:45-53``).
Ordered effects are not usable inside ``shard_map``, so this package
achieves the same program-order guarantee with an ambient
``optimization_barrier`` token chain (see ``mpi4jax_tpu/token.py``).

Differentiation parity: ``allreduce`` is differentiable for ``SUM`` with
JVP = allreduce-of-tangents and transpose = identity (reference
``collective_ops/allreduce.py:138-159``); ``sendrecv`` transposes by
swapping source and destination (``collective_ops/sendrecv.py:278-293``).
"""

__version__ = "0.1.0"

import os as _os

from .jax_compat import check_jax_version as _check_jax_version
from .jax_compat import install_shims as _install_shims

_check_jax_version()  # reference parity: _src/__init__.py:6-8
_install_shims()

from .comm import (  # noqa: F401
    ANY_SOURCE,
    ANY_TAG,
    BAND,
    BOR,
    BXOR,
    CartComm,
    Comm,
    GroupComm,
    LAND,
    LOR,
    LXOR,
    MAX,
    MIN,
    Op,
    PROC_NULL,
    PROD,
    Status,
    SUM,
    get_default_comm,
    resolve_comm,
)
from .ops.quantized import quantized_allreduce  # noqa: F401
from .ops import (  # noqa: F401
    allgather,
    allreduce,
    alltoall,
    barrier,
    bcast,
    gather,
    recv,
    reduce,
    reduce_scatter,
    scan,
    scatter,
    send,
    sendrecv,
)
from .debug import get_logging, set_logging  # noqa: F401

# Join the native shm world when launched by `python -m
# mpi4jax_tpu.launch` — import-time analog of the reference's
# mpi4py-first import triggering MPI_Init (_src/__init__.py:1-3).
if _os.environ.get("M4T_SHM_NAME"):
    from .runtime import shm as _shm_runtime

    _shm_runtime.init_from_env()
    ShmComm = _shm_runtime.ShmComm
else:
    def ShmComm():  # type: ignore
        raise RuntimeError(
            "no shm world active; run under `python -m mpi4jax_tpu.launch`"
        )


def has_tpu_support() -> bool:
    """True if a TPU backend is available to JAX.

    Analog of the reference capability queries ``has_cuda_support`` /
    ``has_sycl_support`` (``mpi4jax/__init__.py``).
    """
    import jax

    try:
        return any(d.platform in ("tpu", "axon") for d in jax.devices())
    except RuntimeError:
        return False


def has_cuda_support() -> bool:
    """Compatibility shim: this package has no CUDA/MPI bridge."""
    return False


def has_sycl_support() -> bool:
    """Compatibility shim: this package has no SYCL/MPI bridge."""
    return False


def has_shm_support() -> bool:
    """True if the native shared-memory CPU backend extension is built."""
    try:
        from .runtime import shm  # noqa: F401
    except Exception:
        return False
    return shm.available()


__all__ = [
    "allgather",
    "allreduce",
    "alltoall",
    "barrier",
    "bcast",
    "gather",
    "recv",
    "reduce",
    "reduce_scatter",
    "quantized_allreduce",
    "scan",
    "scatter",
    "send",
    "sendrecv",
    "Comm",
    "CartComm",
    "GroupComm",
    "Op",
    "SUM",
    "PROD",
    "MAX",
    "MIN",
    "LAND",
    "LOR",
    "LXOR",
    "BAND",
    "BOR",
    "BXOR",
    "PROC_NULL",
    "ANY_TAG",
    "ANY_SOURCE",
    "Status",
    "get_default_comm",
    "resolve_comm",
    "has_tpu_support",
    "has_cuda_support",
    "has_sycl_support",
    "has_shm_support",
    "set_logging",
    "get_logging",
]
