"""Self-healing restart supervisor: diagnose, classify, resume.

Closes the loop PRs 1–4 left open. The diagnosis pipeline can *name*
a failure (``observability/doctor.py``: MISMATCH / HANG / STRAGGLER /
missing rank); this module decides what the name *means* for recovery
and acts on it:

============================  ==============  =======================
doctor verdict                class           supervisor action
============================  ==============  =======================
MISMATCH (ranks diverged)     deterministic   fail fast — a program
                                              that forked will fork
                                              again; print diagnosis
MISMATCH w/ static site join  deterministic   fail fast (the bug has
                                              a source line)
HANG / RANK DIED / BEHIND     transient       restart from the latest
                                              valid checkpoint
MISSING RANK                  transient       restart (preemption /
                                              kill shape)
STRAGGLER only                transient       restart (slow host)
crash, no findings            transient       restart (the crash left
                                              no cross-rank disagree-
                                              ment — env/infra shape)
no telemetry at all           transient       restart blind
exit 143 (PREEMPT_EXIT)       transient       restart; under ``launch
                                              --elastic`` the world
                                              *shrinks* to the
                                              survivors and the
                                              checkpoint is resharded
============================  ==============  =======================

Restarts are bounded (``retries``) with exponential backoff plus
jitter (thundering-herd hygiene — all of a fleet's supervisors backing
off in lockstep re-collide forever). Before each restart the newest
*valid* checkpoint (``resilience/ckpt.py``) is located and exported to
every child via ``M4T_RESUME_STEP``; a training loop that honors
:func:`resume_step` continues from there instead of step 0.

Every attempt's outcome — exit code, doctor verdict classification,
chosen action, backoff, resume step — is appended to a
``supervisor.jsonl`` audit log (the JSONL schema everything else in
this repo speaks), so a run that restarted three times at 2 a.m.
explains itself in the morning.

Driven by ``python -m mpi4jax_tpu.launch --retries K --backoff S
--resume-dir CKPTROOT``; importable directly for custom harnesses.
"""

from __future__ import annotations

import os
import random
import signal
import sys
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Optional

#: finding kinds that mean "the program itself diverged" — re-running
#: deterministically reproduces them, so retrying is burning compute
DETERMINISTIC_KINDS = frozenset({"mismatch"})

#: finding kinds consistent with infrastructure trouble — worth a retry
TRANSIENT_KINDS = frozenset({"hang", "missing_rank", "straggler"})

#: launcher exit code when the hang watchdog tore the world down
WATCHDOG_EXIT = 124

#: exit code of a rank that received a preemption notice (SIGTERM) and
#: left gracefully — 128 + SIGTERM, the shell's own convention for a
#: TERM death, so guarded and unguarded preemptions read the same.
#: ``launch --elastic`` counts ranks with this signature as *capacity
#: lost*, not a bug, and restarts the world smaller.
PREEMPT_EXIT = 143


def resume_step() -> Optional[int]:
    """The step the supervisor resumed this process from
    (``M4T_RESUME_STEP``), or None on a cold start. Training loops
    call this and skip to step+1."""
    raw = os.environ.get("M4T_RESUME_STEP", "")
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


class PreemptGuard:
    """The SIGTERM grace hook for resume-aware loops.

    A cloud preemption notice is a SIGTERM with a short grace window.
    The default Python behavior — die mid-step, possibly mid-collective
    — wastes the window; this guard converts the signal into a *flag*
    so the loop finishes the step it is in, checkpoints, and leaves
    with :data:`PREEMPT_EXIT`::

        guard = PreemptGuard()          # installs the handler
        for step in range(start, steps):
            if guard.preempted:
                mgr.save(step - 1, state)        # or skip: last
                sys.exit(guard.exit_code)        # committed step wins
            state = train_step(state)

    The handler only sets the flag (async-signal-safe by construction)
    — the flight recorder still dumps from its own atexit hook on the
    way out, so a preempted rank leaves the same artifact trail a
    crashed one does, plus the checkpoint. ``install=False`` builds an
    unarmed guard (tests).

    A **second** notice arriving while the grace checkpoint is already
    running escalates to an immediate exit with :data:`PREEMPT_EXIT`:
    the platform is done waiting, and re-entering the checkpoint from
    the handler would interrupt the very save this thread is
    mid-write in. The interrupted save is torn-but-harmless (the
    tmp+rename commit protocol never exposes it) and the previous
    committed step remains the resume point — losing one step beats
    wedging in a recursive save until SIGKILL."""

    exit_code = PREEMPT_EXIT

    def __init__(self, *, install: bool = True,
                 signum: int = signal.SIGTERM):
        self.preempted = False
        self.signum = signum
        self._count = 0
        self._checkpointing = False
        if install:
            signal.signal(signum, self._on_signal)

    def _exit_now(self) -> None:
        """Immediate exit from the signal handler. ``os._exit`` on
        purpose: atexit hooks and finalizers may allocate/lock, which
        a handler interrupting a checkpoint write must not do (the
        flight recorder's SIGTERM dump already ran at the *first*
        notice if armed). Patched by the double-signal unit test."""
        os._exit(self.exit_code)

    def _on_signal(self, signum, frame):
        self.preempted = True
        self._count += 1
        if self._count == 1:
            # write() is async-signal-safe; formatting a message is
            # fine here because we are in the main thread's handler
            try:
                sys.stderr.write(
                    "m4t.resilience: preemption notice (SIGTERM) — "
                    "finishing the current step, then checkpoint + "
                    f"exit {PREEMPT_EXIT}\n"
                )
                sys.stderr.flush()
            except Exception:
                pass
        elif self._checkpointing:
            # escalation: the grace window is over mid-checkpoint —
            # no re-entrant save, just leave with the preemption code
            try:
                sys.stderr.write(
                    "m4t.resilience: second preemption notice during "
                    "the grace checkpoint — exiting immediately with "
                    f"{PREEMPT_EXIT} (last committed step wins)\n"
                )
                sys.stderr.flush()
            except Exception:
                pass
            self._exit_now()

    def exit_if_preempted(
        self, save_fn: Optional[Callable[[], Any]] = None
    ) -> None:
        """Call at a step boundary: if a notice arrived, run
        ``save_fn`` (the checkpoint) and leave with
        :data:`PREEMPT_EXIT`. While ``save_fn`` runs the guard is in
        its *checkpointing* window: a further notice exits on the
        spot instead of re-entering the save."""
        if not self.preempted:
            return
        if save_fn is not None:
            self._checkpointing = True
            try:
                save_fn()
            finally:
                self._checkpointing = False
        sys.exit(self.exit_code)


def classify_findings(
    findings: Iterable[Dict[str, Any]],
) -> Dict[str, Any]:
    """The finding-level half of :func:`classify`: map a list of
    doctor findings (offline report *or* the streaming doctor's live
    verdicts — same schema) to::

        {"klass": "clean" | "transient" | "deterministic",
         "reason": <short machine-readable tag>,
         "kinds": [finding kinds seen]}

    Deterministic wins over transient when both appear: a mismatch
    usually *causes* the hang recorded beside it. The streaming
    doctor (``observability/stream_doctor.py``) stamps this verdict
    on every live ``verdict`` event, so a mid-run escalation already
    carries the recovery class the supervisor would assign
    post-mortem.
    """
    findings = list(findings or [])
    kinds = sorted({f.get("kind", "?") for f in findings})
    det = [f for f in findings if f.get("kind") in DETERMINISTIC_KINDS]
    if det:
        reason = "mismatch"
        if any(
            site
            for f in det
            for g in f.get("groups", [])
            for site in g.get("static_sites", ())
        ):
            reason = "mismatch_static_attributed"
        return {"klass": "deterministic", "reason": reason, "kinds": kinds}
    if any(f.get("kind") in TRANSIENT_KINDS for f in findings):
        return {
            "klass": "transient", "reason": "transient_findings",
            "kinds": kinds,
        }
    return {"klass": "clean", "reason": "no_findings", "kinds": kinds}


def classify(
    report: Optional[Dict[str, Any]], exit_code: int
) -> Dict[str, Any]:
    """Map a doctor report (``doctor.analyze`` output, or None when no
    telemetry was readable) plus the world's exit code to a recovery
    class (:func:`classify_findings` payload shape)."""
    if exit_code == 0:
        return {"klass": "clean", "reason": "exit_zero", "kinds": []}
    if report is None:
        if exit_code == PREEMPT_EXIT:
            return {
                "klass": "transient", "reason": "preempted", "kinds": [],
            }
        return {
            "klass": "transient", "reason": "crash_no_telemetry",
            "kinds": [],
        }
    verdict = classify_findings(report.get("findings", []))
    if verdict["klass"] == "deterministic":
        return verdict
    if exit_code == PREEMPT_EXIT:
        # a rank said "I was preempted" on its way out: capacity loss,
        # not a bug — transient regardless of the hang/missing shapes
        # the surviving ranks' logs show (they were waiting on it)
        return {
            "klass": "transient", "reason": "preempted",
            "kinds": verdict["kinds"],
        }
    if verdict["klass"] == "transient":
        if exit_code == WATCHDOG_EXIT:
            verdict = dict(verdict, reason="hang")
        return verdict
    return {
        "klass": "transient", "reason": "crash_without_mismatch",
        "kinds": verdict["kinds"],
    }


@dataclass
class RetryPolicy:
    """Bounded exponential backoff with jitter."""

    retries: int = 0          # restarts after the first attempt
    backoff_s: float = 1.0    # first delay
    max_backoff_s: float = 60.0
    jitter: float = 0.25      # +- fraction of the delay

    def delay(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        """Delay before launching attempt ``attempt`` (attempt 0 never
        waits)."""
        if attempt <= 0:
            return 0.0
        base = min(
            self.backoff_s * (2.0 ** (attempt - 1)), self.max_backoff_s
        )
        if self.jitter <= 0:
            return base
        r = (rng or random).uniform(-self.jitter, self.jitter)
        return max(0.0, base * (1.0 + r))


class Supervisor:
    """Run a world-launching callable under the retry policy.

    ``run_fn(attempt, resume_step) -> exit_code`` launches one world
    attempt (the launcher passes a closure over its own spawn loop).
    ``diagnose_fn(attempt) -> report|None`` produces the doctor report
    for that attempt's artifacts. ``resume_fn() -> step|None`` names
    the newest valid checkpoint step (queried fresh before every
    restart — the failed attempt may have committed new checkpoints
    before dying). ``extra_fn(attempt) -> dict`` contributes
    additional fields to that attempt's audit record — the elastic
    launcher uses it to put world-size transitions (old world, new
    world, reshard source step) on the ``supervisor.jsonl`` record so
    the doctor can narrate an elastic recovery post-mortem.
    ``abort_fn(attempt) -> reason|None`` is consulted before every
    retry a transient verdict would otherwise earn: a non-None reason
    vetoes the remaining budget (audited ``action: "abort"`` with that
    reason) — the serving pool's poisoned-job two-strikes rule.
    ``span_fn(name, t0, t1, **fields)`` receives one
    ``attempt<k>`` lifecycle span per attempt (start/end of that
    attempt's ``run_fn``, with ``attempt`` and ``exit_code`` fields) —
    the serving plane routes these onto the job's distributed trace
    (``observability/spans.py``); best-effort, like the audit.
    """

    def __init__(
        self,
        run_fn: Callable[[int, Optional[int]], int],
        *,
        policy: RetryPolicy,
        diagnose_fn: Optional[Callable[[int], Optional[Dict[str, Any]]]] = None,
        resume_fn: Optional[Callable[[], Optional[int]]] = None,
        extra_fn: Optional[Callable[[int], Dict[str, Any]]] = None,
        abort_fn: Optional[Callable[[int], Optional[str]]] = None,
        span_fn: Optional[Callable[..., None]] = None,
        audit_path: Optional[str] = None,
        sleep_fn: Callable[[float], None] = time.sleep,
        log: Optional[Callable[[str], None]] = None,
    ):
        self.run_fn = run_fn
        self.policy = policy
        self.diagnose_fn = diagnose_fn or (lambda attempt: None)
        self.resume_fn = resume_fn or (lambda: None)
        self.extra_fn = extra_fn or (lambda attempt: {})
        self.abort_fn = abort_fn or (lambda attempt: None)
        self.span_fn = span_fn
        self.audit_path = audit_path
        self.sleep_fn = sleep_fn
        self.log = log or (lambda msg: None)
        self._rng = random.Random(0xC0FFEE)
        self.attempts: list = []

    def _audit(self, record: Dict[str, Any]) -> None:
        self.attempts.append(record)
        if not self.audit_path:
            return
        from ..observability import events

        try:
            events.EventLog(self.audit_path).append(
                events.event("supervisor", **record)
            )
        except OSError:
            pass  # auditing must not mask the run's own outcome

    def _audit_attempt(
        self, attempt: int, record: Dict[str, Any]
    ) -> None:
        try:
            extra = dict(self.extra_fn(attempt) or {})
        except Exception:
            extra = {}
        extra.update(record)
        self._audit(extra)

    def _span(self, name: str, t0: float, t1: float, **fields: Any) -> None:
        if self.span_fn is None:
            return
        try:
            self.span_fn(name, t0, t1, **fields)
        except Exception:
            pass  # span recording must never mask the run's outcome

    def run(self, resume0: Optional[int] = None) -> int:
        """Drive attempts until success or budget exhaustion.

        ``resume0`` seeds the first attempt's resume step — the
        serving plane passes the newest checkpoint step when it
        re-runs a job reclaimed from a dead server, so attempt 0
        already starts warm instead of from step 0."""
        resume: Optional[int] = (
            resume0 if resume0 is not None
            else resume_step()  # inherit if nested
        )
        exit_code = 0
        for attempt in range(self.policy.retries + 1):
            attempt_t0 = time.time()
            exit_code = self.run_fn(attempt, resume)
            self._span(
                f"attempt{attempt}", attempt_t0, time.time(),
                attempt=attempt, exit_code=exit_code,
                resume_step=resume,
            )
            if exit_code == 0:
                self._audit_attempt(attempt, {
                    "attempt": attempt, "exit_code": 0,
                    "klass": "clean", "reason": "exit_zero",
                    "action": "done", "resume_step": resume,
                })
                return 0
            if exit_code == 130:
                # SIGINT is the operator, not the infrastructure:
                # never retried, never reclassified
                self._audit_attempt(attempt, {
                    "attempt": attempt, "exit_code": 130,
                    "klass": "interrupted", "reason": "sigint",
                    "action": "give_up", "resume_step": resume,
                })
                return 130
            report = self.diagnose_fn(attempt)
            verdict = classify(report, exit_code)
            last = attempt == self.policy.retries
            retrying = verdict["klass"] == "transient" and not last
            # an external veto on further attempts: the serving pool
            # uses this for its two-strikes poisoned-job rule — a job
            # that keeps wedging workers must stop consuming the mesh
            # even while its transient-looking retry budget remains
            abort_reason = None
            if retrying:
                try:
                    abort_reason = self.abort_fn(attempt)
                except Exception:
                    abort_reason = None
                if abort_reason:
                    retrying = False
            delay = self.policy.delay(attempt + 1, self._rng) if retrying else 0.0
            next_resume = self.resume_fn() if retrying else None
            self._audit_attempt(attempt, {
                "attempt": attempt,
                "exit_code": exit_code,
                "klass": verdict["klass"],
                "reason": abort_reason or verdict["reason"],
                "finding_kinds": verdict["kinds"],
                "action": (
                    "retry" if retrying
                    else "abort" if abort_reason else "give_up"
                ),
                "backoff_s": round(delay, 3),
                "resume_step": next_resume,
            })
            if abort_reason:
                self.log(
                    f"supervisor: attempt {attempt} failed and further "
                    f"attempts are vetoed ({abort_reason}); giving up"
                )
                return exit_code
            if verdict["klass"] == "deterministic":
                self.log(
                    f"supervisor: attempt {attempt} failed "
                    f"deterministically ({verdict['reason']}); not "
                    "retrying — rerunning a diverged program reproduces "
                    "the divergence"
                )
                return exit_code
            if not retrying:
                self.log(
                    f"supervisor: attempt {attempt} failed "
                    f"({verdict['reason']}); retry budget exhausted"
                )
                return exit_code
            resume = next_resume
            self.log(
                f"supervisor: attempt {attempt} failed transiently "
                f"({verdict['reason']}); restarting in {delay:.1f}s"
                + (
                    f" from checkpoint step {resume}"
                    if resume is not None else " from step 0"
                )
            )
            if delay > 0:
                self.sleep_fn(delay)
        return exit_code
