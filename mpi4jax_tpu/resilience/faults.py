"""Deterministic fault injection at collective-emission sites.

Chaos testing for the diagnosis pipeline: every failure mode the
doctor can name (``observability/doctor.py`` — mismatch, hang, dead
rank, straggler) and the supervisor can recover from
(``resilience/supervisor.py``) must be *provokable on demand*, or the
recovery path is tested only by production incidents. Cloud
Collectives (PAPERS.md) makes the same argument from the other side:
cloud fleets see preemptions and slow hosts as a matter of course, so
the communication layer has to be designed — and exercised — against
them.

A **fault plan** is a JSON spec of injection rules, armed through
``M4T_FAULT_PLAN=<path-or-inline-json>`` (``launch --fault-plan`` sets
it for every rank). Each rule names *where* (rank, op or fingerprint,
Nth matching emission) and *what* (the action)::

    {"seed": 0, "faults": [
      {"rank": 1, "op": "AllReduce", "nth": 6,
       "action": "crash", "mode": "exception"},
      {"rank": 0, "op": "*", "nth": 3, "action": "delay", "ms": 250},
      {"rank": "*", "op": "Barrier", "nth": 2, "action": "hang"},
      {"rank": 1, "fingerprint": "AllGather[4:float32]@<none>",
       "nth": 1, "action": "slowdown", "ms": 50}
    ]}

(A bare JSON list is accepted as shorthand for ``{"faults": [...]}``.)

Actions:

- ``delay`` — sleep ``ms`` once, at the Nth matching emission;
- ``slowdown`` — sleep ``ms`` at *every* matching emission from the
  Nth on (a synthetic straggler);
- ``hang`` — stop emitting forever (heartbeats continue from their
  daemon thread, so the doctor's verdict is *hung*, not *dead*);
- ``wedge`` — ``hang``'s silent sibling: block forever inside the
  emission hook *and* silence the heartbeat daemon
  (``events.silence_heartbeat``). No emissions, no heartbeats, no
  exit — the shape of a process wedged in native code holding the
  GIL, where not even the heartbeat thread runs. Invisible to
  anything that waits for an exit code; only an external heartbeat
  deadline — the serving pool doctor's
  (``serving/pool.py``) — can name it, which is exactly what makes
  pool wedge-detection deterministically testable device-free;
- ``crash`` — ``mode: "exception"`` (default) raises
  :class:`InjectedFault` at the emission site, ``mode: "sigkill"``
  sends this process SIGKILL (no atexit, no recorder dump — the
  doctor's *dead/missing* evidence path);
- ``preempt`` — sends this process SIGTERM, the cloud preemption
  notice shape. Unlike ``crash`` the signal is *survivable*: a train
  loop that installed :class:`~.supervisor.PreemptGuard` finishes its
  step, checkpoints, and exits ``PREEMPT_EXIT`` (143) — which is what
  lets ``launch --elastic`` tell "this rank was preempted" apart from
  "this rank crashed" and restart the world *smaller* instead of
  dead. Without a guard the default handler terminates the process
  (the same 143-family signature, via the signal exit).

Determinism: matching is by exact per-rank emission counting (token
ordering serializes emissions, so "the Nth AllReduce on rank 1" names
one specific program point), and the optional per-rule probability
``p`` draws from ``random.Random(seed ^ rank)`` — the same plan on the
same rank always injects at the same sites. ``attempt`` scopes a rule
to one supervisor attempt (``M4T_FAULT_ATTEMPT``, set by the
launcher's retry loop; default ``null`` = every attempt).

The hook lives at the end of ``ops/_core.py``'s telemetry prologue —
*after* the flight recorder and event sink record the emission, so an
injected crash leaves exactly the artifact trail a real one would,
plus one ``fault`` JSONL record naming the injection (the doctor and
trace viewer can then overlay injected vs observed failures). Unarmed
(no ``M4T_FAULT_PLAN``, the default) the hook is a single
module-attribute ``is None`` check.
"""

from __future__ import annotations

import json
import os
import random
import signal
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

#: emission-vocabulary op names (ops/_core.emit callers); a rule naming
#: an op outside this set is a typo caught at parse time, not a rule
#: that silently never fires
KNOWN_OPS = frozenset({
    "AllGather", "AllReduce", "AllToAll", "Barrier", "Bcast", "Gather",
    "QuantizedAllReduce", "Recv", "Reduce", "ReduceScatter", "Scan",
    "Scatter", "Send", "Sendrecv",
})

ACTIONS = ("delay", "hang", "crash", "slowdown", "preempt", "wedge")
CRASH_MODES = ("exception", "sigkill")


class FaultPlanError(ValueError):
    """A fault-plan spec that cannot mean what was written."""


class InjectedFault(RuntimeError):
    """Raised at an emission site by a ``crash``-action rule
    (``mode: "exception"``)."""


@dataclass
class FaultRule:
    """One armed injection site."""

    action: str
    rank: Any = "*"              # int | list[int] | "*"
    op: Optional[str] = None     # emission op name | "*" | None
    fingerprint: Optional[str] = None  # exact recorder fingerprint
    nth: int = 1                 # 1-based Nth matching emission
    ms: float = 0.0              # delay/slowdown sleep
    mode: str = "exception"      # crash mode
    p: float = 1.0               # injection probability (seeded)
    attempt: Optional[int] = None  # only on this supervisor attempt
    index: int = 0               # position in the plan (audit key)
    # runtime state (per-process):
    matches: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def applies_to_rank(self, rank: int) -> bool:
        if self.rank == "*":
            return True
        if isinstance(self.rank, list):
            return rank in self.rank
        return rank == self.rank

    def matches_emission(self, op: str, fingerprint: str) -> bool:
        if self.fingerprint is not None:
            return fingerprint == self.fingerprint
        return self.op == "*" or op == self.op


def _parse_rank(value: Any, where: str) -> Any:
    if value == "*":
        return "*"
    if isinstance(value, bool):
        raise FaultPlanError(f"{where}: rank must be an int, list, or '*'")
    if isinstance(value, int):
        if value < 0:
            raise FaultPlanError(f"{where}: rank {value} is negative")
        return value
    if isinstance(value, list) and value and all(
        isinstance(v, int) and not isinstance(v, bool) and v >= 0
        for v in value
    ):
        return value
    raise FaultPlanError(
        f"{where}: rank must be a non-negative int, a non-empty list of "
        f"them, or '*' (got {value!r})"
    )


def _parse_rule(obj: Any, index: int) -> FaultRule:
    where = f"faults[{index}]"
    if not isinstance(obj, dict):
        raise FaultPlanError(f"{where}: each fault must be a JSON object")
    unknown = set(obj) - {
        "rank", "op", "fingerprint", "nth", "action", "ms", "mode", "p",
        "attempt",
    }
    if unknown:
        raise FaultPlanError(
            f"{where}: unknown field(s) {sorted(unknown)}"
        )
    action = obj.get("action")
    if action not in ACTIONS:
        raise FaultPlanError(
            f"{where}: action must be one of {list(ACTIONS)} "
            f"(got {action!r})"
        )
    op = obj.get("op")
    fingerprint = obj.get("fingerprint")
    if op is None and fingerprint is None:
        raise FaultPlanError(f"{where}: needs 'op' or 'fingerprint'")
    if op is not None and fingerprint is not None:
        raise FaultPlanError(
            f"{where}: 'op' and 'fingerprint' are mutually exclusive"
        )
    if op is not None and op != "*" and op not in KNOWN_OPS:
        raise FaultPlanError(
            f"{where}: unknown op {op!r}; emission vocabulary is "
            f"{sorted(KNOWN_OPS)} (or '*')"
        )
    if fingerprint is not None and not isinstance(fingerprint, str):
        raise FaultPlanError(f"{where}: fingerprint must be a string")
    nth = obj.get("nth", 1)
    if not isinstance(nth, int) or isinstance(nth, bool) or nth < 1:
        raise FaultPlanError(
            f"{where}: nth must be a positive integer (got {nth!r})"
        )
    ms = obj.get("ms", 0.0)
    if not isinstance(ms, (int, float)) or isinstance(ms, bool) or ms < 0:
        raise FaultPlanError(
            f"{where}: ms must be a non-negative number (got {ms!r})"
        )
    if action in ("delay", "slowdown") and ms <= 0:
        raise FaultPlanError(
            f"{where}: action {action!r} needs 'ms' > 0"
        )
    mode = obj.get("mode", "exception")
    if mode not in CRASH_MODES:
        raise FaultPlanError(
            f"{where}: crash mode must be one of {list(CRASH_MODES)} "
            f"(got {mode!r})"
        )
    p = obj.get("p", 1.0)
    if not isinstance(p, (int, float)) or isinstance(p, bool) or not (
        0.0 <= p <= 1.0
    ):
        raise FaultPlanError(
            f"{where}: p must be a probability in [0, 1] (got {p!r})"
        )
    attempt = obj.get("attempt")
    if attempt is not None and (
        not isinstance(attempt, int) or isinstance(attempt, bool)
        or attempt < 0
    ):
        raise FaultPlanError(
            f"{where}: attempt must be a non-negative integer or absent"
        )
    return FaultRule(
        action=action,
        rank=_parse_rank(obj.get("rank", "*"), where),
        op=op,
        fingerprint=fingerprint,
        nth=nth,
        ms=float(ms),
        mode=mode,
        p=float(p),
        attempt=attempt,
        index=index,
    )


@dataclass
class FaultPlan:
    rules: List[FaultRule]
    seed: int = 0

    @classmethod
    def parse(cls, spec: Any) -> "FaultPlan":
        """Parse a plan from a JSON string or an already-decoded
        object; raises :class:`FaultPlanError` with the field that is
        wrong, never a bare JSON traceback."""
        if isinstance(spec, (str, bytes)):
            try:
                spec = json.loads(spec)
            except json.JSONDecodeError as e:
                raise FaultPlanError(f"fault plan is not valid JSON: {e}")
        if isinstance(spec, list):
            spec = {"faults": spec}
        if not isinstance(spec, dict):
            raise FaultPlanError(
                "fault plan must be a JSON object {'faults': [...]} or a "
                "bare list of fault rules"
            )
        unknown = set(spec) - {"faults", "seed"}
        if unknown:
            raise FaultPlanError(
                f"fault plan: unknown top-level field(s) {sorted(unknown)}"
            )
        seed = spec.get("seed", 0)
        if not isinstance(seed, int) or isinstance(seed, bool):
            raise FaultPlanError("fault plan: seed must be an integer")
        faults = spec.get("faults")
        if not isinstance(faults, list) or not faults:
            raise FaultPlanError(
                "fault plan: 'faults' must be a non-empty list"
            )
        return cls(
            rules=[_parse_rule(obj, i) for i, obj in enumerate(faults)],
            seed=seed,
        )

    @classmethod
    def load(cls, spec: str) -> "FaultPlan":
        """Parse from a file path (if one exists at ``spec``) or an
        inline JSON string — the ``M4T_FAULT_PLAN`` convention."""
        text = spec
        if os.path.exists(spec):
            with open(spec) as f:
                text = f.read()
        return cls.parse(text)

    def validate_world(self, world: int) -> None:
        """Reject rules naming ranks the world does not have (the
        launcher knows ``-n``; a plan targeting rank 5 of a 2-rank
        world would otherwise silently never fire)."""
        for rule in self.rules:
            ranks = (
                [] if rule.rank == "*"
                else rule.rank if isinstance(rule.rank, list)
                else [rule.rank]
            )
            for r in ranks:
                if r >= world:
                    raise FaultPlanError(
                        f"faults[{rule.index}]: rank {r} out of range for "
                        f"world size {world}"
                    )


# ---------------------------------------------------------------------
# arming and the per-emission hook
# ---------------------------------------------------------------------

#: the armed plan, or None. ``ops/_core.py`` gates its per-emission
#: call on ``faults.active_plan is not None`` — the whole unarmed cost.
active_plan: Optional[FaultPlan] = None

_rank: int = 0
_attempt: int = 0
_rng: Optional[random.Random] = None
_env_checked = False


def arm(
    plan: FaultPlan, *, rank: Optional[int] = None,
    attempt: Optional[int] = None,
) -> None:
    """Activate ``plan`` for this process (tests and chaos harnesses;
    launched ranks arm from ``M4T_FAULT_PLAN`` automatically)."""
    global active_plan, _rank, _attempt, _rng, _env_checked
    from ..observability import events

    _rank = events.current_rank() if rank is None else int(rank)
    _attempt = (
        int(os.environ.get("M4T_FAULT_ATTEMPT", "0") or 0)
        if attempt is None else int(attempt)
    )
    _rng = random.Random(plan.seed ^ (_rank * 0x9E3779B1))
    for rule in plan.rules:
        rule.matches = rule.fired = 0
    active_plan = plan
    _env_checked = True


def disarm() -> None:
    global active_plan
    active_plan = None


def arm_from_env() -> Optional[FaultPlan]:
    """Arm from ``M4T_FAULT_PLAN`` if set; called once lazily from the
    first emission (import order must not matter for launched ranks).
    A malformed plan is a hard error: a chaos run whose faults silently
    never arm would certify nothing."""
    global _env_checked
    _env_checked = True
    spec = os.environ.get("M4T_FAULT_PLAN", "")
    if not spec:
        return None
    plan = FaultPlan.load(spec)
    arm(plan)
    return plan


def _emit_fault_event(rule: FaultRule, op: str, fp: str, cid: str) -> None:
    from ..observability import events

    events.emit(events.event(
        "fault",
        action=rule.action,
        rule=rule.index,
        op=op,
        fingerprint=fp,
        nth=rule.nth,
        match=rule.matches,
        cid=cid,
        attempt=_attempt,
        t=time.time(),
    ))
    sys.stderr.write(
        f"m4t.faults: rank {_rank} injecting {rule.action} at {op} "
        f"(match {rule.matches}, rule {rule.index}, cid {cid})\n"
    )
    sys.stderr.flush()


def on_emission(
    op: str,
    *,
    cid: str = "",
    nbytes: int = 0,
    dtype: Optional[str] = None,
    shape: Optional[Sequence[int]] = None,
    axes: Optional[Sequence[str]] = None,
    world: Optional[int] = None,
) -> None:
    """The ``ops/_core.py`` hook: count this emission against every
    armed rule and perform whatever actions come due. Runs *after* the
    flight recorder / event sink saw the emission, so injected
    failures leave the same artifact trail organic ones do."""
    plan = active_plan
    if plan is None:
        if _env_checked:
            return
        plan = arm_from_env()
        if plan is None:
            return
    from ..observability.recorder import fingerprint as _fingerprint

    fp = _fingerprint({
        "op": op, "bytes": nbytes, "dtype": dtype,
        "shape": None if shape is None else list(shape),
        "axes": list(axes) if axes else [],
    })
    for rule in plan.rules:
        if rule.attempt is not None and rule.attempt != _attempt:
            continue
        if not rule.applies_to_rank(_rank):
            continue
        if not rule.matches_emission(op, fp):
            continue
        rule.matches += 1
        due = (
            rule.matches >= rule.nth  # slowdown: every one from Nth
            if rule.action == "slowdown"
            else rule.matches == rule.nth  # one-shot actions
        )
        if not due:
            continue
        if rule.p < 1.0 and _rng is not None and _rng.random() >= rule.p:
            continue
        rule.fired += 1
        _emit_fault_event(rule, op, fp, cid)
        _perform(rule, op, fp)


def faults_selftest_hook(plan: FaultPlan) -> List[str]:
    """Device-free exercise of arm/match/fire used by the package
    ``--selftest``: arms ``plan`` as rank 0, simulates three AllReduce
    emissions, and returns ``action@op#nth`` labels of the rules that
    fired. Only safe for plans whose rank-0 rules are delays."""
    arm(plan, rank=0, attempt=0)
    try:
        for _ in range(3):
            on_emission(
                "AllReduce", cid="selftest", nbytes=16,
                dtype="float32", shape=(4,), axes=[], world=2,
            )
        return [
            f"{rule.action}@{rule.op}#{rule.nth}"
            for rule in plan.rules
            if rule.fired
        ]
    finally:
        disarm()


def _perform(rule: FaultRule, op: str, fp: str) -> None:
    if rule.action in ("delay", "slowdown"):
        time.sleep(rule.ms / 1000.0)
        return
    if rule.action == "preempt":
        # the preemption notice: SIGTERM to self, then *keep going* —
        # a PreemptGuard-equipped loop finishes the step, checkpoints,
        # and exits PREEMPT_EXIT at the next step boundary; an
        # unguarded process dies on the default handler. Either way
        # the artifacts written so far survive (fsync'd events, and
        # the recorder dumps from its own SIGTERM/atexit hooks).
        os.kill(os.getpid(), signal.SIGTERM)
        return
    if rule.action == "hang":
        # stop emitting forever; the heartbeat daemon thread keeps
        # running, so the doctor sees "alive but stuck" — the verdict
        # a rank wedged inside a collective would earn
        while True:
            time.sleep(3600.0)
    if rule.action == "wedge":
        # hang's silent sibling: stop the heartbeat daemon too, then
        # block — no emissions, no heartbeats, no exit. Only an
        # external heartbeat deadline (the serving pool doctor's)
        # can detect this process state.
        from ..observability import events

        events.silence_heartbeat()
        while True:
            time.sleep(3600.0)
    if rule.action == "crash":
        if rule.mode == "sigkill":
            # no atexit, no recorder dump: the "rank vanished" failure
            # mode (preemption, OOM-kill) — only the fsync'd events
            # above survive as evidence
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(3600.0)  # pragma: no cover — death is async
        raise InjectedFault(
            f"fault plan rule {rule.index}: injected crash at {op} "
            f"(match {rule.matches}, fingerprint {fp})"
        )
