"""Step-tagged, crash-safe checkpoint management for elastic restart.

``utils/checkpoint.py`` knows how to save/restore one pytree at one
path; recovery needs more: a *history* of step-tagged checkpoints, an
atomic commit protocol so a rank killed mid-save can never leave a
checkpoint that half-parses, and a validity scan so resume picks the
newest checkpoint that is actually whole. That is this module:

- **Layout** — ``root/step_00000042/`` holds the saved pytree under
  ``data`` plus a ``manifest.json`` recording step, world size, the
  pytree fingerprint (structure + shapes + dtypes), and timestamps.
- **Atomicity** — a save is built in ``root/.tmp-*`` and
  ``os.replace``'d into place; the manifest is written (and fsync'd)
  *last* inside the staging dir, so a directory whose manifest parses
  is a directory whose data was fully written first. Torn saves are
  ``.tmp-*`` litter, swept by the next :meth:`CheckpointManager.save`.
- **Retention** — the newest ``keep`` checkpoints survive; older step
  dirs are deleted after each successful save.
- **Validity** — :meth:`CheckpointManager.latest_valid` walks steps
  newest-first and returns the first one whose manifest parses, whose
  step tag matches its directory, whose data exists, and (when asked)
  whose world size / pytree fingerprint match the resuming program —
  a checkpoint from a differently-shaped model or a different world
  must not be silently loaded into this one.

The storage layer is pluggable (``save_fn``/``restore_fn``): the
default is ``utils/checkpoint.py`` (orbax), and the device-free
``--selftest`` (``__main__.py``) swaps in a JSON saver so the commit
protocol is testable with no jax, no orbax, no devices.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import time
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

MANIFEST_NAME = "manifest.json"
DATA_NAME = "data"
MANIFEST_SCHEMA = "m4t-ckpt/1"

_STEP_RE = re.compile(r"^step_(\d{8,})$")


def step_dirname(step: int) -> str:
    return f"step_{int(step):08d}"


def pytree_fingerprint(tree: Any) -> str:
    """Stable identity of a pytree's *shape*: sha256 over the sorted
    (path, shape, dtype) leaf descriptions. Two trees with the same
    fingerprint can restore into each other's templates; values do not
    participate. Leaves without shape/dtype (plain Python scalars in a
    state dict) hash their type name."""
    import jax

    leaves = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        leaves.append((
            jax.tree_util.keystr(path),
            None if shape is None else [int(d) for d in shape],
            type(leaf).__name__ if dtype is None else str(dtype),
        ))
    leaves.sort()
    blob = json.dumps(leaves, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


@dataclass
class CheckpointInfo:
    """One valid on-disk checkpoint."""

    step: int
    path: str          # the step directory
    manifest: dict

    @property
    def data_path(self) -> str:
        return os.path.join(self.path, DATA_NAME)


def _default_save(path: str, state: Any) -> None:
    from ..utils import checkpoint

    checkpoint.save(path, state)


def _default_restore(path: str, template: Any) -> Any:
    from ..utils import checkpoint

    return checkpoint.restore(path, template)


class CheckpointManager:
    """Step-tagged atomic saves with retention and validity scanning.

    ``fingerprint=False`` skips the pytree fingerprint (the default
    computes it via jax at save time); pass a string to pin one
    explicitly (the device-free selftest path).
    """

    def __init__(
        self,
        root: str,
        *,
        keep: int = 3,
        world: Optional[int] = None,
        save_fn: Callable[[str, Any], None] = _default_save,
        restore_fn: Callable[[str, Any], Any] = _default_restore,
    ):
        self.root = os.path.abspath(root)
        self.keep = max(1, int(keep))
        self.world = None if world is None else int(world)
        self._save_fn = save_fn
        self._restore_fn = restore_fn
        os.makedirs(self.root, exist_ok=True)

    # -- scanning -----------------------------------------------------

    def steps(self) -> List[int]:
        """Step tags present on disk (committed dirs only), ascending."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.root, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def _validate(
        self,
        step: int,
        *,
        fingerprint: Optional[str] = None,
        world: Optional[int] = None,
    ) -> Optional[CheckpointInfo]:
        path = os.path.join(self.root, step_dirname(step))
        manifest_path = os.path.join(path, MANIFEST_NAME)
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None  # torn: no/unparseable manifest
        if not isinstance(manifest, dict) or manifest.get("step") != step:
            return None  # renamed/copied dir whose tag lies
        data = os.path.join(path, DATA_NAME)
        if not os.path.exists(data) or (
            os.path.isdir(data) and not os.listdir(data)
        ):
            return None  # manifest without data: truncated by hand
        want_world = self.world if world is None else int(world)
        if want_world is not None and manifest.get("world") not in (
            None, want_world
        ):
            return None  # checkpoint from a differently-sized world
        if fingerprint is not None and manifest.get("fingerprint") not in (
            None, fingerprint
        ):
            return None  # different model shape: do not resume into it
        return CheckpointInfo(step=step, path=path, manifest=manifest)

    def at_step(
        self,
        step: int,
        *,
        fingerprint: Optional[str] = None,
        world: Optional[int] = None,
    ) -> Optional[CheckpointInfo]:
        """The committed checkpoint at exactly ``step``, if valid —
        how a restarted rank resolves the ``M4T_RESUME_STEP`` the
        supervisor validated (every rank must restore the *same* step,
        not whatever is newest by the time it looks)."""
        return self._validate(
            int(step), fingerprint=fingerprint, world=world
        )

    def latest_valid(
        self,
        *,
        fingerprint: Optional[str] = None,
        world: Optional[int] = None,
        template: Any = None,
    ) -> Optional[CheckpointInfo]:
        """Newest checkpoint that passes validation; torn or
        mismatched ones are skipped, not fatal — resume prefers an
        older good checkpoint over dying on a bad new one.
        ``template`` computes the wanted fingerprint for you."""
        if template is not None and fingerprint is None:
            fingerprint = pytree_fingerprint(template)
        for step in reversed(self.steps()):
            info = self._validate(
                step, fingerprint=fingerprint, world=world
            )
            if info is not None:
                return info
        return None

    # -- saving -------------------------------------------------------

    def save(
        self,
        step: int,
        state: Any,
        *,
        fingerprint: Optional[str] = None,
        extra: Optional[dict] = None,
    ) -> CheckpointInfo:
        """Atomically commit ``state`` as the step-``step`` checkpoint
        and prune beyond the retention window. An existing checkpoint
        at the same step is replaced."""
        step = int(step)
        self._sweep_tmp()
        if fingerprint is None:
            try:
                fingerprint = pytree_fingerprint(state)
            except Exception:
                fingerprint = None  # non-jax state (selftest saver)
        final = os.path.join(self.root, step_dirname(step))
        tmp = os.path.join(
            self.root, f".tmp-{step_dirname(step)}.{os.getpid()}"
        )
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            self._save_fn(os.path.join(tmp, DATA_NAME), state)
            manifest = {
                "schema": MANIFEST_SCHEMA,
                "step": step,
                "world": self.world,
                "fingerprint": fingerprint,
                "t": time.time(),
            }
            if extra:
                manifest.update(extra)
            # manifest last, fsync'd: its presence certifies the data
            mpath = os.path.join(tmp, MANIFEST_NAME)
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=1, default=str)
                f.flush()
                os.fsync(f.fileno())
            if os.path.exists(final):
                shutil.rmtree(final)
            os.replace(tmp, final)
        finally:
            if os.path.exists(tmp):
                shutil.rmtree(tmp, ignore_errors=True)
        self.prune()
        return CheckpointInfo(step=step, path=final, manifest=manifest)

    def _sweep_tmp(self) -> None:
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for name in names:
            if name.startswith(".tmp-"):
                shutil.rmtree(
                    os.path.join(self.root, name), ignore_errors=True
                )

    def prune(self) -> List[int]:
        """Drop committed checkpoints beyond the newest ``keep``;
        returns the pruned steps."""
        steps = self.steps()
        doomed = steps[:-self.keep] if len(steps) > self.keep else []
        for step in doomed:
            shutil.rmtree(
                os.path.join(self.root, step_dirname(step)),
                ignore_errors=True,
            )
        return doomed

    # -- restoring ----------------------------------------------------

    def restore(self, info: CheckpointInfo, template: Any) -> Any:
        return self._restore_fn(info.data_path, template)

    def restore_latest(
        self, template: Any, *, world: Optional[int] = None,
        match_fingerprint: bool = True,
    ) -> Optional[tuple]:
        """``(step, state)`` from the newest valid checkpoint matching
        ``template``'s fingerprint (and ``world``), or None when there
        is nothing to resume from."""
        fingerprint = None
        if match_fingerprint:
            try:
                fingerprint = pytree_fingerprint(template)
            except Exception:
                fingerprint = None
        info = self.latest_valid(fingerprint=fingerprint, world=world)
        if info is None:
            return None
        return info.step, self.restore(info, template)
