"""Step-tagged, crash-safe checkpoint management for elastic restart.

``utils/checkpoint.py`` knows how to save/restore one pytree at one
path; recovery needs more: a *history* of step-tagged checkpoints, an
atomic commit protocol so a rank killed mid-save can never leave a
checkpoint that half-parses, and a validity scan so resume picks the
newest checkpoint that is actually whole. That is this module:

- **Layout** — ``root/step_00000042/`` holds the saved pytree under
  ``data`` plus a ``manifest.json`` recording step, world size, the
  pytree fingerprint (structure + shapes + dtypes), and timestamps.
- **Atomicity** — a save is built in ``root/.tmp-*`` and
  ``os.replace``'d into place; the manifest is written (and fsync'd)
  *last* inside the staging dir, so a directory whose manifest parses
  is a directory whose data was fully written first. Torn saves are
  ``.tmp-*`` litter, swept by the next :meth:`CheckpointManager.save`.
- **Retention** — the newest ``keep`` checkpoints survive; older step
  dirs are deleted after each successful save.
- **Validity** — :meth:`CheckpointManager.latest_valid` walks steps
  newest-first and returns the first one whose manifest parses, whose
  step tag matches its directory, whose data exists, and (when asked)
  whose world size / pytree fingerprint match the resuming program.
  The scan tolerates *vanishing* step dirs: keep-K retention in a
  concurrent writer (the serving plane's drain path reads while a
  resident job checkpoints) may delete a step between the directory
  listing and the manifest read — that step simply reads as invalid
  and the scan falls through to an older one. A checkpoint from a
  differently-shaped model or a differently-sized world likewise
  must not be silently loaded into this one. A checkpoint that is
  valid *except* for its world size is never silently skipped: by
  default the skip is logged, and under ``allow_reshard=True`` it is
  returned as an explicit **reshard candidate**
  (``CheckpointInfo.world_mismatch``) for the elastic resume path.
- **Sharded schema** (``m4t-ckpt/2``) — manifests record the *global*
  pytree shapes plus a per-leaf :class:`~.reshard.LeafSpec` sharding
  layout, and data is stored as per-rank ``.npy`` shards
  (``data/rank00000/leaf00000.npy``; replicated leaves once under
  ``data/replicated/``). That is what makes an N-rank checkpoint
  reshardable onto M ranks (``reshard.reshard_checkpoint``) with
  bounded peak memory, and readable without jax or orbax. v1
  checkpoints remain readable exactly as before.

The v1 storage layer is pluggable (``save_fn``/``restore_fn``): the
default is ``utils/checkpoint.py`` (orbax), and the device-free
``--selftest`` (``__main__.py``) swaps in a JSON saver so the commit
protocol is testable with no jax, no orbax, no devices. The v2 layer
is numpy-only by construction.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import sys
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import reshard as _reshard
from .reshard import LeafSpec, specs_fingerprint

MANIFEST_NAME = "manifest.json"
DATA_NAME = "data"
MANIFEST_SCHEMA = "m4t-ckpt/1"
MANIFEST_SCHEMA_V2 = "m4t-ckpt/2"

#: v2 data layout: per-rank shard dirs + one dir for replicated leaves
RANK_DIR_FMT = "rank{:05d}"
REPLICATED_DIR = "replicated"
STAGE_PREFIX = ".stage-"

_STEP_RE = re.compile(r"^step_(\d{8,})$")


def _log(msg: str) -> None:
    sys.stderr.write(f"m4t.ckpt: {msg}\n")


def _leaf_files(specs: Dict[str, LeafSpec]) -> Dict[str, str]:
    """Deterministic per-leaf file names (sorted key order), recorded
    in the manifest so readers never re-derive them."""
    return {k: f"leaf{i:05d}.npy" for i, k in enumerate(sorted(specs))}


def step_dirname(step: int) -> str:
    return f"step_{int(step):08d}"


def pytree_fingerprint(tree: Any) -> str:
    """Stable identity of a pytree's *shape*: sha256 over the sorted
    (path, shape, dtype) leaf descriptions. Two trees with the same
    fingerprint can restore into each other's templates; values do not
    participate. Leaves without shape/dtype (plain Python scalars in a
    state dict) hash their type name."""
    import jax

    leaves = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        leaves.append((
            jax.tree_util.keystr(path),
            None if shape is None else [int(d) for d in shape],
            type(leaf).__name__ if dtype is None else str(dtype),
        ))
    leaves.sort()
    blob = json.dumps(leaves, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


@dataclass
class CheckpointInfo:
    """One valid on-disk checkpoint. ``world_mismatch`` marks a
    checkpoint returned under ``allow_reshard=True`` whose recorded
    world differs from the requested one — a *reshard candidate*, not
    something to restore directly."""

    step: int
    path: str          # the step directory
    manifest: dict
    world_mismatch: bool = False

    @property
    def data_path(self) -> str:
        return os.path.join(self.path, DATA_NAME)

    @property
    def world(self) -> Optional[int]:
        w = self.manifest.get("world")
        return None if w is None else int(w)

    @property
    def schema(self) -> Optional[str]:
        return self.manifest.get("schema")

    @property
    def sharded(self) -> bool:
        """True when this checkpoint records a per-leaf sharding
        layout (schema v2) and can therefore be resharded."""
        return self.schema == MANIFEST_SCHEMA_V2


def _checkpoint_io():
    """The device-free array IO layer (lazy: importing the resilience
    package must stay cheap)."""
    from ..utils import checkpoint

    return checkpoint


def _default_save(path: str, state: Any) -> None:
    from ..utils import checkpoint

    checkpoint.save(path, state)


def _default_restore(path: str, template: Any) -> Any:
    from ..utils import checkpoint

    return checkpoint.restore(path, template)


class CheckpointManager:
    """Step-tagged atomic saves with retention and validity scanning.

    ``fingerprint=False`` skips the pytree fingerprint (the default
    computes it via jax at save time); pass a string to pin one
    explicitly (the device-free selftest path).
    """

    def __init__(
        self,
        root: str,
        *,
        keep: int = 3,
        world: Optional[int] = None,
        save_fn: Callable[[str, Any], None] = _default_save,
        restore_fn: Callable[[str, Any], Any] = _default_restore,
    ):
        self.root = os.path.abspath(root)
        self.keep = max(1, int(keep))
        self.world = None if world is None else int(world)
        self._save_fn = save_fn
        self._restore_fn = restore_fn
        os.makedirs(self.root, exist_ok=True)

    # -- scanning -----------------------------------------------------

    def steps(self) -> List[int]:
        """Step tags present on disk (committed dirs only), ascending."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.root, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def _validate(
        self,
        step: int,
        *,
        fingerprint: Optional[str] = None,
        world: Optional[int] = None,
        allow_reshard: bool = False,
    ) -> Optional[CheckpointInfo]:
        path = os.path.join(self.root, step_dirname(step))
        manifest_path = os.path.join(path, MANIFEST_NAME)
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None  # torn: no/unparseable manifest
        if not isinstance(manifest, dict) or manifest.get("step") != step:
            return None  # renamed/copied dir whose tag lies
        data = os.path.join(path, DATA_NAME)
        try:
            if manifest.get("schema") == MANIFEST_SCHEMA_V2:
                if not self._v2_data_complete(data, manifest):
                    return None  # truncated shard layout
            elif not os.path.exists(data) or (
                os.path.isdir(data) and not os.listdir(data)
            ):
                return None  # manifest without data: truncated by hand
        except OSError:
            # keep-K retention (this process's or a concurrent
            # writer's prune — real under serving, where the drain
            # path reads while a job writes) deleted the step dir
            # between our listing and this read. A vanished
            # checkpoint reads as "not valid", never as a crash:
            # latest_valid falls through to an older committed step.
            return None
        if fingerprint is not None and manifest.get("fingerprint") not in (
            None, fingerprint
        ):
            return None  # different model shape: do not resume into it
        want_world = self.world if world is None else int(world)
        have_world = manifest.get("world")
        if want_world is not None and have_world not in (None, want_world):
            # otherwise-valid checkpoint from a differently-sized
            # world: NEVER indistinguishable from "no checkpoint" —
            # either hand it back as an explicit reshard candidate or
            # say out loud that it was skipped
            if allow_reshard:
                return CheckpointInfo(
                    step=step, path=path, manifest=manifest,
                    world_mismatch=True,
                )
            _log(
                f"skipping otherwise-valid checkpoint step {step} at "
                f"{path}: world {have_world} != wanted {want_world} "
                "(pass allow_reshard=True to get it as a reshard "
                "candidate)"
            )
            return None
        return CheckpointInfo(step=step, path=path, manifest=manifest)

    @staticmethod
    def _v2_data_complete(data: str, manifest: dict) -> bool:
        """Every shard file the v2 manifest names must exist — a rank
        dir deleted by hand must read as torn, not crash the resume."""
        leaves = manifest.get("leaves")
        world = manifest.get("world")
        if not isinstance(leaves, dict) or not leaves:
            return False
        if not isinstance(world, int) or world < 1:
            return False
        for meta in leaves.values():
            fname = meta.get("file")
            if not fname:
                return False
            if meta.get("kind") == "replicated":
                paths = [os.path.join(data, REPLICATED_DIR, fname)]
            else:
                paths = [
                    os.path.join(data, RANK_DIR_FMT.format(r), fname)
                    for r in range(world)
                ]
            if not all(os.path.exists(p) for p in paths):
                return False
        return True

    def at_step(
        self,
        step: int,
        *,
        fingerprint: Optional[str] = None,
        world: Optional[int] = None,
        allow_reshard: bool = False,
    ) -> Optional[CheckpointInfo]:
        """The committed checkpoint at exactly ``step``, if valid —
        how a restarted rank resolves the ``M4T_RESUME_STEP`` the
        supervisor validated (every rank must restore the *same* step,
        not whatever is newest by the time it looks)."""
        return self._validate(
            int(step), fingerprint=fingerprint, world=world,
            allow_reshard=allow_reshard,
        )

    def latest_valid(
        self,
        *,
        fingerprint: Optional[str] = None,
        world: Optional[int] = None,
        template: Any = None,
        allow_reshard: bool = False,
    ) -> Optional[CheckpointInfo]:
        """Newest checkpoint that passes validation; torn or
        mismatched ones are skipped, not fatal — resume prefers an
        older good checkpoint over dying on a bad new one.
        ``template`` computes the wanted fingerprint for you.
        ``allow_reshard=True`` additionally accepts a checkpoint whose
        recorded world disagrees with the wanted one, returned with
        ``world_mismatch=True`` — the elastic resume path reshards it
        (``reshard.reshard_checkpoint``) instead of losing the run."""
        if template is not None and fingerprint is None:
            fingerprint = pytree_fingerprint(template)
        for step in reversed(self.steps()):
            info = self._validate(
                step, fingerprint=fingerprint, world=world,
                allow_reshard=allow_reshard,
            )
            if info is not None:
                return info
        return None

    # -- saving -------------------------------------------------------

    def save(
        self,
        step: int,
        state: Any,
        *,
        fingerprint: Optional[str] = None,
        extra: Optional[dict] = None,
    ) -> CheckpointInfo:
        """Atomically commit ``state`` as the step-``step`` checkpoint
        and prune beyond the retention window. An existing checkpoint
        at the same step is replaced."""
        step = int(step)
        self._sweep_tmp()
        if fingerprint is None:
            try:
                fingerprint = pytree_fingerprint(state)
            except Exception:
                fingerprint = None  # non-jax state (selftest saver)
        final = os.path.join(self.root, step_dirname(step))
        tmp = os.path.join(
            self.root, f".tmp-{step_dirname(step)}.{os.getpid()}"
        )
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            self._save_fn(os.path.join(tmp, DATA_NAME), state)
            manifest = {
                "schema": MANIFEST_SCHEMA,
                "step": step,
                "world": self.world,
                "fingerprint": fingerprint,
                "t": time.time(),
            }
            if extra:
                manifest.update(extra)
            # manifest last, fsync'd: its presence certifies the data
            mpath = os.path.join(tmp, MANIFEST_NAME)
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=1, default=str)
                f.flush()
                os.fsync(f.fileno())
            if os.path.exists(final):
                shutil.rmtree(final)
            os.replace(tmp, final)
        finally:
            if os.path.exists(tmp):
                shutil.rmtree(tmp, ignore_errors=True)
        self.prune()
        return CheckpointInfo(step=step, path=final, manifest=manifest)

    # -- saving, sharded (schema m4t-ckpt/2) --------------------------

    def _commit_manifest(
        self, tmp: str, final: str, manifest: dict
    ) -> None:
        """The shared commit tail: manifest written + fsync'd last in
        the staging dir, then the whole dir renamed into place."""
        mpath = os.path.join(tmp, MANIFEST_NAME)
        with open(mpath, "w") as f:
            json.dump(manifest, f, indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)

    def _v2_manifest(
        self,
        step: int,
        specs: Dict[str, LeafSpec],
        world: int,
        extra: Optional[dict],
    ) -> dict:
        files = _leaf_files(specs)
        manifest = {
            "schema": MANIFEST_SCHEMA_V2,
            "step": int(step),
            "world": int(world),
            "fingerprint": specs_fingerprint(specs),
            "leaves": {
                k: dict(specs[k].to_json(), file=files[k])
                for k in sorted(specs)
            },
            "t": time.time(),
        }
        if extra:
            manifest.update(extra)
        return manifest

    def save_sharded(
        self,
        step: int,
        flat: Dict[str, Any],
        specs: Dict[str, LeafSpec],
        *,
        world: Optional[int] = None,
        extra: Optional[dict] = None,
    ) -> CheckpointInfo:
        """Single-writer sharded commit: ``flat`` maps leaf keys to
        *global* arrays; each rank's shard is sliced out and written
        as its own ``.npy`` (replicated leaves once). Same atomic
        protocol as :meth:`save`. This is the path a single-process
        training loop (or the offline reshard CLI writing its output)
        uses; a launcher world where no rank sees the whole state
        stages per-rank instead (:meth:`stage_shard` +
        :meth:`commit_sharded`)."""
        step = int(step)
        world = int(self.world if world is None else world)
        if world < 1:
            raise ValueError(
                "save_sharded needs a world size (manager world=None "
                "and no world= given)"
            )
        if set(flat) != set(specs):
            raise ValueError(
                f"flat/specs key mismatch: {sorted(set(flat) ^ set(specs))}"
            )
        self._sweep_tmp()
        final = os.path.join(self.root, step_dirname(step))
        tmp = os.path.join(
            self.root, f".tmp-{step_dirname(step)}.{os.getpid()}"
        )
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            data = os.path.join(tmp, DATA_NAME)
            files = _leaf_files(specs)
            for key in sorted(specs):
                spec = specs[key]
                arr = np.asarray(flat[key])
                if tuple(arr.shape) != spec.shape:
                    raise ValueError(
                        f"leaf {key!r}: array shape {arr.shape} != "
                        f"global spec shape {spec.shape}"
                    )
                wire = spec.wire_dtype()
                if arr.dtype != wire:
                    arr = np.ascontiguousarray(arr).view(wire)
                if spec.kind == "replicated":
                    d = os.path.join(data, REPLICATED_DIR)
                    os.makedirs(d, exist_ok=True)
                    _checkpoint_io().save_array(
                        os.path.join(d, files[key]), arr
                    )
                else:
                    for r in range(world):
                        d = os.path.join(data, RANK_DIR_FMT.format(r))
                        os.makedirs(d, exist_ok=True)
                        _checkpoint_io().save_array(
                            os.path.join(d, files[key]),
                            arr[_reshard.shard_slices(spec, world, r)],
                        )
            manifest = self._v2_manifest(step, specs, world, extra)
            self._commit_manifest(tmp, final, manifest)
        finally:
            if os.path.exists(tmp):
                shutil.rmtree(tmp, ignore_errors=True)
        self.prune()
        return CheckpointInfo(step=step, path=final, manifest=manifest)

    def save_resharded(
        self,
        step: int,
        plan: "_reshard.ReshardPlan",
        read_slice: Callable[[str, int, int, int], np.ndarray],
        specs: Dict[str, LeafSpec],
        *,
        extra: Optional[dict] = None,
    ) -> CheckpointInfo:
        """Commit the output of a reshard plan without ever holding
        the global state: each destination shard is built slice by
        slice (``reshard.execute_plan`` memory bound) and written to
        the staging dir before the next one is touched."""
        step = int(step)
        world = plan.dst_world
        self._sweep_tmp()
        final = os.path.join(self.root, step_dirname(step))
        tmp = os.path.join(
            self.root, f".tmp-{step_dirname(step)}.{os.getpid()}"
        )
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            data = os.path.join(tmp, DATA_NAME)
            files = _leaf_files(specs)

            def write_shard(key: str, dst_rank: int, arr: np.ndarray):
                spec = specs[key]
                if spec.kind == "replicated":
                    if dst_rank != 0:
                        return  # stored once
                    d = os.path.join(data, REPLICATED_DIR)
                else:
                    d = os.path.join(data, RANK_DIR_FMT.format(dst_rank))
                os.makedirs(d, exist_ok=True)
                _checkpoint_io().save_array(
                    os.path.join(d, files[key]), arr
                )

            _reshard.execute_plan(plan, read_slice, write_shard)
            manifest = self._v2_manifest(step, specs, world, extra)
            self._commit_manifest(tmp, final, manifest)
        finally:
            if os.path.exists(tmp):
                shutil.rmtree(tmp, ignore_errors=True)
        self.prune()
        return CheckpointInfo(step=step, path=final, manifest=manifest)

    # -- saving, sharded, two-phase (every rank writes its own shard) --

    def _stage_dir(self, step: int) -> str:
        return os.path.join(self.root, STAGE_PREFIX + step_dirname(step))

    def stage_shard(
        self,
        step: int,
        rank: int,
        flat_local: Dict[str, Any],
        specs: Dict[str, LeafSpec],
        *,
        world: Optional[int] = None,
    ) -> str:
        """Phase one of a cooperative sharded save: rank ``rank``
        writes its *local* shards (and, on rank 0, the replicated
        leaves) into a shared staging dir. No manifest is written —
        the stage is invisible to the validity scan until every rank
        has staged and one rank runs :meth:`commit_sharded` (callers
        barrier in between). Ranks write disjoint files, so there is
        no cross-rank ordering to get wrong; a stage left behind by a
        crashed attempt is simply overwritten file by file when the
        step is recomputed, and swept at the next commit."""
        step = int(step)
        rank = int(rank)
        world = int(self.world if world is None else world)
        stage = self._stage_dir(step)
        data = os.path.join(stage, DATA_NAME)
        files = _leaf_files(specs)
        for key in sorted(specs):
            spec = specs[key]
            arr = np.asarray(flat_local[key])
            wire = spec.wire_dtype()
            if arr.dtype != wire:
                arr = np.ascontiguousarray(arr).view(wire)
            if spec.kind == "replicated":
                if rank != 0:
                    continue
                if tuple(arr.shape) != spec.shape:
                    raise ValueError(
                        f"leaf {key!r}: replicated array shape "
                        f"{arr.shape} != spec shape {spec.shape}"
                    )
                d = os.path.join(data, REPLICATED_DIR)
            else:
                want = _reshard.shard_shape(spec, world, rank)
                if tuple(arr.shape) != want:
                    raise ValueError(
                        f"leaf {key!r}: rank {rank} shard shape "
                        f"{arr.shape} != expected {want} "
                        f"(world {world})"
                    )
                d = os.path.join(data, RANK_DIR_FMT.format(rank))
            os.makedirs(d, exist_ok=True)
            _checkpoint_io().save_array(os.path.join(d, files[key]), arr)
        return stage

    def commit_sharded(
        self,
        step: int,
        specs: Dict[str, LeafSpec],
        *,
        world: Optional[int] = None,
        extra: Optional[dict] = None,
    ) -> CheckpointInfo:
        """Phase two: verify every staged shard the manifest will name
        actually exists (a rank that died before staging must abort
        the commit, not produce a checkpoint that lies), then write
        the manifest last and rename the stage into place. Run by one
        rank, after a barrier."""
        step = int(step)
        world = int(self.world if world is None else world)
        stage = self._stage_dir(step)
        final = os.path.join(self.root, step_dirname(step))
        manifest = self._v2_manifest(step, specs, world, extra)
        if not self._v2_data_complete(
            os.path.join(stage, DATA_NAME), manifest
        ):
            raise RuntimeError(
                f"commit_sharded(step={step}): staged data incomplete "
                f"at {stage} — did every rank stage_shard() first?"
            )
        self._commit_manifest(stage, final, manifest)
        # sweep stages left behind by crashed attempts
        try:
            for name in os.listdir(self.root):
                if name.startswith(STAGE_PREFIX):
                    shutil.rmtree(
                        os.path.join(self.root, name), ignore_errors=True
                    )
        except OSError:
            pass
        self.prune()
        return CheckpointInfo(step=step, path=final, manifest=manifest)

    def _sweep_tmp(self) -> None:
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for name in names:
            if name.startswith(".tmp-"):
                shutil.rmtree(
                    os.path.join(self.root, name), ignore_errors=True
                )

    def prune(self) -> List[int]:
        """Drop committed checkpoints beyond the newest ``keep``;
        returns the pruned steps."""
        steps = self.steps()
        doomed = steps[:-self.keep] if len(steps) > self.keep else []
        for step in doomed:
            shutil.rmtree(
                os.path.join(self.root, step_dirname(step)),
                ignore_errors=True,
            )
        return doomed

    # -- restoring ----------------------------------------------------

    def restore(self, info: CheckpointInfo, template: Any) -> Any:
        if info.sharded:
            raise ValueError(
                f"checkpoint step {info.step} is sharded "
                f"({MANIFEST_SCHEMA_V2}); read it with load_shard() / "
                "load_sharded_global(), not restore()"
            )
        return self._restore_fn(info.data_path, template)

    def restore_latest(
        self, template: Any, *, world: Optional[int] = None,
        match_fingerprint: bool = True,
    ) -> Optional[tuple]:
        """``(step, state)`` from the newest valid checkpoint matching
        ``template``'s fingerprint (and ``world``), or None when there
        is nothing to resume from."""
        fingerprint = None
        if match_fingerprint:
            try:
                fingerprint = pytree_fingerprint(template)
            except Exception:
                fingerprint = None
        info = self.latest_valid(fingerprint=fingerprint, world=world)
        if info is None:
            return None
        return info.step, self.restore(info, template)


# ---------------------------------------------------------------------
# sharded (v2) readers — module-level, numpy-only
# ---------------------------------------------------------------------


def specs_from_manifest(manifest: dict) -> Dict[str, LeafSpec]:
    """The per-leaf layout an ``m4t-ckpt/2`` manifest records."""
    leaves = manifest.get("leaves")
    if not isinstance(leaves, dict):
        raise ValueError(
            f"manifest schema {manifest.get('schema')!r} records no "
            "per-leaf layout"
        )
    return {k: LeafSpec.from_json(v) for k, v in leaves.items()}


def _leaf_file(
    info: CheckpointInfo, key: str, spec: LeafSpec, rank: int
) -> str:
    fname = info.manifest["leaves"][key]["file"]
    sub = (
        REPLICATED_DIR if spec.kind == "replicated"
        else RANK_DIR_FMT.format(rank)
    )
    return os.path.join(info.data_path, sub, fname)


def shard_slice_reader(
    info: CheckpointInfo,
    specs: Dict[str, LeafSpec],
    src_world: int,
) -> Callable[[str, int, int, int], np.ndarray]:
    """A ``reshard.execute_plan`` reader over the checkpoint's shard
    files, memory-mapped: a slice read touches only the bytes the
    slice covers, which is what keeps the offline reshard at the
    plan's peak-memory bound."""
    io = _checkpoint_io()

    def read_slice(key: str, src_rank: int, lo: int, hi: int):
        spec = specs[key]
        arr = io.open_array(_leaf_file(info, key, spec, src_rank))
        if spec.kind == "replicated":
            return arr
        base, _ = _reshard.shard_extent(
            spec.shape[spec.axis], src_world, src_rank
        )
        idx = tuple(
            slice(lo - base, hi - base) if i == spec.axis else slice(None)
            for i in range(len(spec.shape))
        )
        return arr[idx]

    return read_slice


def _logical_view(arr: np.ndarray, spec: LeafSpec) -> np.ndarray:
    """View stored wire bytes back as the logical dtype when this
    interpreter can construct it (ml_dtypes present); opaque bytes
    otherwise — resharding never needed the logical dtype anyway."""
    try:
        dt = np.dtype(spec.dtype)
    except TypeError:
        return arr
    return arr if arr.dtype == dt else arr.view(dt)


def load_shard(
    info: CheckpointInfo,
    rank: int,
    *,
    specs: Optional[Dict[str, LeafSpec]] = None,
) -> Dict[str, np.ndarray]:
    """Rank ``rank``'s local state from a sharded checkpoint:
    ``{leaf key: local shard}`` (replicated leaves whole). What a
    launched rank reads at resume — it never touches peer shards."""
    specs = specs or specs_from_manifest(info.manifest)
    io = _checkpoint_io()
    out: Dict[str, np.ndarray] = {}
    for key in sorted(specs):
        spec = specs[key]
        arr = np.array(io.open_array(
            _leaf_file(info, key, spec, rank), mmap=False
        ))
        out[key] = _logical_view(arr, spec)
    return out


def load_sharded_global(
    info: CheckpointInfo,
    *,
    specs: Optional[Dict[str, LeafSpec]] = None,
) -> Dict[str, np.ndarray]:
    """The whole global state assembled from a sharded checkpoint —
    the single-process resume path (small states); the bounded-memory
    path is :func:`load_shard` per rank."""
    specs = specs or specs_from_manifest(info.manifest)
    world = info.world or 1
    io = _checkpoint_io()
    out: Dict[str, np.ndarray] = {}
    for key in sorted(specs):
        spec = specs[key]
        if spec.kind == "replicated":
            arr = np.array(io.open_array(
                _leaf_file(info, key, spec, 0), mmap=False
            ))
        else:
            parts = [
                np.asarray(io.open_array(
                    _leaf_file(info, key, spec, r), mmap=False
                ))
                for r in range(world)
            ]
            arr = np.concatenate(parts, axis=spec.axis)
        out[key] = _logical_view(arr, spec)
    return out


# ---------------------------------------------------------------------
# pytree <-> flat-dict bridge (imports jax lazily)
# ---------------------------------------------------------------------


def tree_leaves_dict(tree: Any) -> Dict[str, np.ndarray]:
    """Flatten a pytree to ``{keystr path: numpy array}`` — the
    representation every sharded-checkpoint API speaks (string keys
    survive a JSON manifest; pytree defs do not)."""
    import jax

    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        flat[jax.tree_util.keystr(path)] = np.asarray(leaf)
    return flat


def tree_from_dict(template: Any, flat: Dict[str, np.ndarray]) -> Any:
    """Rebuild a pytree shaped like ``template`` from
    :func:`tree_leaves_dict` output (values come from ``flat``;
    structure from ``template``)."""
    import jax

    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(
        template
    )
    leaves = []
    for path, _leaf in paths_and_leaves:
        key = jax.tree_util.keystr(path)
        if key not in flat:
            raise KeyError(
                f"flat state is missing leaf {key!r} "
                f"(has {sorted(flat)})"
            )
        leaves.append(flat[key])
    return jax.tree_util.tree_unflatten(treedef, leaves)
