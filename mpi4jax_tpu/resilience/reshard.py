"""Elastic world-size resharding: N-rank state onto M ranks, bounded.

The PR-5 supervisor can restart a run only at the exact world size it
crashed with: a checkpoint written by 4 ranks is invisible to a 2-rank
resume (``ckpt.py`` validity scan), so losing one host to preemption
kills the whole job. This module is the missing primitive: given a
pytree checkpointed under world **N** with a recorded per-leaf sharding
layout, produce the equivalent pytree sharded for world **M** — any
M ≠ N, including M ∤ N — via a *planned schedule* of slice-level
transfers whose peak extra memory per rank is **provably bounded**.

The shape of the idea follows "Memory-efficient array redistribution
through portable collective communication" (PAPERS.md, arXiv
2112.01075): never materialize the global array (the allgather-
everything strategy needs N shards of scratch); instead decompose the
redistribution into slice moves between the source and destination
partitions and execute them one staged slice at a time. Each
destination shard overlaps a handful of source shards; building it
needs the destination buffer (≤ 1 shard) plus one in-flight source
slice (≤ 1 shard), so peak scratch per rank is **≤ 2 shard sizes** —
independent of N, M, and the global array size. The plan records that
bound per destination rank (:meth:`ReshardPlan.peak_scratch_bytes`)
and the executor *meters* its allocations against it
(:class:`MemoryMeter`), so tests assert the bound instead of claiming
it.

The primitive is expressible two ways over the same plan:

- **device-free** (:func:`execute_plan`): numpy only, no jax — the
  offline ``python -m mpi4jax_tpu.resilience reshard`` CLI the elastic
  launcher runs between attempts (no mesh is alive then), and the
  tier-1 selftests;
- **on-mesh** (:func:`execute_plan_on_mesh`): the same transfer
  schedule routed through the existing collective ops (``m4t.send`` /
  ``m4t.recv``) for a live world whose ranks each hold some of the
  source shards — every rank walks the plan in the same global order,
  so the point-to-point pairing is deadlock-free by construction.

Layouts are :class:`LeafSpec` per leaf — ``sharded`` (balanced
contiguous split along one axis) or ``replicated`` (every rank holds
the full leaf; stored once). ``ckpt.py`` persists them in the
``m4t-ckpt/2`` manifest; :func:`reshard_checkpoint` rewrites a whole
checkpoint N→M through a plan, which is how ``launch --elastic`` turns
a preemption into a shrink instead of a death.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

KINDS = ("sharded", "replicated")

#: numpy dtype kinds portable to a vanilla (no ml_dtypes) reader; other
#: dtypes (bfloat16, float8_*) travel as opaque ``V<itemsize>`` bytes
_PORTABLE_KINDS = frozenset("biufc")


class ReshardError(ValueError):
    """A layout or plan that cannot mean what was written."""


# ---------------------------------------------------------------------
# layout specs
# ---------------------------------------------------------------------


@dataclass(frozen=True)
class LeafSpec:
    """How one leaf's *global* array maps onto a world of ranks.

    ``shape``/``dtype`` describe the global (logical) array; ``kind``
    is ``"sharded"`` (balanced contiguous split along ``axis``) or
    ``"replicated"`` (every rank holds the whole leaf). ``itemsize``
    is recorded explicitly so a device-free reader can move the bytes
    of dtypes it cannot construct (bfloat16 without ml_dtypes)."""

    shape: Tuple[int, ...]
    dtype: str
    kind: str = "sharded"
    axis: int = 0
    itemsize: int = 0

    def __post_init__(self):
        object.__setattr__(self, "shape", tuple(int(d) for d in self.shape))
        if self.kind not in KINDS:
            raise ReshardError(
                f"kind must be one of {list(KINDS)} (got {self.kind!r})"
            )
        if any(d < 0 for d in self.shape):
            raise ReshardError(f"negative dim in shape {self.shape}")
        if self.kind == "sharded":
            if not self.shape:
                raise ReshardError(
                    "a scalar leaf cannot be sharded; use replicated"
                )
            if not (0 <= self.axis < len(self.shape)):
                raise ReshardError(
                    f"axis {self.axis} out of range for shape {self.shape}"
                )
        if self.itemsize == 0:
            try:
                object.__setattr__(
                    self, "itemsize", int(np.dtype(self.dtype).itemsize)
                )
            except TypeError:
                raise ReshardError(
                    f"dtype {self.dtype!r} is not constructible here; "
                    "pass itemsize explicitly"
                )

    @property
    def nbytes(self) -> int:
        """Bytes of the whole (global) leaf."""
        n = self.itemsize
        for d in self.shape:
            n *= d
        return n

    def wire_dtype(self) -> np.dtype:
        """The dtype the bytes travel (and are stored) as: the logical
        dtype when it is portable to a vanilla numpy reader, else
        opaque ``V<itemsize>`` — resharding is pure byte movement, so
        a device-free reader without ml_dtypes still reshards bfloat16
        correctly, and the ``.npy`` shard files never carry a descr
        only some interpreters can parse."""
        try:
            dt = np.dtype(self.dtype)
        except TypeError:
            return np.dtype(f"V{self.itemsize}")
        if dt.kind in _PORTABLE_KINDS:
            return dt
        return np.dtype(f"V{dt.itemsize}")

    def to_json(self) -> Dict[str, Any]:
        return {
            "shape": list(self.shape),
            "dtype": self.dtype,
            "kind": self.kind,
            "axis": self.axis,
            "itemsize": self.itemsize,
        }

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "LeafSpec":
        if not isinstance(obj, dict):
            raise ReshardError(f"leaf spec must be an object (got {obj!r})")
        try:
            return cls(
                shape=tuple(obj["shape"]),
                dtype=str(obj["dtype"]),
                kind=obj.get("kind", "sharded"),
                axis=int(obj.get("axis", 0)),
                itemsize=int(obj.get("itemsize", 0)),
            )
        except KeyError as e:
            raise ReshardError(f"leaf spec missing field {e}")


def spec_for_array(
    arr: Any, *, kind: str = "sharded", axis: int = 0
) -> LeafSpec:
    """A :class:`LeafSpec` describing ``arr`` as the global array."""
    a = np.asarray(arr)
    return LeafSpec(
        shape=a.shape, dtype=str(a.dtype), kind=kind, axis=axis,
        itemsize=a.dtype.itemsize,
    )


def specs_fingerprint(specs: Dict[str, LeafSpec]) -> str:
    """World-independent identity of a sharded state's *shape*: sha256
    over the sorted (key, global shape, dtype, kind, axis) rows. The
    same state checkpointed at world 4 and world 2 fingerprints
    identically — that is what lets an M-rank resume recognize an
    N-rank checkpoint as its own."""
    rows = sorted(
        (k, list(s.shape), s.dtype, s.kind, s.axis)
        for k, s in specs.items()
    )
    blob = json.dumps(rows, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


# ---------------------------------------------------------------------
# partition math (balanced contiguous split, M ∤ N welcome)
# ---------------------------------------------------------------------


def shard_extent(length: int, world: int, rank: int) -> Tuple[int, int]:
    """Global index range ``[lo, hi)`` rank ``rank`` owns of an axis of
    ``length`` split over ``world`` ranks: the first ``length % world``
    ranks get one extra element. Empty extents are legal (axis shorter
    than the world)."""
    if world < 1:
        raise ReshardError(f"world must be >= 1 (got {world})")
    if not (0 <= rank < world):
        raise ReshardError(f"rank {rank} out of range for world {world}")
    base, rem = divmod(length, world)
    lo = rank * base + min(rank, rem)
    return lo, lo + base + (1 if rank < rem else 0)


def shard_shape(
    spec: LeafSpec, world: int, rank: int
) -> Tuple[int, ...]:
    """The local shard shape of ``spec`` on ``rank`` of ``world``."""
    if spec.kind == "replicated":
        return spec.shape
    lo, hi = shard_extent(spec.shape[spec.axis], world, rank)
    shape = list(spec.shape)
    shape[spec.axis] = hi - lo
    return tuple(shape)


def shard_nbytes(spec: LeafSpec, world: int, rank: int) -> int:
    n = spec.itemsize
    for d in shard_shape(spec, world, rank):
        n *= d
    return n


def shard_slices(
    spec: LeafSpec, world: int, rank: int
) -> Tuple[slice, ...]:
    """Index expression selecting ``rank``'s shard from the global
    array (replicated: the whole array)."""
    if spec.kind == "replicated":
        return tuple(slice(None) for _ in spec.shape)
    lo, hi = shard_extent(spec.shape[spec.axis], world, rank)
    return tuple(
        slice(lo, hi) if i == spec.axis else slice(None)
        for i in range(len(spec.shape))
    )


# ---------------------------------------------------------------------
# the plan
# ---------------------------------------------------------------------


@dataclass(frozen=True)
class Transfer:
    """One slice-level move: global range ``[lo, hi)`` on the sharded
    axis, from ``src_rank``'s source shard into ``dst_rank``'s
    destination shard. For replicated leaves ``lo``/``hi`` span the
    whole axis (or are 0 for scalars) and ``src_rank`` names the copy
    being read."""

    src_rank: int
    dst_rank: int
    lo: int
    hi: int
    nbytes: int

    def to_json(self) -> List[int]:
        return [self.src_rank, self.dst_rank, self.lo, self.hi, self.nbytes]


@dataclass
class ReshardPlan:
    """The full N→M transfer schedule for one pytree layout.

    ``transfers[key]`` is ordered (by destination rank, then source
    rank) — both executors walk it in exactly this order, which is
    what makes the memory accounting provable and the on-mesh
    point-to-point pairing deadlock-free."""

    src_world: int
    dst_world: int
    specs: Dict[str, LeafSpec]
    transfers: Dict[str, List[Transfer]] = field(default_factory=dict)

    # -- memory accounting -------------------------------------------

    def peak_scratch_bytes(self) -> Dict[int, int]:
        """Planned peak live scratch per destination rank: leaves are
        built one at a time, each needing its destination buffer plus
        at most one staged inbound slice. The executor's meter must
        agree with this number exactly (tests assert it)."""
        peaks = {d: 0 for d in range(self.dst_world)}
        for key, spec in self.specs.items():
            per_dst: Dict[int, List[Transfer]] = {}
            for t in self.transfers.get(key, []):
                per_dst.setdefault(t.dst_rank, []).append(t)
            for d in range(self.dst_world):
                ts = per_dst.get(d, [])
                if spec.kind == "replicated":
                    # staged copy + destination buffer coexist briefly
                    peak = 2 * spec.nbytes if ts else 0
                else:
                    buf = shard_nbytes(spec, self.dst_world, d)
                    peak = buf + max((t.nbytes for t in ts), default=0)
                peaks[d] = max(peaks[d], peak)
        return peaks

    def max_peak_bytes(self) -> int:
        peaks = self.peak_scratch_bytes()
        return max(peaks.values()) if peaks else 0

    def memory_bound_bytes(self) -> int:
        """The paper-style guarantee: 2 × the largest shard in either
        world (replicated leaves count whole). Every planned (and
        therefore every measured) peak is ≤ this."""
        biggest = 0
        for spec in self.specs.values():
            if spec.kind == "replicated":
                biggest = max(biggest, spec.nbytes)
                continue
            for world in (self.src_world, self.dst_world):
                for r in range(world):
                    biggest = max(biggest, shard_nbytes(spec, world, r))
        return 2 * biggest

    def total_moved_bytes(self) -> int:
        return sum(
            t.nbytes for ts in self.transfers.values() for t in ts
        )

    def summary(self) -> Dict[str, Any]:
        peaks = self.peak_scratch_bytes()
        return {
            "src_world": self.src_world,
            "dst_world": self.dst_world,
            "leaves": len(self.specs),
            "transfers": sum(len(ts) for ts in self.transfers.values()),
            "moved_bytes": self.total_moved_bytes(),
            "peak_scratch_bytes": max(peaks.values()) if peaks else 0,
            "memory_bound_bytes": self.memory_bound_bytes(),
        }


def plan_reshard(
    specs: Dict[str, LeafSpec], src_world: int, dst_world: int
) -> ReshardPlan:
    """Plan the slice-level schedule moving every leaf from its
    ``src_world`` partition to its ``dst_world`` partition.

    Sharded leaves: destination rank ``d``'s range overlaps a
    contiguous run of source ranks; one transfer per overlap, in
    (dst, src) order. Replicated leaves: one whole-leaf copy per
    destination rank, read from source copy ``d % src_world`` (any
    copy is the copy — the mapping just keeps reads spread and
    deterministic)."""
    if src_world < 1 or dst_world < 1:
        raise ReshardError(
            f"world sizes must be >= 1 (got {src_world}→{dst_world})"
        )
    plan = ReshardPlan(
        src_world=src_world, dst_world=dst_world, specs=dict(specs)
    )
    for key, spec in specs.items():
        ts: List[Transfer] = []
        if spec.kind == "replicated":
            axis_len = spec.shape[spec.axis] if spec.shape else 0
            for d in range(dst_world):
                ts.append(Transfer(
                    src_rank=d % src_world, dst_rank=d,
                    lo=0, hi=axis_len, nbytes=spec.nbytes,
                ))
        else:
            length = spec.shape[spec.axis]
            row_bytes = spec.itemsize
            for i, dim in enumerate(spec.shape):
                if i != spec.axis:
                    row_bytes *= dim
            for d in range(dst_world):
                dlo, dhi = shard_extent(length, dst_world, d)
                for s in range(src_world):
                    slo, shi = shard_extent(length, src_world, s)
                    lo, hi = max(dlo, slo), min(dhi, shi)
                    if lo < hi:
                        ts.append(Transfer(
                            src_rank=s, dst_rank=d, lo=lo, hi=hi,
                            nbytes=(hi - lo) * row_bytes,
                        ))
        plan.transfers[key] = ts
    return plan


# ---------------------------------------------------------------------
# metered execution (device-free)
# ---------------------------------------------------------------------


class MemoryMeter:
    """Accounting allocator: the executor charges every staged buffer
    here, so a test asserts the *measured* peak against the plan
    instead of trusting a docstring."""

    def __init__(self):
        self.live = 0
        self.peak = 0

    def alloc(self, nbytes: int) -> None:
        self.live += int(nbytes)
        self.peak = max(self.peak, self.live)

    def free(self, nbytes: int) -> None:
        self.live -= int(nbytes)


def reader_from_global(
    flat: Dict[str, np.ndarray], specs: Dict[str, LeafSpec],
    src_world: int,
) -> Callable[[str, int, int, int], np.ndarray]:
    """A ``read_slice`` over in-memory *global* arrays (tests, and the
    single-writer checkpoint path)."""

    def read_slice(key: str, src_rank: int, lo: int, hi: int):
        spec = specs[key]
        arr = np.asarray(flat[key])
        if spec.kind == "replicated":
            return arr
        idx = tuple(
            slice(lo, hi) if i == spec.axis else slice(None)
            for i in range(len(spec.shape))
        )
        return arr[idx]

    return read_slice


def reader_from_shards(
    shards: Dict[Tuple[str, int], np.ndarray],
    specs: Dict[str, LeafSpec], src_world: int,
) -> Callable[[str, int, int, int], np.ndarray]:
    """A ``read_slice`` over per-(key, src_rank) local shards — the
    shape checkpoint data actually has on disk."""

    def read_slice(key: str, src_rank: int, lo: int, hi: int):
        spec = specs[key]
        arr = shards[key, src_rank]
        if spec.kind == "replicated":
            return arr
        base, _ = shard_extent(spec.shape[spec.axis], src_world, src_rank)
        idx = tuple(
            slice(lo - base, hi - base) if i == spec.axis else slice(None)
            for i in range(len(spec.shape))
        )
        return arr[idx]

    return read_slice


def execute_plan(
    plan: ReshardPlan,
    read_slice: Callable[[str, int, int, int], np.ndarray],
    write_shard: Callable[[str, int, np.ndarray], None],
    *,
    dst_ranks: Optional[Sequence[int]] = None,
    meter: Optional[MemoryMeter] = None,
) -> MemoryMeter:
    """Run the schedule with numpy: for each leaf, for each destination
    rank, allocate the destination shard, stage each inbound slice,
    copy, free — then hand the shard to ``write_shard`` and free it.
    At no point is more than (1 destination shard + 1 staged slice)
    live per leaf, which is exactly what the meter records.

    ``read_slice(key, src_rank, lo, hi)`` returns the slice of that
    source shard covering global range ``[lo, hi)`` on the sharded
    axis (whole array for replicated). ``dst_ranks`` restricts
    execution to some destination ranks (a surviving rank rebuilding
    only its own shard)."""
    meter = meter or MemoryMeter()
    wanted = list(range(plan.dst_world)) if dst_ranks is None else [
        int(d) for d in dst_ranks
    ]
    for d in wanted:
        if not (0 <= d < plan.dst_world):
            raise ReshardError(
                f"dst rank {d} out of range for world {plan.dst_world}"
            )
    for key in sorted(plan.specs):
        spec = plan.specs[key]
        wire = spec.wire_dtype()
        per_dst: Dict[int, List[Transfer]] = {}
        for t in plan.transfers.get(key, []):
            per_dst.setdefault(t.dst_rank, []).append(t)
        for d in wanted:
            ts = per_dst.get(d, [])
            if spec.kind == "replicated":
                if not ts:
                    continue
                chunk = np.asarray(read_slice(key, ts[0].src_rank, 0, 0))
                meter.alloc(chunk.nbytes)
                if chunk.dtype != wire:
                    chunk = np.ascontiguousarray(chunk).view(wire)
                buf = np.array(chunk)
                meter.alloc(buf.nbytes)
                meter.free(chunk.nbytes)
            else:
                dshape = shard_shape(spec, plan.dst_world, d)
                dlo, _dhi = shard_extent(
                    spec.shape[spec.axis], plan.dst_world, d
                )
                buf = np.empty(dshape, dtype=wire)
                meter.alloc(buf.nbytes)
                for t in ts:
                    chunk = np.asarray(read_slice(key, t.src_rank, t.lo,
                                                  t.hi))
                    meter.alloc(chunk.nbytes)
                    if chunk.dtype != wire:
                        chunk = np.ascontiguousarray(chunk).view(wire)
                    idx = tuple(
                        slice(t.lo - dlo, t.hi - dlo)
                        if i == spec.axis else slice(None)
                        for i in range(len(spec.shape))
                    )
                    buf[idx] = chunk
                    meter.free(chunk.nbytes)
            write_shard(key, d, buf)
            meter.free(buf.nbytes)
    return meter


def reshard_flat(
    flat: Dict[str, np.ndarray],
    specs: Dict[str, LeafSpec],
    src_world: int,
    dst_world: int,
) -> Dict[Tuple[str, int], np.ndarray]:
    """Convenience: plan + execute over in-memory global arrays,
    returning ``{(key, dst_rank): shard}``. The bounded-memory story
    belongs to the shard-file path; this is for small states and
    tests."""
    plan = plan_reshard(specs, src_world, dst_world)
    out: Dict[Tuple[str, int], np.ndarray] = {}
    execute_plan(
        plan,
        reader_from_global(flat, specs, src_world),
        lambda key, d, arr: out.__setitem__((key, d), arr),
    )
    return out


def assemble_global(
    shards: Dict[Tuple[str, int], np.ndarray],
    specs: Dict[str, LeafSpec],
    world: int,
) -> Dict[str, np.ndarray]:
    """Stitch per-rank shards back into global arrays (resume paths
    that want the whole state in one process; inverse of
    :func:`reshard_flat` at world 1 granularity)."""
    out: Dict[str, np.ndarray] = {}
    for key, spec in specs.items():
        if spec.kind == "replicated":
            out[key] = np.asarray(shards[key, 0])
            continue
        parts = [np.asarray(shards[key, r]) for r in range(world)]
        out[key] = np.concatenate(parts, axis=spec.axis) if parts else (
            np.empty(spec.shape, dtype=spec.wire_dtype())
        )
    return out


# ---------------------------------------------------------------------
# on-mesh execution (the existing collective ops)
# ---------------------------------------------------------------------


def execute_plan_on_mesh(
    plan: ReshardPlan,
    my_rank: int,
    read_slice: Callable[[str, int, int, int], Optional[np.ndarray]],
    *,
    src_owner: Optional[Callable[[int], int]] = None,
    send_fn: Optional[Callable[..., Any]] = None,
    recv_fn: Optional[Callable[..., Any]] = None,
) -> Dict[str, np.ndarray]:
    """Execute the plan inside a live ``dst_world``-rank world using
    the framework's point-to-point ops: every rank walks the same
    global transfer order; for each transfer the owner of the source
    shard sends the staged slice, the destination rank receives it
    into its buffer, and everyone else does nothing. One send/recv
    pair at a time in a globally agreed order — deadlock-free the same
    way the schedule simulator proves p2p programs are.

    ``src_owner(src_rank)`` maps an *old-world* shard index to the
    current rank that can read it (after an N→M shrink the survivor
    with new rank r typically holds old shards ``r, r+M, ...`` — i.e.
    ``src_owner = lambda s: s % M``, the default). ``read_slice`` is
    consulted only on the owning rank. Returns this rank's
    destination shards keyed by leaf.
    """
    if not (0 <= my_rank < plan.dst_world):
        raise ReshardError(
            f"rank {my_rank} out of range for world {plan.dst_world}"
        )
    owner = src_owner or (lambda s: s % plan.dst_world)
    if send_fn is None or recv_fn is None:
        import mpi4jax_tpu as m4t

        send_fn = send_fn or m4t.send
        recv_fn = recv_fn or m4t.recv

    import numpy as _np

    out: Dict[str, np.ndarray] = {}
    for key in sorted(plan.specs):
        spec = plan.specs[key]
        wire = spec.wire_dtype()
        # jax arrays cannot carry void dtypes: opaque bytes travel as
        # the matching unsigned int and are viewed back on arrival
        transport = wire
        if wire.kind == "V":
            if wire.itemsize not in (1, 2, 4, 8):
                raise ReshardError(
                    f"no transport dtype for itemsize {wire.itemsize}"
                )
            transport = np.dtype(f"u{wire.itemsize}")
        buf = None
        dlo = 0
        if spec.kind != "replicated":
            dlo, _ = shard_extent(
                spec.shape[spec.axis], plan.dst_world, my_rank
            )
        for t in plan.transfers.get(key, []):
            src_p = owner(t.src_rank)
            dst_p = t.dst_rank
            i_send = src_p == my_rank
            i_recv = dst_p == my_rank
            if not (i_send or i_recv):
                continue
            if i_recv and buf is None:
                shape = shard_shape(spec, plan.dst_world, my_rank)
                buf = _np.empty(shape, dtype=wire)
            chunk = None
            if i_send:
                chunk = _np.ascontiguousarray(
                    _np.asarray(read_slice(key, t.src_rank, t.lo, t.hi))
                )
                if chunk.dtype != transport:
                    chunk = chunk.view(transport)  # contiguous by now
            if i_send and i_recv:
                pass  # local copy, no wire trip
            elif i_send:
                send_fn(chunk, dest=dst_p)
                continue
            else:
                shape = list(spec.shape)
                if spec.kind != "replicated":
                    shape[spec.axis] = t.hi - t.lo
                chunk = _np.asarray(
                    recv_fn(_np.empty(tuple(shape), dtype=transport),
                            source=src_p)
                )
            if chunk.dtype != wire:
                chunk = chunk.view(wire)
            if spec.kind == "replicated":
                buf[...] = chunk.reshape(buf.shape)
            else:
                idx = tuple(
                    slice(t.lo - dlo, t.hi - dlo)
                    if i == spec.axis else slice(None)
                    for i in range(len(spec.shape))
                )
                buf[idx] = chunk.reshape(buf[idx].shape)
        if buf is not None:
            out[key] = buf
    return out


# ---------------------------------------------------------------------
# checkpoint resharding (the elastic launcher's offline path)
# ---------------------------------------------------------------------


def reshard_checkpoint(
    mgr: Any,
    info: Any,
    dst_world: int,
    *,
    out_mgr: Any = None,
    log: Optional[Callable[[str], None]] = None,
) -> Any:
    """Rewrite the ``m4t-ckpt/2`` checkpoint ``info`` (world N) as an
    equivalent checkpoint at ``dst_world`` ranks, through a planned
    bounded-memory schedule: source shards are memory-mapped, each
    destination shard is built slice by slice and written to the
    staging dir before the next one is touched. Commits atomically at
    the *same step* (``out_mgr`` redirects to a different root) with
    ``resharded_from`` provenance in the manifest; returns the new
    :class:`~.ckpt.CheckpointInfo`.
    """
    from . import ckpt as _ckpt

    manifest = info.manifest
    if manifest.get("schema") != _ckpt.MANIFEST_SCHEMA_V2:
        raise ReshardError(
            f"checkpoint step {info.step} has schema "
            f"{manifest.get('schema')!r}; only {_ckpt.MANIFEST_SCHEMA_V2} "
            "records the sharding layout needed to reshard"
        )
    specs = _ckpt.specs_from_manifest(manifest)
    src_world = int(manifest.get("world") or 0)
    if src_world < 1:
        raise ReshardError(
            f"checkpoint step {info.step} records no world size"
        )
    plan = plan_reshard(specs, src_world, dst_world)
    if log:
        s = plan.summary()
        log(
            f"resharding step {info.step}: world {src_world} -> "
            f"{dst_world}, {s['transfers']} transfer(s), "
            f"{s['moved_bytes']} B moved, peak scratch "
            f"{s['peak_scratch_bytes']} B (bound {s['memory_bound_bytes']} B)"
        )
    read_slice = _ckpt.shard_slice_reader(info, specs, src_world)
    target = out_mgr or mgr
    extra = {
        "resharded_from": {
            "world": src_world,
            "step": info.step,
            "plan": plan.summary(),
        }
    }
    return target.save_resharded(
        info.step, plan, read_slice, specs, extra=extra,
    )


# ---------------------------------------------------------------------
# selftest (device-free; wired into tier-1 and the CLI)
# ---------------------------------------------------------------------


def selftest(verbose: bool = False) -> int:
    """Seeded end-to-end exercise of the primitive with no jax, no
    devices: partition math, plan coverage, metered execution against
    the planned peak, round-trip bit-identity, and the opaque-dtype
    wire path."""
    rng = np.random.RandomState(0)

    # partition math: cover, stay contiguous, stay balanced
    for length in (0, 1, 5, 8, 64, 101):
        for world in (1, 2, 3, 4, 7, 16):
            spans = [shard_extent(length, world, r) for r in range(world)]
            assert spans[0][0] == 0 and spans[-1][1] == length
            for (a, b), (c, d) in zip(spans, spans[1:]):
                assert b == c and b >= a and d >= c
            sizes = {b - a for a, b in spans}
            assert len(sizes) <= 2 and max(sizes) - min(sizes) <= 1

    # random layouts x random world pairs: execute, meter, round-trip
    for trial in range(12):
        n_leaves = rng.randint(1, 5)
        specs: Dict[str, LeafSpec] = {}
        flat: Dict[str, np.ndarray] = {}
        for i in range(n_leaves):
            nd = rng.randint(1, 4)
            shape = tuple(int(rng.randint(1, 9)) for _ in range(nd))
            dtype = rng.choice(["float32", "int32", "float64"])
            kind = "replicated" if rng.rand() < 0.3 else "sharded"
            axis = int(rng.randint(0, nd)) if kind == "sharded" else 0
            key = f"leaf{i}"
            specs[key] = LeafSpec(shape=shape, dtype=dtype, kind=kind,
                                  axis=axis)
            flat[key] = (rng.randn(*shape) * 8).astype(dtype)
        src_world = int(rng.randint(1, 7))
        dst_world = int(rng.randint(1, 7))

        plan = plan_reshard(specs, src_world, dst_world)
        # coverage: each destination index written exactly once
        for key, spec in specs.items():
            if spec.kind != "sharded":
                continue
            for d in range(dst_world):
                dlo, dhi = shard_extent(
                    spec.shape[spec.axis], dst_world, d)
                got = sorted(
                    (t.lo, t.hi) for t in plan.transfers[key]
                    if t.dst_rank == d
                )
                covered = dlo
                for lo, hi in got:
                    assert lo == covered, (key, d, got)
                    covered = hi
                assert covered == dhi

        # execute from shards (the on-disk shape), meter the peak
        shards = {
            (k, r): np.asarray(flat[k][shard_slices(s, src_world, r)])
            for k, s in specs.items() for r in range(src_world)
        }
        meter = MemoryMeter()
        out: Dict[Tuple[str, int], np.ndarray] = {}
        execute_plan(
            plan, reader_from_shards(shards, specs, src_world),
            lambda k, d, a: out.__setitem__((k, d), a), meter=meter,
        )
        assert meter.live == 0
        assert meter.peak == plan.max_peak_bytes(), (
            meter.peak, plan.max_peak_bytes())
        assert meter.peak <= plan.memory_bound_bytes()
        # correctness: shards equal direct slicing of the global array
        for k, s in specs.items():
            for d in range(dst_world):
                want = flat[k][shard_slices(s, dst_world, d)]
                np.testing.assert_array_equal(out[k, d], want)
        # round trip M -> N is bit-identical to the original shards
        back = {}
        execute_plan(
            plan_reshard(specs, dst_world, src_world),
            reader_from_shards(
                {k: v for k, v in out.items()}, specs, dst_world),
            lambda k, d, a: back.__setitem__((k, d), a),
        )
        for k_r, arr in shards.items():
            np.testing.assert_array_equal(back[k_r], arr)
        if verbose:
            print(
                f"  trial {trial}: {n_leaves} leaves "
                f"{src_world}->{dst_world} peak {meter.peak} B "
                f"(bound {plan.memory_bound_bytes()} B)"
            )

    # opaque wire dtype: bytes move correctly without the logical dtype
    spec = LeafSpec(shape=(6, 3), dtype="mystery16", itemsize=2)
    raw = np.arange(18, dtype=np.uint16).reshape(6, 3).view("V2")
    out2 = reshard_flat({"x": raw}, {"x": spec}, 1, 4)
    merged = np.concatenate(
        [out2["x", r].view(np.uint16) for r in range(4)], axis=0
    )
    np.testing.assert_array_equal(merged, raw.view(np.uint16))

    print("reshard selftest ok")
    return 0
