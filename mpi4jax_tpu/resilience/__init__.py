"""Resilience subsystem: provoke failures, survive them.

PRs 1–4 built detection — flight recorder, cross-rank doctor, static
linter, perf anomaly watch. This package closes the loop from
*detection* to *recovery*, the robustness shape real TPU/cloud fleets
need (preemptions, slow hosts, and transient hangs are weather, not
incidents — see PAPERS.md, Cloud Collectives):

- :mod:`.faults` — deterministic, seeded fault-injection plans
  (``M4T_FAULT_PLAN`` / ``launch --fault-plan``): delay / hang /
  crash / slowdown at the Nth emission of an op on a rank, logged as
  ``fault`` JSONL events so injected and observed failures can be
  overlaid. Chaos testing for everything below.
- :mod:`.ckpt` — :class:`~.ckpt.CheckpointManager`: step-tagged
  atomic checkpoint commits (tmp dir + rename, manifest written
  last), retention of the last K, and ``latest_valid()`` that skips
  torn or mismatched checkpoints on resume.
- :mod:`.supervisor` — restart policy over the doctor's verdicts:
  transient failures (hang, dead/missing rank, plain crash) restart
  from the latest valid checkpoint with exponential backoff + jitter
  and ``M4T_RESUME_STEP`` exported to the children; deterministic
  failures (MISMATCH, statically attributable) fail fast with the
  diagnosis. Every attempt is recorded in a ``supervisor.jsonl``
  audit log. Driven by ``python -m mpi4jax_tpu.launch --retries K
  --backoff S --resume-dir DIR``.

``python -m mpi4jax_tpu.resilience --selftest`` is the device-free CI
smoke (no jax, no orbax, no subprocesses). See ``docs/resilience.md``.
"""

from . import ckpt  # noqa: F401
from . import faults  # noqa: F401
from . import supervisor  # noqa: F401
from .ckpt import CheckpointInfo, CheckpointManager  # noqa: F401
from .faults import FaultPlan, FaultPlanError, InjectedFault  # noqa: F401
from .supervisor import (  # noqa: F401
    RetryPolicy,
    Supervisor,
    classify,
    classify_findings,
    resume_step,
)

__all__ = [
    "CheckpointInfo",
    "CheckpointManager",
    "FaultPlan",
    "FaultPlanError",
    "InjectedFault",
    "RetryPolicy",
    "Supervisor",
    "ckpt",
    "classify",
    "classify_findings",
    "faults",
    "resume_step",
    "supervisor",
]
