"""Resilience subsystem: provoke failures, survive them.

PRs 1–4 built detection — flight recorder, cross-rank doctor, static
linter, perf anomaly watch. This package closes the loop from
*detection* to *recovery*, the robustness shape real TPU/cloud fleets
need (preemptions, slow hosts, and transient hangs are weather, not
incidents — see PAPERS.md, Cloud Collectives):

- :mod:`.faults` — deterministic, seeded fault-injection plans
  (``M4T_FAULT_PLAN`` / ``launch --fault-plan``): delay / hang /
  crash / slowdown at the Nth emission of an op on a rank, logged as
  ``fault`` JSONL events so injected and observed failures can be
  overlaid. Chaos testing for everything below.
- :mod:`.ckpt` — :class:`~.ckpt.CheckpointManager`: step-tagged
  atomic checkpoint commits (tmp dir + rename, manifest written
  last), retention of the last K, and ``latest_valid()`` that skips
  torn or mismatched checkpoints on resume.
- :mod:`.supervisor` — restart policy over the doctor's verdicts:
  transient failures (hang, dead/missing rank, plain crash,
  preemption) restart from the latest valid checkpoint with
  exponential backoff + jitter and ``M4T_RESUME_STEP`` exported to
  the children; deterministic failures (MISMATCH, statically
  attributable) fail fast with the diagnosis. Every attempt is
  recorded in a ``supervisor.jsonl`` audit log. Driven by ``python -m
  mpi4jax_tpu.launch --retries K --backoff S --resume-dir DIR``.
- :mod:`.reshard` — the elastic half: a planned, peak-memory-bounded
  (≤ 2 shard sizes per rank) resharding primitive that rewrites an
  N-rank ``m4t-ckpt/2`` checkpoint for M ranks, device-free (numpy;
  the offline ``reshard`` CLI) or on-mesh (the existing p2p ops).
  :class:`~.supervisor.PreemptGuard` turns a SIGTERM preemption
  notice into checkpoint-and-exit-143, and ``launch --elastic
  --min-ranks K`` turns "we lost two hosts" into "restart at the
  shrunk world from a resharded checkpoint" instead of a dead job.

``python -m mpi4jax_tpu.resilience --selftest`` is the device-free CI
smoke (no devices, no orbax, no subprocesses); ``python -m
mpi4jax_tpu.resilience reshard --selftest`` covers the resharding
primitive the same way. See ``docs/resilience.md``.
"""

from . import ckpt  # noqa: F401
from . import faults  # noqa: F401
from . import reshard  # noqa: F401
from . import supervisor  # noqa: F401
from .ckpt import CheckpointInfo, CheckpointManager  # noqa: F401
from .faults import FaultPlan, FaultPlanError, InjectedFault  # noqa: F401
from .reshard import (  # noqa: F401
    LeafSpec,
    ReshardError,
    ReshardPlan,
    plan_reshard,
    reshard_checkpoint,
)
from .supervisor import (  # noqa: F401
    PREEMPT_EXIT,
    PreemptGuard,
    RetryPolicy,
    Supervisor,
    classify,
    classify_findings,
    resume_step,
)

__all__ = [
    "CheckpointInfo",
    "CheckpointManager",
    "FaultPlan",
    "FaultPlanError",
    "InjectedFault",
    "LeafSpec",
    "PREEMPT_EXIT",
    "PreemptGuard",
    "ReshardError",
    "ReshardPlan",
    "RetryPolicy",
    "Supervisor",
    "ckpt",
    "classify",
    "classify_findings",
    "faults",
    "plan_reshard",
    "reshard",
    "reshard_checkpoint",
    "resume_step",
    "supervisor",
]
