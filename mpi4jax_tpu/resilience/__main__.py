"""``python -m mpi4jax_tpu.resilience``: device-free CLIs.

- ``--selftest`` mirrors ``observability.perf --selftest``: a
  CI-runnable exercise of the subsystem's pure-Python core —
  fault-plan parsing and matching, the checkpoint commit/validity
  protocol (via a JSON storage layer, so no jax/orbax), verdict
  classification, and the supervisor retry loop — with no devices, no
  subprocess worlds, no network. Wired into tier-1 by
  ``tests/test_resilience.py`` so the CLI cannot silently rot.
- ``reshard ROOT --world M`` rewrites the newest (or ``--step S``)
  ``m4t-ckpt/2`` checkpoint under ``ROOT`` for an M-rank world
  through the planned bounded-memory schedule (``reshard.py``) —
  what ``launch --elastic`` runs between attempts, and what an
  operator runs by hand to move a run across differently-sized
  reservations. ``--dry-run`` prints the plan (transfers, bytes
  moved, peak scratch vs bound) without writing; ``--out DIR``
  writes the resharded checkpoint to a different root;
  ``reshard --selftest`` is the primitive's own device-free smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

from .ckpt import CheckpointManager
from .faults import FaultPlan, FaultPlanError, faults_selftest_hook
from .supervisor import RetryPolicy, Supervisor, classify


def _json_save(path: str, state) -> None:
    with open(path, "w") as f:
        json.dump(state, f)


def _json_restore(path: str, template):
    with open(path) as f:
        return json.load(f)


def selftest() -> int:
    # -- fault plans: parse, validate, count, inject -------------------
    plan = FaultPlan.parse(json.dumps({
        "seed": 7,
        "faults": [
            {"rank": 0, "op": "AllReduce", "nth": 2, "action": "delay",
             "ms": 1},
            {"rank": 1, "op": "*", "nth": 1, "action": "crash"},
        ],
    }))
    assert len(plan.rules) == 2 and plan.seed == 7
    plan.validate_world(2)
    for bad, needle in (
        ("{not json", "not valid JSON"),
        ('{"faults": []}', "non-empty"),
        ('[{"rank": 0, "op": "NoSuchOp", "action": "hang"}]', "unknown op"),
        ('[{"rank": 0, "op": "Barrier", "action": "explode"}]', "action"),
        ('[{"rank": -1, "op": "Barrier", "action": "hang"}]', "rank"),
        ('[{"rank": 0, "op": "Barrier", "action": "delay"}]', "ms"),
        ('[{"rank": 0, "action": "hang"}]', "'op' or 'fingerprint'"),
    ):
        try:
            FaultPlan.parse(bad)
        except FaultPlanError as e:
            assert needle in str(e), (bad, e)
        else:
            raise AssertionError(f"plan {bad!r} should not parse")
    try:
        FaultPlan.parse(
            '[{"rank": 5, "op": "Barrier", "action": "hang"}]'
        ).validate_world(2)
    except FaultPlanError as e:
        assert "out of range" in str(e)
    else:
        raise AssertionError("rank 5 of world 2 should not validate")
    fired = faults_selftest_hook(plan)
    assert fired == ["delay@AllReduce#2"], fired

    # -- checkpoint manager: atomicity, retention, validity ------------
    with tempfile.TemporaryDirectory() as tmp:
        mgr = CheckpointManager(
            os.path.join(tmp, "ckpt"), keep=2, world=2,
            save_fn=_json_save, restore_fn=_json_restore,
        )
        for step in (1, 2, 3, 4):
            mgr.save(step, {"w": [step, step], "step": step},
                     fingerprint="fp0")
        assert mgr.steps() == [3, 4], mgr.steps()  # retention keep=2
        info = mgr.latest_valid(fingerprint="fp0", world=2)
        assert info is not None and info.step == 4
        # torn checkpoint (no manifest) is skipped, older one wins
        os.unlink(os.path.join(info.path, "manifest.json"))
        info2 = mgr.latest_valid(fingerprint="fp0", world=2)
        assert info2 is not None and info2.step == 3, info2
        # wrong fingerprint / world are skipped too
        assert mgr.latest_valid(fingerprint="other") is None
        assert mgr.latest_valid(fingerprint="fp0", world=4) is None
        state = mgr.restore(info2, template=None)
        assert state == {"w": [3, 3], "step": 3}

    # -- classification ------------------------------------------------
    assert classify(None, 0)["klass"] == "clean"
    assert classify(None, 1) == {
        "klass": "transient", "reason": "crash_no_telemetry", "kinds": [],
    }
    mismatch = {"findings": [{"kind": "mismatch", "seq": 3, "groups": []}]}
    assert classify(mismatch, 1)["klass"] == "deterministic"
    hang = {"findings": [{"kind": "hang", "rank": 1, "verdict": "hung"}]}
    assert classify(hang, 124) == {
        "klass": "transient", "reason": "hang", "kinds": ["hang"],
    }
    both = {"findings": mismatch["findings"] + hang["findings"]}
    assert classify(both, 124)["klass"] == "deterministic"
    clean_crash = {"findings": []}
    assert classify(clean_crash, 1)["reason"] == "crash_without_mismatch"

    # -- retry policy + supervisor loop --------------------------------
    policy = RetryPolicy(retries=3, backoff_s=1.0, jitter=0.0)
    assert [policy.delay(a) for a in range(4)] == [0.0, 1.0, 2.0, 4.0]
    capped = RetryPolicy(retries=9, backoff_s=1.0, max_backoff_s=4.0,
                         jitter=0.0)
    assert capped.delay(9) == 4.0

    # transient failures retry (with the resumed step advancing), then
    # succeed
    calls = []
    sup = Supervisor(
        lambda attempt, resume: calls.append((attempt, resume)) or (
            0 if attempt == 2 else 1
        ),
        policy=RetryPolicy(retries=3, backoff_s=0.0, jitter=0.0),
        diagnose_fn=lambda attempt: {"findings": []},
        resume_fn=lambda: 10 * (len(calls)),
        sleep_fn=lambda s: None,
    )
    assert sup.run() == 0
    assert calls == [(0, None), (1, 10), (2, 20)], calls
    assert [a["action"] for a in sup.attempts] == ["retry", "retry", "done"]

    # deterministic failure is never retried
    calls2 = []
    sup2 = Supervisor(
        lambda attempt, resume: calls2.append(attempt) or 1,
        policy=RetryPolicy(retries=5, backoff_s=0.0, jitter=0.0),
        diagnose_fn=lambda attempt: {
            "findings": [{"kind": "mismatch", "seq": 1, "groups": []}]
        },
        sleep_fn=lambda s: None,
    )
    assert sup2.run() == 1
    assert calls2 == [0], calls2
    assert sup2.attempts[-1]["action"] == "give_up"
    assert sup2.attempts[-1]["klass"] == "deterministic"

    # retry budget is bounded
    calls3 = []
    sup3 = Supervisor(
        lambda attempt, resume: calls3.append(attempt) or 7,
        policy=RetryPolicy(retries=2, backoff_s=0.0, jitter=0.0),
        diagnose_fn=lambda attempt: {"findings": []},
        sleep_fn=lambda s: None,
    )
    assert sup3.run() == 7
    assert calls3 == [0, 1, 2], calls3

    print("resilience selftest ok")
    return 0


def reshard_main(argv) -> int:
    """The ``reshard`` subcommand (offline, numpy-only)."""
    from . import reshard as _reshard
    from . import ckpt as _ckpt

    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.resilience reshard",
        description=(
            "Rewrite an m4t-ckpt/2 checkpoint written at world N as an "
            "equivalent checkpoint for world M, through a planned "
            "slice-transfer schedule whose peak scratch per rank is "
            "bounded by 2 shard sizes."
        ),
    )
    parser.add_argument(
        "root", nargs="?", default=None,
        help="CheckpointManager root holding the source checkpoint",
    )
    parser.add_argument(
        "--world", type=int, default=None, metavar="M",
        help="target world size",
    )
    parser.add_argument(
        "--step", type=int, default=None, metavar="S",
        help="reshard this exact step (default: newest valid)",
    )
    parser.add_argument(
        "--out", default=None, metavar="DIR",
        help="write the resharded checkpoint under this root instead "
        "of committing in place",
    )
    parser.add_argument(
        "--keep", type=int, default=3, metavar="N",
        help="retention at the target root (default %(default)s)",
    )
    parser.add_argument(
        "--dry-run", action="store_true",
        help="plan only: print transfers / bytes moved / peak scratch "
        "vs the 2-shard bound, write nothing",
    )
    parser.add_argument(
        "--json", action="store_true", help="print the plan summary as JSON",
    )
    parser.add_argument(
        "--selftest", action="store_true",
        help="device-free smoke of the primitive (partition math, "
        "plan coverage, metered execution vs planned peak, round-trip "
        "bit-identity)",
    )
    args = parser.parse_args(argv)

    if args.selftest:
        return _reshard.selftest()
    if not args.root or args.world is None:
        parser.error("reshard needs ROOT and --world M (or --selftest)")
    if args.world < 1:
        parser.error("--world must be >= 1")

    mgr = CheckpointManager(args.root, keep=args.keep, world=args.world)
    if args.step is not None:
        info = mgr.at_step(args.step, allow_reshard=True)
    else:
        info = mgr.latest_valid(allow_reshard=True)
    if info is None:
        print(
            f"reshard: no valid checkpoint under {args.root}",
            file=sys.stderr,
        )
        return 2
    if not info.sharded:
        print(
            f"reshard: checkpoint step {info.step} has schema "
            f"{info.schema!r}; only m4t-ckpt/2 records the sharding "
            "layout needed to reshard",
            file=sys.stderr,
        )
        return 1
    src_world = info.world or 0
    specs = _ckpt.specs_from_manifest(info.manifest)
    plan = _reshard.plan_reshard(specs, src_world, args.world)
    summary = plan.summary()
    if args.json:
        print(json.dumps({"step": info.step, **summary}, indent=1))
    else:
        print(
            f"reshard: step {info.step}: world {src_world} -> "
            f"{args.world}; {summary['leaves']} leaves, "
            f"{summary['transfers']} transfer(s), "
            f"{summary['moved_bytes']} B moved; peak scratch "
            f"{summary['peak_scratch_bytes']} B <= bound "
            f"{summary['memory_bound_bytes']} B",
            file=sys.stderr,
        )
    if args.dry_run:
        return 0
    if src_world == args.world and not args.out:
        print(
            f"reshard: checkpoint step {info.step} is already at "
            f"world {args.world}; nothing to do",
            file=sys.stderr,
        )
        return 0
    out_mgr = None
    if args.out:
        out_mgr = CheckpointManager(
            args.out, keep=args.keep, world=args.world
        )
    new = _reshard.reshard_checkpoint(
        mgr, info, args.world, out_mgr=out_mgr,
        log=lambda m: print(f"reshard: {m}", file=sys.stderr),
    )
    print(
        f"reshard: committed step {new.step} at world {args.world} "
        f"under {new.path}",
        file=sys.stderr,
    )
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "reshard":
        return reshard_main(argv[1:])
    if "--selftest" in argv:
        return selftest()
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main())
