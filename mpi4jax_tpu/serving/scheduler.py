"""Fair job scheduling: FIFO within a tenant, round-robin across them.

A plain FIFO queue lets one chatty tenant starve everyone else — they
submit 50 jobs, every other tenant waits behind all 50. The serving
scheduler keeps FIFO *within* each tenant (submit order is respected
where it is fair) but rotates *across* tenants: each pick goes to the
least-recently-served tenant that has work, ties broken by whose
oldest job has waited longest. One job per pick, because the mesh
runs one world at a time; fairness emerges from the rotation, not
from preemption.

Deterministic by construction (no clocks, no randomness): the same
pending list picked in sequence always yields the same order, which
is what the fairness property test pins.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from . import profile as _profile
from .spool import JobSpec


class FairScheduler:
    """Pick the next job from a FIFO-ordered pending list."""

    def __init__(self) -> None:
        #: tenant -> logical time of its last pick (-1 = never served)
        self._last_pick: Dict[str, int] = {}
        self._clock = 0
        #: undo state for :meth:`revert` (one level deep)
        self._prev: Optional[tuple] = None

    def pick(self, pending: List[JobSpec]) -> Optional[JobSpec]:
        """The next job to claim, or None when the queue is empty.

        ``pending`` must be in FIFO submit order (``Spool.pending()``
        is). The winning tenant is the one served least recently;
        among never-served tenants, the one whose oldest job was
        submitted first — so a fresh scheduler over a fresh queue
        degenerates to exactly FIFO until a second job from a
        repeat tenant would cut the line."""
        if not pending:
            return None
        prof = _profile.active
        if prof is None:
            return self._pick(pending)
        # armed: the decision is micro-spanned (``picked=`` joins it to
        # the winning job's queue-wait decomposition); determinism is
        # untouched — the profiler only brackets, never reorders
        t0 = prof.t()
        spec = self._pick(pending)
        prof.phase(
            "sched.pick", t0, picked=spec.id if spec else None,
            depth=len(pending),
        )
        return spec

    def _pick(self, pending: List[JobSpec]) -> Optional[JobSpec]:
        first: Dict[str, JobSpec] = {}
        order: Dict[str, int] = {}
        for i, spec in enumerate(pending):
            if spec.tenant not in first:
                first[spec.tenant] = spec
                order[spec.tenant] = i
        tenant = min(
            first,
            key=lambda t: (self._last_pick.get(t, -1), order[t]),
        )
        self._prev = (tenant, self._last_pick.get(tenant), self._clock)
        self._clock += 1
        self._last_pick[tenant] = self._clock
        return first[tenant]

    def pick_batch(
        self, pending: List[JobSpec], k: int
    ) -> List[JobSpec]:
        """Up to ``k`` jobs in fair pick order — the batch the
        event-driven loop leases in one :meth:`~.spool.Spool.claim_batch`.

        Pure simulation: repeated single picks are replayed against
        *copies* of the fairness state, so tenant round-robin holds
        across the batch boundary (three jobs from tenant ``a`` and
        one each from ``b``/``c`` batch as ``a, b, c`` — never
        ``a, a, a``) while the real state stays untouched until
        :meth:`commit_batch` records the claim *winners*. A federated
        server that loses part of the batch to a peer therefore burns
        no tenant's turn for jobs it never dispatched."""
        if k <= 0 or not pending:
            return []
        prof = _profile.active
        last = dict(self._last_pick)
        clock = self._clock
        remaining = list(pending)
        out: List[JobSpec] = []
        while remaining and len(out) < k:
            first: Dict[str, JobSpec] = {}
            order: Dict[str, int] = {}
            for i, spec in enumerate(remaining):
                if spec.tenant not in first:
                    first[spec.tenant] = spec
                    order[spec.tenant] = i
            t0 = prof.t() if prof is not None else 0.0
            tenant = min(
                first, key=lambda t: (last.get(t, -1), order[t])
            )
            clock += 1
            last[tenant] = clock
            spec = first[tenant]
            if prof is not None:
                prof.phase(
                    "sched.pick", t0, picked=spec.id,
                    depth=len(remaining),
                )
            out.append(spec)
            remaining.remove(spec)
        return out

    def commit_batch(self, won: List[JobSpec]) -> None:
        """Fold the claim winners of a :meth:`pick_batch` into the
        real fairness state, in pick order — exactly the mutations a
        sequence of single :meth:`pick` calls for those jobs would
        have made (race losers simply never happened)."""
        for spec in won:
            self._clock += 1
            self._last_pick[spec.tenant] = self._clock
        self._prev = None

    def revert(self) -> None:
        """Undo the most recent :meth:`pick`. A federated server that
        loses the claim race to a peer must not burn the tenant's
        turn — the pick never dispatched, so fairness state rolls
        back as if it never happened."""
        if self._prev is None:
            return
        tenant, last, clock = self._prev
        self._prev = None
        self._clock = clock
        if last is None:
            self._last_pick.pop(tenant, None)
        else:
            self._last_pick[tenant] = last
