"""``python -m mpi4jax_tpu.serving``: the serving-plane CLI.

Subcommands:

- ``serve SPOOL -n N`` — run the queue-draining supervisor over the
  spool: claim jobs fairly, run each in its own fault domain, shrink
  elastically on preemption (``--elastic --min-ranks K``), gate
  admission through the static verifier (``--verify``), export queue
  metrics (``SPOOL/metrics.prom``, ``--metrics-port``).
- ``submit SPOOL [--spec JOB.json | flags + argv]`` — validate and
  enqueue one job; prints the JSON response. Exit 0 = queued, 3 =
  rejected (queue_full / draining / duplicate_id — the explicit
  backpressure contract), 2 = invalid spec. With ``--wait`` the exit
  code mirrors the job's *outcome* instead: 0 completed, 1 failed,
  3 rejected (2 when ``--wait-timeout`` expires first).
- ``status SPOOL`` — queue depth, running and finished jobs, plus the
  federation server table (who holds a lease, how fresh it is).
- ``drain SPOOL [--wait]`` — stop admission (new submits are
  rejected) and, with ``--wait``, block until the queue is empty.
  The sentinel is spool-global: every federated server sees it.
- ``reclaim SPOOL`` — one offline scavenger pass: requeue running
  entries whose owner's lease expired (the same pass every federated
  server runs in its loop; this is the no-server-left recovery tool).
- ``dispatch SPOOL`` — the event-driven dispatch plane's counters
  (active wake wire, wakeups, batch sizes, coalesced jobs, group
  commits, fsyncs/job); ``dispatch --selftest`` exercises the wires,
  batched claims, coalescing and group commit device-free.
- ``--selftest`` — device-free exercise of the whole control plane
  (spool protocol, scheduler fairness, server loop under a stub
  runner including elastic shrink over a real resharded checkpoint,
  exporter contract). No devices, no subprocess worlds; wired into
  tier-1 by ``tests/test_serving.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .scheduler import FairScheduler
from .server import Server
from .spool import (
    DEFAULT_LEASE_S,
    DEFAULT_MAX_RECLAIMS,
    JobSpecError,
    Spool,
    parse_job,
)


def _cmd_serve(args) -> int:
    spool = Spool(args.spool)
    if args.fastpath:
        # pool workers are separate processes: they learn the serve
        # loop runs event-driven from the env and arm their own
        # mailbox wake wires (serving/pool.py worker_loop)
        os.environ["M4T_DISPATCH_FASTPATH"] = str(args.fastpath)
    if args.queue_cap is not None:
        spool.configure(args.queue_cap)
    slo = None
    if args.slo:
        from .slo import SLOError, SLOWatch, parse_slo

        try:
            slo = SLOWatch(
                spool, parse_slo(args.slo), min_jobs=args.slo_min_jobs
            )
        except SLOError as e:
            print(f"serve: {e}", file=sys.stderr)
            return 2
    pool = None
    if args.warm:
        from .pool import WorkerPool

        pool = WorkerPool(
            os.path.join(spool.root, "pool"),
            args.nproc,
            heartbeat_s=args.pool_heartbeat,
            deadline_s=args.pool_deadline,
            mesh=args.mesh,
            elastic=args.elastic,
            audit=spool.audit,
            span=spool.span,
        )
    try:
        server = Server(
            spool,
            nproc=args.nproc,
            elastic=args.elastic,
            min_ranks=args.min_ranks,
            verify=args.verify,
            poll_s=args.poll,
            max_jobs=args.max_jobs,
            idle_exit_s=args.idle_exit,
            metrics_port=args.metrics_port,
            pool=pool,
            slo=slo,
            server_id=args.server_id,
            lease_s=args.lease,
            max_reclaims=args.max_reclaims,
            fastpath=args.fastpath,
            batch=args.batch,
            coalesce=not args.no_coalesce,
        )
    except ValueError as e:
        print(f"serve: {e}", file=sys.stderr)
        return 2
    if pool is not None:
        pool.start()
    try:
        return server.serve()
    finally:
        if pool is not None:
            pool.stop()


def _cmd_submit(args) -> int:
    spool = Spool(args.spool)
    if args.spec:
        text = args.spec
        if os.path.exists(text):
            with open(text) as f:
                text = f.read()
        try:
            obj = json.loads(text)
        except json.JSONDecodeError as e:
            print(f"submit: spec is not valid JSON: {e}",
                  file=sys.stderr)
            return 2
    else:
        if not args.cmd and not args.module:
            print(
                "submit: need --spec, --module, or an argv to run "
                "(e.g. `submit SPOOL script.py arg`)", file=sys.stderr,
            )
            return 2
        obj = {"cmd": list(args.cmd) or None, "module": args.module}
        obj = {k: v for k, v in obj.items() if v is not None}
    # explicit flags override/augment the spec body
    for key, value in (
        ("id", args.id), ("tenant", args.tenant),
        ("nproc", args.nproc), ("timeout_s", args.timeout),
        ("retries", args.retries), ("backoff_s", args.backoff),
        ("resume_dir", args.resume_dir),
        ("fault_plan", args.fault_plan),
    ):
        if value is not None:
            obj[key] = value
    if args.verify:
        obj["verify"] = True
    try:
        response = spool.submit(obj)
    except JobSpecError as e:
        print(f"submit: {e}", file=sys.stderr)
        return 2
    print(json.dumps(response))
    if response.get("status") != "queued":
        return 3
    if not args.wait:
        return 0
    # block until the id is terminal; the exit code mirrors the
    # outcome, so e2e scripts need no hand-rolled poll loop
    job_id = response["job"]
    deadline = (
        None if args.wait_timeout is None
        else time.monotonic() + args.wait_timeout
    )
    while True:
        for rec in spool.done():
            if rec.get("id") == job_id:
                outcome = str(rec.get("outcome"))
                print(json.dumps({
                    "job": job_id, "outcome": outcome,
                    "reason": rec.get("reason"),
                }))
                return {
                    "completed": 0, "failed": 1, "rejected": 3,
                }.get(outcome, 1)
        if deadline is not None and time.monotonic() > deadline:
            print(
                f"submit: job {job_id} not terminal after "
                f"{args.wait_timeout:g}s", file=sys.stderr,
            )
            return 2
        time.sleep(0.2)


def _cmd_status(args) -> int:
    from . import export as sexport

    spool = Spool(args.spool)
    status = spool.status()
    pool = sexport.pool_snapshot(spool)
    if args.json:
        if pool is not None:
            status = dict(status, pool=pool)
        print(json.dumps(status, indent=1))
        return 0
    print(
        f"spool {status['root']}: depth {status['depth']}/"
        f"{status['capacity']}"
        + (" [draining]" if status["draining"] else "")
    )
    servers = status.get("servers") or []
    if servers:
        alive = sum(1 for s in servers if s.get("alive"))
        print(f"  servers: {alive}/{len(servers)} alive")
        for s in servers:
            age = s.get("lease_age_s")
            print(
                f"    {s.get('id')}: "
                + ("lease ok" if s.get("alive") else "lease EXPIRED")
                + (f", renewed {age:.1f}s ago"
                   if age is not None else "")
                + f" (lease {s.get('lease_s'):g}s, "
                f"pid {s.get('pid')})"
            )
    for state in ("pending", "running"):
        for job in status[state]:
            owner = ""
            if state == "running" and job.get("server"):
                owner = (
                    f" server={job['server']} epoch={job.get('epoch')}"
                )
            print(
                f"  {state:>7}  {job['job']}  tenant={job['tenant']} "
                f"nproc={job['nproc']}" + owner
            )
    for job in status["done"]:
        print(
            f"  {job.get('outcome', '?'):>7}  {job.get('job')}  "
            f"tenant={job.get('tenant')}"
        )
    if status["outcomes"]:
        print("  outcomes: " + ", ".join(
            f"{k}={v}" for k, v in sorted(status["outcomes"].items())
        ))
    disp = sexport._dispatch_snapshot(spool)
    if disp is not None:
        wakeups = sum((disp.get("wakeups") or {}).values())
        fpj = disp.get("fsyncs_per_job")
        print(
            f"  dispatch: wire {disp.get('wire')}, "
            f"{wakeups} wakeup(s), {disp.get('batches', 0)} batch(es) "
            f"(p50 {disp.get('batch_size_p50')}), "
            f"{disp.get('coalesced_jobs', 0)} coalesced job(s)"
            + (f", {fpj:g} fsyncs/job" if fpj is not None else "")
        )
    if pool is not None:
        counters = pool.get("counters", {})
        print(
            f"  warm pool: {pool.get('capacity')}/{pool.get('size')} "
            f"slot(s), {counters.get('respawns', 0)} respawn(s), "
            f"{sum((counters.get('quarantines') or {}).values())} "
            f"quarantine(s), {counters.get('poisoned', 0)} poisoned "
            "job(s)"
        )
        ages = pool.get("heartbeat_age_s", {})
        for worker in pool.get("workers", []):
            rank = worker.get("rank")
            age = ages.get(str(rank))
            print(
                f"    worker {rank}: {worker.get('state'):>11}  "
                f"inc {worker.get('incarnation')}  "
                f"served {worker.get('jobs_served')}  "
                + (f"beat {age:.1f}s ago" if age is not None
                   else "no heartbeat")
            )
    return 0


def _cmd_reclaim(args) -> int:
    spool = Spool(args.spool)
    actions = spool.reclaim(
        by=args.by, max_reclaims=args.max_reclaims,
        grace_s=args.grace,
    )
    if args.json:
        print(json.dumps(actions, indent=1))
    else:
        for act in actions:
            print(
                f"reclaim: job {act.get('job')} "
                f"{act.get('action')} (owner "
                f"{act.get('from_server')}, {act.get('reason')})",
                file=sys.stderr,
            )
        print(
            f"reclaim: {len(actions)} action(s) on {spool.root}",
            file=sys.stderr,
        )
    return 0


def _cmd_profile(args) -> int:
    from . import profile as cp_profile

    report = cp_profile.profile_report(args.spool)
    if not report["records"]:
        print(
            f"profile: no control-plane records under {args.spool} — "
            f"arm with {cp_profile.ENV_VAR}=1 before serving",
            file=sys.stderr,
        )
        return 2
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(cp_profile.format_report(report))
    return 0


def _cmd_dispatch(args) -> int:
    from . import dispatch as _dispatch

    return _dispatch.main(
        [args.spool] + (["--json"] if args.json else [])
    )


def _cmd_drain(args) -> int:
    spool = Spool(args.spool)
    spool.request_drain(note=args.note or "")
    print(f"drain: requested on {spool.root}", file=sys.stderr)
    if not args.wait:
        return 0
    deadline = time.monotonic() + args.timeout
    while time.monotonic() < deadline:
        status = spool.status()
        if not status["pending"] and not status["running"]:
            print("drain: queue empty", file=sys.stderr)
            return 0
        time.sleep(args.poll)
    print(
        f"drain: queue not empty after {args.timeout:g}s",
        file=sys.stderr,
    )
    return 1


# ---------------------------------------------------------------------
# selftest (device-free; wired into tier-1)
# ---------------------------------------------------------------------


def selftest() -> int:  # noqa: C901 — one linear smoke script
    import tempfile

    import numpy as np

    from . import export as sexport
    from ..resilience import ckpt as _ckpt
    from ..resilience.reshard import LeafSpec

    # -- job-spec validation: every bad field is named -----------------
    for bad, needle in (
        ("{not json", "not valid JSON"),
        ("[]", "JSON object"),
        ('{"cmd": ["x"], "nope": 1}', "unknown field"),
        ('{"cmd": ["x"], "module": "m"}', "exactly one"),
        ('{"module": "m", "nproc": 0}', "nproc"),
        ('{"cmd": [], "nproc": 1}', "cmd"),
        ('{"cmd": ["x"], "timeout_s": -1}', "timeout_s"),
        ('{"cmd": ["x"], "retries": -2}', "retries"),
        ('{"cmd": ["x"], "tenant": "bad tenant!"}', "tenant"),
        ('{"cmd": ["x"], "id": "no spaces allowed"}', "id"),
        ('{"cmd": ["x"], "env": {"A": 1}}', "env"),
        ('{"cmd": ["x"], "fault_plan": {"faults": []}}', "fault_plan"),
    ):
        try:
            parse_job(bad)
        except JobSpecError as e:
            assert needle in str(e), (bad, e)
        else:
            raise AssertionError(f"spec {bad!r} should not parse")
    spec = parse_job({"cmd": ["-c", "pass"], "tenant": "t0",
                      "nproc": 2, "retries": 1})
    assert spec.nproc == 2 and spec.target == "-c"

    with tempfile.TemporaryDirectory() as tmp:
        # -- spool protocol: submit/claim/finish, bounded queue --------
        spool = Spool(os.path.join(tmp, "spool"))
        spool.configure(3)
        assert spool.capacity == 3
        ids = []
        for i, tenant in enumerate(("a", "a", "b")):
            r = spool.submit({
                "id": f"j{i}", "tenant": tenant, "cmd": ["-c", "pass"],
            })
            assert r["status"] == "queued", r
            ids.append(r["job"])
        full = spool.submit({"id": "j3", "cmd": ["-c", "pass"]})
        assert full == {
            "job": "j3", "status": "rejected", "reason": "queue_full",
            "depth": 3, "capacity": 3,
        }, full
        dup = spool.submit({"id": "j0", "cmd": ["-c", "pass"]})
        # queue_full outranks duplicate detection at depth 3; drain one
        assert dup["status"] == "rejected"
        assert spool.depth() == 3

        # -- scheduler: FIFO within tenant, round-robin across ---------
        sched = FairScheduler()
        picked = []
        pending = spool.pending()
        while pending:
            s = sched.pick(pending)
            picked.append((s.id, s.tenant))
            assert spool.claim(s) is not None
            spool.finish(s, "completed", queue_wait_s=0.0, run_s=0.0,
                         attempts=1, world=1)
            spool.audit("completed", job=s.id, tenant=s.tenant)
            pending = spool.pending()
        # a, then b (round-robin cuts a's second job), then a again
        assert picked == [("j0", "a"), ("j2", "b"), ("j1", "a")], picked
        assert sched.pick([]) is None
        # double-claim: the loser of the rename race gets None
        r = spool.submit({"id": "j4", "cmd": ["-c", "pass"]})
        (s4,) = spool.pending()
        assert spool.claim(s4) is not None
        assert spool.claim(s4) is None
        spool.finish(s4, "completed")
        # duplicate id now rejected explicitly (j0 lives in done/)
        dup = spool.submit({"id": "j0", "cmd": ["-c", "pass"]})
        assert dup["status"] == "rejected" and (
            dup["reason"] == "duplicate_id"
        ), dup

        # -- drain: new submits rejected, queue still drains -----------
        spool.request_drain("selftest")
        assert spool.draining()
        r = spool.submit({"id": "late", "cmd": ["-c", "pass"]})
        assert r["status"] == "rejected" and r["reason"] == "draining"

        # -- server loop under a stub runner ---------------------------
        # fresh spool: 4 jobs — one clean, one transient-then-clean
        # (retries budget), one always-failing, one preempted under
        # --elastic with a real m4t-ckpt/2 checkpoint resharded 2 -> 1
        spool2 = Spool(os.path.join(tmp, "spool2"))
        spool2.configure(8)
        ckroot = os.path.join(tmp, "ck")
        mgr = _ckpt.CheckpointManager(ckroot, keep=2, world=2)
        mgr.save_sharded(
            5, {"w": np.arange(8.0, dtype=np.float64)},
            {"w": LeafSpec(shape=(8,), dtype="float64")},
        )
        for obj in (
            {"id": "clean", "tenant": "a", "cmd": ["-c", "pass"],
             "nproc": 2},
            {"id": "flaky", "tenant": "b", "cmd": ["-c", "pass"],
             "nproc": 2, "retries": 2, "backoff_s": 0.0},
            {"id": "bad", "tenant": "a", "cmd": ["-c", "pass"],
             "nproc": 2, "retries": 1, "backoff_s": 0.0},
            {"id": "pre", "tenant": "c", "cmd": ["-c", "pass"],
             "nproc": 2, "retries": 2, "backoff_s": 0.0,
             "resume_dir": ckroot},
        ):
            assert spool2.submit(obj)["status"] == "queued"

        calls = []

        def stub_runner(spec, world, events_dir, attempt, resume_step):
            calls.append((spec.id, world, attempt, resume_step))
            assert events_dir and os.path.isdir(events_dir)
            if spec.id == "flaky":
                return (1, []) if attempt == 0 else (0, [])
            if spec.id == "bad":
                return 1, []
            if spec.id == "pre" and attempt == 0:
                return 143, [1]  # rank 1 preempted: capacity lost
            return 0, []

        server = Server(
            spool2, nproc=2, elastic=True, min_ranks=1,
            max_jobs=4, poll_s=0.01, runner=stub_runner,
            log=lambda msg: None,
        )
        rc = server.serve()
        assert rc == 0, rc
        assert server.capacity == 1  # shrank when "pre" lost a rank
        outcomes = {
            rec["id"]: rec["outcome"] for rec in spool2.done()
        }
        assert outcomes == {
            "clean": "completed", "flaky": "completed",
            "bad": "failed", "pre": "completed",
        }, outcomes
        # the preempted job resumed from the *resharded* step at the
        # shrunk world; its checkpoint now exists at world 1
        pre_calls = [c for c in calls if c[0] == "pre"]
        assert pre_calls[0][1] == 2 and pre_calls[1][1] == 1, pre_calls
        assert pre_calls[1][3] == 5, pre_calls  # resumed at step 5
        info = _ckpt.CheckpointManager(ckroot, world=1).latest_valid(
            world=1
        )
        assert info is not None and info.manifest[
            "resharded_from"]["world"] == 2
        # the audit accounts for every job id, and the world transition
        recs = spool2.audit_records()
        by_event = {}
        for r in recs:
            by_event.setdefault(r["event"], []).append(r)
        done_ids = {
            r["job"] for e in ("completed", "failed", "rejected")
            for r in by_event.get(e, [])
        }
        assert done_ids == {"clean", "flaky", "bad", "pre"}, done_ids
        (world_rec,) = by_event["world"]
        assert world_rec["world"] == 2 and world_rec["next_world"] == 1
        assert world_rec["resharded_from_step"] == 5
        assert world_rec["preempted_ranks"] == [1]

        # per-job fault domain: "bad" burned its own retry budget only
        bad_calls = [c for c in calls if c[0] == "bad"]
        assert len(bad_calls) == 2, bad_calls

        # -- admission gate: an unprovable job is rejected -------------
        spool3 = Spool(os.path.join(tmp, "spool3"))
        assert spool3.submit(
            {"id": "nope", "cmd": ["-c", "pass"], "verify": True}
        )["status"] == "queued"
        server3 = Server(
            spool3, nproc=2, max_jobs=1, poll_s=0.01,
            runner=stub_runner,
            verify_fn=lambda spec, world: False,
            log=lambda msg: None,
        )
        assert server3.serve() == 0
        (rec,) = spool3.done()
        assert rec["outcome"] == "rejected"
        assert rec["reason"] == "verify_failed"

        # -- exporter contract -----------------------------------------
        snap = sexport.serving_snapshot(spool2)
        assert snap["counts"]["completed"] == 3
        assert snap["counts"]["failed"] == 1
        assert snap["world"] == 1  # last audited transition
        text = sexport.render_serving_metrics(snap)
        assert text.endswith("# EOF\n")
        for needle in (
            "m4t_serve_queue_depth 0",
            'm4t_serve_jobs_total{outcome="completed"} 3',
            'm4t_serve_jobs_total{outcome="failed"} 1',
            "m4t_serve_world 1",
            'm4t_serve_job_attempts{job="pre",tenant="c"} 2',
        ):
            assert needle in text, (needle, text)
        path = sexport.write_serving_prom(spool2)
        assert os.path.exists(path)
        assert open(path).read() == sexport.render_serving_metrics(
            sexport.serving_snapshot(spool2)
        )
        # rejected reasons are split out (spool1 saw all three kinds)
        text1 = sexport.render_serving_metrics(
            sexport.serving_snapshot(spool)
        )
        assert 'm4t_serve_rejected_total{reason="queue_full"} 2' in text1
        assert 'm4t_serve_rejected_total{reason="draining"} 1' in text1

        # ======== resident warm pool (serving/pool.py) ================
        import threading

        from . import pool as pool_mod

        # -- work-item execution + the hygiene contract ----------------
        base = {"schema": pool_mod.WORK_SCHEMA, "item": "i", "job": "j"}
        r = pool_mod.run_item({**base, "cmd": ["-c", "pass"]})
        assert r["rc"] == 0 and r["hygiene"]["clean"], r
        r = pool_mod.run_item(
            {**base, "cmd": ["-c", "import sys; sys.exit(7)"]}
        )
        assert r["rc"] == 7, r
        r = pool_mod.run_item(
            {**base, "cmd": ["-c", "raise ValueError('boom')"]}
        )
        assert r["rc"] == 1 and "ValueError" in r["error"], r
        # env bleed is named AND rolled back
        r = pool_mod.run_item({**base, "cmd": [
            "-c", "import os; os.environ['M4T_SELFTEST_BLEED'] = '1'",
        ]})
        assert r["hygiene"]["env_bleed"] == ["M4T_SELFTEST_BLEED"], r
        assert not r["hygiene"]["clean"]
        assert "M4T_SELFTEST_BLEED" not in os.environ
        # a plan the payload armed itself is a violation...
        r = pool_mod.run_item({**base, "cmd": [
            "-c",
            "from mpi4jax_tpu.resilience import faults; "
            "faults.arm(faults.FaultPlan.parse("
            "{'faults': [{'op': '*', 'action': 'delay', 'ms': 1}]}))",
        ]})
        assert r["hygiene"]["fault_armed"] and not r["hygiene"]["clean"]
        # ...one the job declared is scoped to it and unscoped after
        from ..resilience import faults as _faults

        r = pool_mod.run_item({
            **base, "cmd": ["-c", "pass"],
            "fault_plan": {
                "faults": [{"op": "*", "action": "delay", "ms": 1}]
            },
        })
        assert r["rc"] == 0 and r["hygiene"]["clean"], r
        assert _faults.active_plan is None
        # sub-mesh packing: the payload sees its GroupComm partition
        r = pool_mod.run_item({
            **base,
            "cmd": ["-c",
                    "from mpi4jax_tpu.serving.pool import job_comm; "
                    "c = job_comm(); "
                    "assert c.groups == ((1, 2), (0,), (3,)), c.groups"],
            "group": {"ranks": [1, 2], "rank": 0, "size": 2,
                      "world": 4},
        })
        assert r["rc"] == 0, r

        # -- pool doctor: quarantine / respawn / two-strikes -----------
        class _ThreadWorker:
            """Stub handle: the real mailbox + hygiene code paths,
            driven by an in-process thread instead of a subprocess.
            A job env carrying STUB_WEDGE makes it claim the item,
            stop heartbeating, and never answer — the wedge shape."""

            def __init__(self, p, w):
                self.rc = None
                self.pid = None
                self._stop = threading.Event()
                self._root, self._rank = p.root, w.rank
                self._inc = w.incarnation
                self._t = threading.Thread(target=self._run,
                                           daemon=True)
                self._t.start()

            def poll(self):
                return self.rc

            def terminate(self):
                self._stop.set()

            kill = terminate

            def wait(self, timeout=None):
                self._t.join(timeout)

            def _run(self):
                from ..observability import events as ev

                sink = ev.EventLog(
                    pool_mod.worker_sink(self._root, self._rank)
                )
                wdir = pool_mod.worker_dir(self._root, self._rank)
                inbox = os.path.join(wdir, pool_mod.INBOX_DIR)
                outbox = os.path.join(wdir, pool_mod.OUTBOX_DIR)
                cur = os.path.join(wdir, "current.json")
                while not self._stop.is_set():
                    sink.append(ev.event(
                        "heartbeat", source="stub", t=time.time(),
                    ))
                    name = pool_mod._oldest_entry(inbox)
                    if name is not None:
                        try:
                            os.replace(os.path.join(inbox, name), cur)
                            with open(cur) as f:
                                item = json.load(f)
                        except (OSError, json.JSONDecodeError):
                            continue
                        if (item.get("env") or {}).get("STUB_WEDGE"):
                            self._stop.wait(60.0)
                            return
                        res = pool_mod.run_item(
                            item, worker=self._rank,
                            incarnation=self._inc,
                        )
                        pool_mod._write_json_atomic(
                            os.path.join(
                                outbox, f"{item['item']}.json"
                            ),
                            res,
                        )
                        try:
                            os.unlink(cur)
                        except OSError:
                            pass
                    time.sleep(0.01)

        spool4 = Spool(os.path.join(tmp, "spool4"))
        pool = pool_mod.WorkerPool(
            os.path.join(spool4.root, "pool"), 2,
            spawn_fn=lambda p, w: _ThreadWorker(p, w),
            heartbeat_s=0.05, deadline_s=0.5, start_deadline_s=10.0,
            check_s=0.01, audit=spool4.audit, log=lambda m: None,
        )
        pool.start(doctor=False)
        for obj in (
            {"id": "warm", "tenant": "a", "cmd": ["-c", "pass"],
             "nproc": 2},
            {"id": "leaky", "tenant": "a", "cmd": [
                "-c", "import os; os.environ['M4T_LEAK'] = '1'",
            ]},
            {"id": "wedger", "tenant": "b", "cmd": ["-c", "pass"],
             "env": {"STUB_WEDGE": "1"}, "retries": 3,
             "backoff_s": 0.0},
        ):
            assert spool4.submit(obj)["status"] == "queued"
        server4 = Server(
            spool4, nproc=2, max_jobs=3, poll_s=0.01, pool=pool,
            log=lambda msg: None,
        )
        rc = server4.serve()
        pool._write_state(force=True)
        assert rc == 0, rc
        outcomes = {
            rec["id"]: rec["outcome"] for rec in spool4.done()
        }
        assert outcomes == {
            "warm": "completed", "leaky": "completed",
            "wedger": "failed",
        }, outcomes
        failed = [rec for rec in spool4.done() if rec["id"] == "wedger"]
        assert failed[0]["reason"] == "poisoned", failed
        # two strikes: exactly two wedged quarantines, then refusal
        assert pool.strikes("wedger") == 2
        assert pool.poisoned("wedger")
        q = pool.counters["quarantines"]
        assert q.get("wedged") == 2, q
        assert q.get("hygiene") == 1, q  # "leaky" dirtied its worker
        assert pool.counters["respawns"] == 3, pool.counters
        # every slot healed: back to a live incarnation
        by_event = {}
        for rec in spool4.audit_records():
            by_event.setdefault(rec["event"], []).append(rec)
        for needle in ("pool_start", "pool_dispatch",
                       "pool_quarantine", "pool_respawn",
                       "pool_strike", "pool_poisoned",
                       "pool_hygiene"):
            assert by_event.get(needle), (needle, sorted(by_event))
        # exporter: per-worker health + pool counters
        snap4 = sexport.serving_snapshot(spool4)
        assert snap4["pool"] and snap4["pool"]["size"] == 2
        text4 = sexport.render_serving_metrics(snap4)
        for needle in (
            "m4t_pool_capacity 2",
            'm4t_pool_quarantines_total{reason="wedged"} 2',
            'm4t_pool_quarantines_total{reason="hygiene"} 1',
            "m4t_pool_respawns_total 3",
            "m4t_pool_poisoned_total 1",
            'm4t_pool_worker_alive{worker="0"}',
            'm4t_pool_worker_last_heartbeat_age{worker="1"}',
        ):
            assert needle in text4, (needle, text4)
        pool.stop(grace_s=0.2)

        # ======== federation: leases, reclaim, zombie fencing =========
        spool5 = Spool(os.path.join(tmp, "spool5"))
        spool5.configure(8)
        ck5 = os.path.join(tmp, "ck5")
        mgr5 = _ckpt.CheckpointManager(ck5, keep=2, world=1)
        mgr5.save_sharded(
            7, {"w": np.arange(4.0, dtype=np.float64)},
            {"w": LeafSpec(shape=(4,), dtype="float64")},
        )
        assert spool5.submit({
            "id": "orph", "cmd": ["-c", "pass"], "nproc": 1,
            "resume_dir": ck5,
        })["status"] == "queued"
        # server A registers, claims, then "dies" (no more renewals)
        spool5.register_server("sA", lease_s=1.0, now=100.0)
        (specA,) = spool5.pending()
        claimed = spool5.claim(specA, server="sA")
        assert claimed is not None and claimed.epoch == 1
        assert spool5.claim(specA, server="sB") is None  # one winner
        # before expiry the scavenger must not touch the claim
        assert spool5.reclaim(now=100.5, by="sB") == []
        acts = spool5.reclaim(now=102.0, by="sB")
        assert [a["action"] for a in acts] == ["requeued"], acts
        (req,) = spool5.pending()
        assert req.reclaims == 1
        assert req.reclaimed_from[0]["server"] == "sA"
        # server B drains the orphan; it resumes from the checkpoint
        # the dead server left behind
        resumes = []

        def runner5(spec, world, events_dir, attempt, resume_step):
            resumes.append(resume_step)
            return 0, []

        serverB = Server(
            spool5, nproc=1, max_jobs=1, poll_s=0.01, runner=runner5,
            server_id="sB", lease_s=30.0, log=lambda msg: None,
        )
        assert serverB.serve() == 0
        assert resumes == [7], resumes  # reclaimed job started warm
        (rec5,) = spool5.done()
        assert rec5["outcome"] == "completed"
        assert rec5["reclaims"] == 1, rec5
        # the zombie revives and writes its stale outcome: fenced
        assert spool5.finish(
            claimed, "completed", server="sA", epoch=1
        ) is False
        assert [r["id"] for r in spool5.done()] == ["orph"]
        by5 = {}
        for r in spool5.audit_records():
            by5.setdefault(r["event"], []).append(r)
        for needle in ("server_register", "lease_expired", "reclaim",
                       "fenced", "server_stop"):
            assert by5.get(needle), (needle, sorted(by5))
        terminal5 = [
            r for e in ("completed", "failed", "rejected")
            for r in by5.get(e, []) if r.get("job") == "orph"
        ]
        assert len(terminal5) == 1, terminal5  # exactly-once, audited
        # exporter: the federation metric families
        text5 = sexport.render_serving_metrics(
            sexport.serving_snapshot(spool5)
        )
        for needle in (
            "m4t_serve_servers_alive",
            'm4t_serve_reclaims_total{reason="lease_expired"} 1',
            "m4t_serve_fenced_total 1",
            'm4t_serve_server_lease_age{server="sA"}',
        ):
            assert needle in text5, (needle, text5)
        # persistent poison verdicts accumulate across servers
        spool5.record_strike("tox", reason="wedged", server="sA")
        assert not spool5.poisoned("tox")
        spool5.record_strike("tox", reason="wedged", server="sB")
        assert spool5.poisoned("tox") and spool5.strikes("tox") == 2

    print("serving selftest ok")
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["dispatch"] and "--selftest" in argv:
        from . import dispatch as _dispatch

        return _dispatch.selftest()
    if "--selftest" in argv:
        return selftest()
    # everything after a standalone `--` is the job's argv, verbatim —
    # argparse.REMAINDER would otherwise swallow the submit flags too
    job_argv: list = []
    if argv and argv[0] == "submit" and "--" in argv:
        split = argv.index("--")
        job_argv = argv[split + 1:]
        argv = argv[:split]
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.serving", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("serve", help="run the serving supervisor")
    p.add_argument("spool", help="spool directory (created if absent)")
    p.add_argument("-n", "--nproc", type=int, required=True,
                   help="mesh capacity in ranks")
    p.add_argument("--elastic", action="store_true",
                   help="treat preemption exits (143/SIGTERM) as "
                   "capacity loss: drain, reshard the resident job's "
                   "checkpoint, continue smaller")
    p.add_argument("--min-ranks", type=int, default=1, metavar="K",
                   help="elastic floor: below K survivors the server "
                   "stops with exit 1 (default %(default)s)")
    p.add_argument("--verify", action="store_true",
                   help="admission gate: prove every job's declared "
                   "entry points deadlock-free at its world before "
                   "it runs (unprovable jobs are rejected)")
    p.add_argument("--queue-cap", type=int, default=None, metavar="C",
                   help="pin the bounded-queue capacity (submits past "
                   "it are rejected queue_full)")
    p.add_argument("--poll", "--poll-interval", type=float,
                   default=0.2, metavar="S", dest="poll",
                   help="idle poll period between queue scans "
                   "(default %(default)s)")
    p.add_argument("--max-jobs", type=int, default=None, metavar="N",
                   help="exit 0 after serving N jobs (harness bound)")
    p.add_argument("--idle-exit", type=float, default=None,
                   metavar="S",
                   help="exit 0 after S idle seconds (harness bound)")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="P",
                   help="serve queue OpenMetrics on "
                   "http://127.0.0.1:P/metrics (0 = free port)")
    p.add_argument("--warm", action="store_true",
                   help="resident warm pool: spawn -n worker "
                   "processes once (serving/pool.py) and dispatch "
                   "jobs to them as mailbox work items — imports, "
                   "compile caches and the plan cache stay warm "
                   "across jobs; the pool doctor quarantines and "
                   "respawns wedged/crashed/leaky workers")
    p.add_argument("--mesh", action="store_true",
                   help="with --warm: spawn the pool as one resident "
                   "shm world so payloads can run real cross-worker "
                   "collectives over their sub-mesh (job_comm()); "
                   "default is un-meshed workers that can be killed "
                   "and respawned independently")
    p.add_argument("--pool-heartbeat", type=float, default=0.5,
                   metavar="S",
                   help="with --warm: worker heartbeat period "
                   "(default %(default)s)")
    p.add_argument("--pool-deadline", type=float, default=None,
                   metavar="S",
                   help="with --warm: quarantine a worker after S "
                   "seconds without a fresh heartbeat (default "
                   "max(6 heartbeats, 3s))")
    p.add_argument("--slo", default=None, metavar="SPEC",
                   help="declarative SLOs (serving/slo.py): inline "
                   "'p99_latency_s=2.0[,error_rate=0.05]', inline "
                   "JSON, or a slo.json path with per-tenant "
                   "overrides; breaches land as deduped verdict "
                   "events in SPOOL/slo.jsonl (+ retune "
                   "recommendations when communication dominates) "
                   "and the doctor narrates the dominant stage")
    p.add_argument("--slo-min-jobs", type=int, default=1, metavar="N",
                   help="finished jobs a tenant needs before its "
                   "percentile objectives are judged (default "
                   "%(default)s)")
    p.add_argument("--server-id", default=None, metavar="ID",
                   help="federation identity for this serving loop "
                   "(registry file, claim owner suffix, fence key); "
                   "default: a unique minted id")
    p.add_argument("--lease", type=float, default=DEFAULT_LEASE_S,
                   metavar="S",
                   help="heartbeat lease: peers presume this server "
                   "dead and reclaim its running jobs after S "
                   "seconds without a renewal (default %(default)s)")
    p.add_argument("--max-reclaims", type=int,
                   default=DEFAULT_MAX_RECLAIMS, metavar="K",
                   help="per-job reclaim cap: a job orphaned more "
                   "than K times ends failed: reclaim_exhausted "
                   "(default %(default)s)")
    p.add_argument("--fastpath", nargs="?", const="auto",
                   default=None, metavar="WIRE",
                   help="event-driven dispatch (serving/dispatch.py): "
                   "wake wires instead of idle polls, batched lease "
                   "claims, same-shape job coalescing, group-"
                   "committed terminal records; WIRE pins the wake "
                   "wire (inotify|socket|poll-fallback; default: "
                   "best available)")
    p.add_argument("--batch", type=int, default=8, metavar="K",
                   help="with --fastpath: lease up to K jobs per "
                   "claim batch (default %(default)s)")
    p.add_argument("--no-coalesce", action="store_true",
                   help="with --fastpath: never fuse same-shape jobs "
                   "into one sub-mesh dispatch")
    p.set_defaults(fn=_cmd_serve)

    p = sub.add_parser("submit", help="enqueue one job")
    p.add_argument("spool")
    p.add_argument("--spec", default=None, metavar="FILE|JSON",
                   help="full job spec (m4t-job/1) as a file or "
                   "inline JSON; flags below override its fields")
    p.add_argument("--id", default=None)
    p.add_argument("--tenant", default=None)
    p.add_argument("-n", "--nproc", type=int, default=None)
    p.add_argument("--timeout", type=float, default=None, metavar="S",
                   help="per-job deadline (grace-kill past it)")
    p.add_argument("--retries", type=int, default=None, metavar="K")
    p.add_argument("--backoff", type=float, default=None, metavar="S")
    p.add_argument("--resume-dir", default=None, metavar="CKPTROOT")
    p.add_argument("--fault-plan", default=None, metavar="SPEC",
                   help="chaos: per-job fault plan (path or inline "
                   "JSON)")
    p.add_argument("--verify", action="store_true",
                   help="gate this job through the static verifier")
    p.add_argument("--wait", action="store_true",
                   help="block until the job is terminal; exit code "
                   "mirrors the outcome (0 completed / 1 failed / "
                   "3 rejected)")
    p.add_argument("--wait-timeout", type=float, default=None,
                   metavar="S",
                   help="with --wait: give up (exit 2) after S "
                   "seconds (default: wait forever)")
    p.add_argument("-m", dest="module", default=None,
                   help="run a module instead of a script")
    p.add_argument("cmd", nargs="*",
                   help="argv appended to `python`; put it after a "
                   "standalone `--` when it starts with a dash "
                   "(e.g. `submit SPOOL --id j1 -- -c pass`)")
    p.set_defaults(fn=_cmd_submit)

    p = sub.add_parser("status", help="queue + outcome summary")
    p.add_argument("spool")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_status)

    p = sub.add_parser("reclaim", help="offline scavenger pass: "
                       "requeue orphans of dead servers")
    p.add_argument("spool")
    p.add_argument("--by", default=None, metavar="ID",
                   help="attribute the pass to this server id "
                   "(skips its own claims)")
    p.add_argument("--max-reclaims", type=int,
                   default=DEFAULT_MAX_RECLAIMS, metavar="K")
    p.add_argument("--grace", type=float, default=0.0, metavar="S",
                   help="extra slack on top of each owner's lease "
                   "before it counts as expired")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_reclaim)

    p = sub.add_parser("profile", help="control-plane micro-span "
                       "report: per-phase p50/p99, syscall budget, "
                       "wasted wakeups, queue-wait decomposition "
                       "(arm the server with M4T_CP_PROFILE=1 first)")
    p.add_argument("spool")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_profile)

    p = sub.add_parser("dispatch", help="event-driven dispatch "
                       "counters: active wake wire, wakeups, batch "
                       "sizes, coalesced jobs, group commits, "
                       "fsyncs/job (run serve --fastpath first; "
                       "--selftest exercises the plane device-free)")
    p.add_argument("spool")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_dispatch)

    p = sub.add_parser("drain", help="stop admission; optionally wait "
                       "for the queue to empty")
    p.add_argument("spool")
    p.add_argument("--wait", action="store_true")
    p.add_argument("--timeout", type=float, default=300.0, metavar="S")
    p.add_argument("--poll", type=float, default=0.5, metavar="S")
    p.add_argument("--note", default=None)
    p.set_defaults(fn=_cmd_drain)

    args = parser.parse_args(argv)
    if job_argv:
        args.cmd = list(getattr(args, "cmd", []) or []) + job_argv
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
