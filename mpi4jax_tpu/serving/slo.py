"""Per-tenant SLOs over the serving plane, with stage attribution.

The span chain (``observability/spans.py``) decomposes every job's
wall clock; this module turns that decomposition into objectives and
verdicts:

- **Latency breakdown** — :func:`job_breakdown` splits one finished
  job into ``queue_wait / verify / dispatch / spawn|warm_dispatch /
  comm / compute / result`` seconds. The communication share comes
  from the PR 4 attribution join over the job's own telemetry
  records (``spans.collect_job_records``: dedicated attempt dirs on
  the cold path, trace-id-filtered resident-worker sinks on the warm
  path): every runtime ``latency`` sample is collective time, so
  ``comm`` is the per-rank mean of sampled collective seconds and
  ``compute`` is the run remainder.
- **Objectives** — a declarative config (``serve --slo
  'p99_latency_s=2.0'`` inline, or a JSON file with per-tenant
  overrides) over per-tenant percentiles of finished-job latency
  (queue wait + run), queue wait alone, and the failure rate::

      {"default": {"p99_latency_s": 2.0},
       "tenants": {"bulk": {"p99_latency_s": 30.0,
                            "error_rate": 0.1}}}

- **Breach verdicts** — :class:`SLOWatch` evaluates after every
  finished job and appends *deduped* verdict events to
  ``SPOOL/slo.jsonl`` in the exact shape the PR 8 retune loop
  consumes (``{"kind": "verdict", "finding": {...}, "klass": ...}``),
  plus a ``retune`` recommendation carrying the breached job's plan
  keys whenever the dominant stage is communication — so ``planner
  tune --from-verdicts SPOOL`` can re-pin from an SLO breach the same
  way it re-pins from a live straggler. Every breach is also audited
  (``event: "slo_breach"``) on ``serving.jsonl``.
- **Narration** — :func:`narrate` names the dominant stage in
  operator language (``job j7: 83% queue-wait -> capacity, not
  compute``); the doctor prints it whenever a spool with SLO verdicts
  is diagnosed.

Import-light (stdlib only) like the rest of the offline stack.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

from .spool import Spool

SLO_LOG_NAME = "slo.jsonl"

#: recognised objective keys
_QUANTILE_RE = re.compile(r"^p(\d{2})_(latency|queue_wait)_s$")
_SCALAR_OBJECTIVES = frozenset({"error_rate"})

#: stage -> what the dominant stage means for the operator
STAGE_ADVICE = {
    "queue_wait": "capacity, not compute",
    "verify": "admission gate",
    "dispatch": "control-plane overhead",
    "spawn": "cold spawn latency — consider serve --warm",
    "warm_dispatch": "pool dispatch latency",
    "comm": "communication-bound — retune candidates recorded",
    "compute": "compute-bound",
    "result": "bookkeeping",
}


class SLOError(ValueError):
    """An SLO config that cannot mean what was written."""


def _check_objectives(obj: Dict[str, Any], where: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for key, value in obj.items():
        if not _QUANTILE_RE.match(key) and key not in _SCALAR_OBJECTIVES:
            raise SLOError(
                f"slo: unknown objective {key!r} in {where} (want "
                f"pNN_latency_s / pNN_queue_wait_s / error_rate)"
            )
        if not isinstance(value, (int, float)) or isinstance(
            value, bool
        ) or value < 0:
            raise SLOError(
                f"slo: {where}: {key} must be a non-negative number "
                f"(got {value!r})"
            )
        out[key] = float(value)
    return out


def parse_slo(spec: Union[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Parse an SLO config into ``{"default": {...}, "tenants":
    {...}}``. Accepts the inline ``k=v[,k=v...]`` CLI form, a path to
    a JSON file, or a decoded/inline JSON object (flat = default for
    every tenant, or the full two-level shape)."""
    if isinstance(spec, str):
        text = spec.strip()
        if os.path.exists(text):
            with open(text) as f:
                try:
                    spec = json.load(f)
                except json.JSONDecodeError as e:
                    raise SLOError(f"slo: {text}: not valid JSON: {e}")
        elif text.startswith("{"):
            try:
                spec = json.loads(text)
            except json.JSONDecodeError as e:
                raise SLOError(f"slo: not valid JSON: {e}")
        else:
            obj: Dict[str, Any] = {}
            for part in text.split(","):
                part = part.strip()
                if not part:
                    continue
                key, sep, value = part.partition("=")
                if not sep:
                    raise SLOError(
                        f"slo: expected objective=threshold, got {part!r}"
                    )
                try:
                    obj[key.strip()] = float(value)
                except ValueError:
                    raise SLOError(
                        f"slo: {key.strip()}: threshold {value!r} is "
                        "not a number"
                    )
            spec = obj
    if not isinstance(spec, dict):
        raise SLOError("slo: config must be a JSON object")
    if "default" in spec or "tenants" in spec:
        unknown = set(spec) - {"default", "tenants"}
        if unknown:
            raise SLOError(f"slo: unknown section(s) {sorted(unknown)}")
        default = _check_objectives(spec.get("default") or {}, "default")
        tenants_in = spec.get("tenants") or {}
        if not isinstance(tenants_in, dict):
            raise SLOError("slo: tenants must be an object")
        tenants = {
            str(t): _check_objectives(o or {}, f"tenant {t!r}")
            for t, o in tenants_in.items()
        }
    else:
        default = _check_objectives(spec, "default")
        tenants = {}
    if not default and not any(tenants.values()):
        raise SLOError("slo: config declares no objectives")
    return {"default": default, "tenants": tenants}


def objectives_for(config: Dict[str, Any], tenant: str) -> Dict[str, float]:
    """Effective objectives for one tenant: default, overridden per
    tenant key by key."""
    out = dict(config.get("default") or {})
    out.update((config.get("tenants") or {}).get(tenant) or {})
    return out


def _pct(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    i = min(
        len(sorted_vals) - 1,
        max(0, int(round(q * (len(sorted_vals) - 1)))),
    )
    return sorted_vals[i]


# ---------------------------------------------------------------------
# per-job stage breakdown
# ---------------------------------------------------------------------


def _span_dur(
    spans: List[Dict[str, Any]], name: str
) -> float:
    return sum(
        float(s.get("dur_s") or 0.0) for s in spans
        if s.get("span") == name
    )


def comm_seconds(by_rank: Dict[int, List[Dict[str, Any]]]) -> float:
    """Per-rank mean of sampled collective seconds — the cid->latency
    attribution join's time-side aggregate (the bandwidth side lives
    in ``observability/perf.py``). 0.0 when runtime sampling was off
    (the breakdown then reports the whole run as compute, honestly
    labelled by ``sampled=False``)."""
    if not by_rank:
        return 0.0
    per_rank = []
    for recs in by_rank.values():
        total = sum(
            float(r.get("seconds") or 0.0)
            for r in recs
            if r.get("kind") == "latency"
            and isinstance(r.get("seconds"), (int, float))
            and r["seconds"] >= 0
        )
        per_rank.append(total)
    live = [t for t in per_rank if t > 0]
    return sum(live) / len(live) if live else 0.0


def job_breakdown(
    root: str,
    job_id: str,
    *,
    spans: Optional[List[Dict[str, Any]]] = None,
    trace: Optional[str] = None,
) -> Dict[str, Any]:
    """Decompose one job's wall clock into stage seconds. ``spans``
    may be pre-loaded (one ``span_records()`` read serves many jobs);
    otherwise the spool's audit log is read."""
    from ..observability import spans as _spans

    if spans is None:
        spans = [
            s for s in _spans.load_spans([root])
            if s.get("job") == job_id
        ]
    else:
        spans = [s for s in spans if s.get("job") == job_id]
    if trace is None:
        trace = next(
            (s.get("trace") for s in spans if s.get("trace")), None
        )
    run_s = _span_dur(spans, "run")
    spawn_s = _span_dur(spans, "spawn")
    warm_s = _span_dur(spans, "warm_dispatch")
    reshard_s = _span_dur(spans, "reshard")
    by_rank = _spans.collect_job_records(root, job_id, trace)
    comm_s = min(comm_seconds(by_rank), max(0.0, run_s))
    stages: Dict[str, float] = {
        "queue_wait": _span_dur(spans, "queued"),
        "verify": _span_dur(spans, "verify"),
        "dispatch": _span_dur(spans, "dispatch"),
        "spawn": spawn_s,
        "warm_dispatch": warm_s,
        "reshard": reshard_s,
        "comm": comm_s,
        "compute": max(
            0.0, run_s - spawn_s - warm_s - reshard_s - comm_s
        ),
        "result": _span_dur(spans, "result"),
    }
    total = sum(stages.values())
    out = {
        "job": job_id,
        "trace": trace,
        "stages": {k: round(v, 9) for k, v in stages.items()},
        "total_s": round(total, 9),
        "run_s": round(run_s, 9),
        "sampled": comm_s > 0.0,
        "ranks": sorted(by_rank),
    }
    if any(s.get("coalesced") for s in spans):
        # additive: this job's run/dispatch time was shared with its
        # coalesced batch (serving/dispatch.py), so per-stage seconds
        # attribute the shared world, not an exclusive one
        out["coalesced"] = True
    return out


def dominant_stage(breakdown: Dict[str, Any]) -> Tuple[str, float]:
    """The stage that ate the job, as ``(name, share-of-total)``."""
    stages = breakdown.get("stages") or {}
    total = float(breakdown.get("total_s") or 0.0)
    if not stages or total <= 0:
        return "compute", 0.0
    name = max(stages, key=lambda k: stages[k])
    return name, stages[name] / total


# ---------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------


def evaluate(
    spool: Union[Spool, str],
    config: Dict[str, Any],
    *,
    min_jobs: int = 1,
) -> List[Dict[str, Any]]:
    """Check every tenant's objectives against its finished jobs.
    Returns breaches (worst-job attributed); an objective with fewer
    than ``min_jobs`` finished jobs is not judged."""
    if not isinstance(spool, Spool):
        spool = Spool(spool)
    by_tenant: Dict[str, List[Dict[str, Any]]] = {}
    for rec in spool.done():
        tenant = str(rec.get("tenant") or "default")
        by_tenant.setdefault(tenant, []).append(rec)
    span_recs = spool.span_records()
    breaches: List[Dict[str, Any]] = []
    for tenant in sorted(by_tenant):
        objectives = objectives_for(config, tenant)
        if not objectives:
            continue
        finished = by_tenant[tenant]
        done_ok = [
            r for r in finished if r.get("outcome") == "completed"
        ]
        latencies = sorted(
            float(r.get("queue_wait_s") or 0.0)
            + float(r.get("run_s") or 0.0)
            for r in done_ok
        )
        waits = sorted(
            float(r.get("queue_wait_s") or 0.0) for r in done_ok
        )
        failed = [r for r in finished if r.get("outcome") == "failed"]
        for objective, threshold in sorted(objectives.items()):
            observed: Optional[float] = None
            pool: List[Dict[str, Any]] = done_ok
            if objective == "error_rate":
                if len(finished) >= min_jobs and finished:
                    observed = len(failed) / len(finished)
                pool = failed or finished
            else:
                m = _QUANTILE_RE.match(objective)
                q = int(m.group(1)) / 100.0
                vals = latencies if m.group(2) == "latency" else waits
                if len(vals) >= min_jobs:
                    observed = _pct(vals, q)
            if observed is None or observed <= threshold:
                continue
            worst = max(
                pool,
                key=lambda r: (
                    float(r.get("queue_wait_s") or 0.0)
                    + float(r.get("run_s") or 0.0)
                ),
                default=None,
            ) if pool else None
            breach: Dict[str, Any] = {
                "tenant": tenant,
                "objective": objective,
                "threshold": threshold,
                "observed": round(float(observed), 9),
                "jobs": len(finished),
            }
            if worst is not None:
                bd = job_breakdown(
                    spool.root, str(worst.get("id")),
                    spans=span_recs, trace=worst.get("trace"),
                )
                stage, share = dominant_stage(bd)
                breach.update(
                    job=worst.get("id"),
                    trace=bd.get("trace") or worst.get("trace"),
                    dominant_stage=stage,
                    dominant_share=round(share, 6),
                    stages=bd["stages"],
                )
            breaches.append(breach)
    return breaches


def narrate(breach: Dict[str, Any]) -> str:
    """The operator sentence: name the job, the dominant stage, and
    what it implies."""
    stage = breach.get("dominant_stage") or "?"
    share = breach.get("dominant_share")
    head = (
        f"SLO breach [{breach.get('tenant')}]: "
        f"{breach.get('objective')} = {breach.get('observed'):.3g} "
        f"> {breach.get('threshold'):.3g}"
    )
    if breach.get("job") is None or share is None:
        return head
    label = "queue-wait" if stage == "queue_wait" else stage
    return (
        f"{head} — job {breach['job']}: {share * 100.0:.0f}% {label} "
        f"→ {STAGE_ADVICE.get(stage, stage)}"
    )


# ---------------------------------------------------------------------
# the watch: dedupe + verdict/retune emission
# ---------------------------------------------------------------------


class SLOWatch:
    """Evaluate on demand; emit each breach exactly once.

    The dedupe key is ``(tenant, objective, worst job)``: a breach
    re-observed over the same evidence stays quiet, a *new* worst job
    (the breach moved, or got worse somewhere else) speaks again —
    the streaming doctor's once-per-key convention.
    """

    def __init__(
        self,
        spool: Union[Spool, str],
        config: Dict[str, Any],
        *,
        verdict_log: Optional[str] = None,
        min_jobs: int = 1,
    ):
        self.spool = spool if isinstance(spool, Spool) else Spool(spool)
        self.config = config
        self.min_jobs = int(min_jobs)
        self.verdict_log = verdict_log or os.path.join(
            self.spool.root, SLO_LOG_NAME
        )
        self._seen: set = set()

    def _append(self, record: Dict[str, Any]) -> None:
        from ..observability import events

        try:
            events.EventLog(self.verdict_log).append(record)
        except OSError:
            pass  # the verdict log must never take the queue down

    def _plan_keys(self, breach: Dict[str, Any]) -> List[str]:
        """Plan keys of the breached job's plannable emissions — what
        ``planner tune --from-verdicts`` should sweep."""
        try:
            from .. import config as _config
            from ..observability import spans as _spans
            from ..planner import plan as _plan

            platform = _config.PLATFORM_CLASS or "cpu"
            by_rank = _spans.collect_job_records(
                self.spool.root, str(breach.get("job")),
                breach.get("trace"),
            )
            records = [
                r for recs in by_rank.values() for r in recs
                if r.get("kind") in ("emission", "recorder")
            ]
            return _plan.keys_from_records(records, platform)
        except Exception:
            return []

    def check(self) -> List[Dict[str, Any]]:
        """One evaluation pass; returns (and emits) the new breaches."""
        new: List[Dict[str, Any]] = []
        for breach in evaluate(
            self.spool, self.config, min_jobs=self.min_jobs
        ):
            key = (
                breach["tenant"], breach["objective"],
                breach.get("job"),
            )
            if key in self._seen:
                continue
            self._seen.add(key)
            new.append(breach)
            finding = {"kind": "slo_breach"}
            finding.update(breach)
            # the PR 8 verdict-event shape: stream_doctor appends the
            # same {kind, finding, klass, t} envelope to live.jsonl —
            # SLO breaches are capacity/performance trouble, i.e. the
            # supervisor's *transient* class, never deterministic
            self._append({
                "kind": "verdict",
                "finding": finding,
                "klass": "transient",
                "t": time.time(),
            })
            self.spool.audit(
                "slo_breach",
                tenant=breach["tenant"],
                objective=breach["objective"],
                observed=breach["observed"],
                threshold=breach["threshold"],
                job=breach.get("job"),
                trace=breach.get("trace"),
                dominant_stage=breach.get("dominant_stage"),
            )
            if breach.get("dominant_stage") == "comm":
                plan_keys = self._plan_keys(breach)
                if plan_keys:
                    self._append({
                        "kind": "retune",
                        "reason": "slo_breach",
                        "op": None,
                        "rank": None,
                        "plan_keys": plan_keys,
                        "detail": {
                            "tenant": breach["tenant"],
                            "objective": breach["objective"],
                            "observed": breach["observed"],
                            "threshold": breach["threshold"],
                            "job": breach.get("job"),
                        },
                        "t": time.time(),
                    })
        return new

    @staticmethod
    def narrate(breach: Dict[str, Any]) -> str:
        return narrate(breach)


def load_slo_verdicts(inputs: Iterable[str]) -> List[Dict[str, Any]]:
    """``slo.jsonl`` verdict records found beside the given inputs or
    up to three levels up (the ``load_serving_audit`` discovery walk,
    so the doctor pointed at one job attempt finds the spool's SLO
    trail)."""
    from ..observability import events

    seen: set = set()
    records: List[Dict[str, Any]] = []
    for item in inputs:
        d = item if os.path.isdir(item) else os.path.dirname(item)
        d = os.path.abspath(d)
        cands = [d]
        for _ in range(3):
            cands.append(os.path.dirname(cands[-1]))
        for cand in cands:
            path = os.path.join(cand, SLO_LOG_NAME)
            if path in seen:
                continue
            seen.add(path)
            if not os.path.exists(path):
                continue
            try:
                records.extend(
                    r for r in events.iter_records(path)
                    if r.get("kind") == "verdict"
                    and (r.get("finding") or {}).get("kind")
                    == "slo_breach"
                )
            except OSError:
                continue
    return records


def format_slo_breaches(records: List[Dict[str, Any]]) -> str:
    """The doctor's SLO section: one narration line per breach."""
    lines = [f"SLO breaches ({len(records)} verdict(s)):"]
    for rec in records:
        lines.append("  " + narrate(rec.get("finding") or {}))
    return "\n".join(lines)
