"""Self-healing resident worker pool: warm serving that survives
wedged, crashed, and leaky workers.

PR 10's serving plane spawns a cold world per job, so every job pays
python + jax import and compile latency, and a dead rank costs a
whole-world teardown. This module keeps a pool of **resident
workers** — rank processes spawned once through the launcher's
``rank_env`` seam — that loop on a per-worker filesystem mailbox and
execute job payloads *in-process*: imports stay imported, compile and
plan caches stay warm (``M4T_PLAN_CACHE`` arms once at worker start
and routes every subsequent job), and dispatching a job costs one
fsync'd file rename instead of a world spawn.

A pool that lives for hours is a robustness problem first, so the
core of this module is the **pool doctor**:

- **Heartbeats** — every worker runs the library heartbeat daemon
  (``observability/events.start_heartbeat``) into its own per-worker
  sink (``POOL/events-rank<k>.jsonl``); the controller tails the
  sinks with the live plane's machinery
  (``observability/live.HeartbeatTail`` over ``TailReader`` —
  torn-line and rotation safe, bounded memory). Freshness is arrival
  time, so a respawned worker can never look alive on its dead
  predecessor's heartbeats.
- **Quarantine + respawn** — a worker that exits, misses its
  heartbeat deadline (``wedged`` — the failure shape the ``wedge``
  fault action reproduces deterministically: no emissions, no
  heartbeats, no exit), overruns its job's ``timeout_s``
  (``job_timeout``), or fails the post-job **hygiene check** is
  quarantined (killed, audited) and respawned as a fresh incarnation
  appending to the same sink. In-flight jobs on the worker's
  sub-mesh fail that attempt — their peers are respawned too
  (``peer_lost``: a gang member may be blocked on the dead rank) —
  and retry under their existing per-job
  :class:`~..resilience.supervisor.Supervisor`.
- **Hygiene check** — after every payload the worker proves it left
  no state for the next job to trip over: the telemetry registry is
  reset, leaked point-to-point sends are drained
  (``token.drain_pending_sends`` — a payload that left one is
  reported, not inherited), the fault-plan arming is unscoped
  (``faults.disarm``; a plan the *payload* armed is a violation),
  and the job's environment overlay is rolled back (new ``M4T_*``
  keys a payload exported are named as bleed). An unclean worker
  still returns its job's result — then gets quarantined, because a
  respawn is the only state reset that proves anything.
- **Poisoned jobs (two strikes)** — a job whose attempts *wedge* its
  workers twice (``wedged`` / ``job_timeout`` quarantines) is marked
  **poisoned**: further dispatch is refused and the job fails with
  ``reason: "poisoned"`` on ``serving.jsonl`` (via the supervisor's
  ``abort_fn`` veto), so one bad program degrades to one failed job,
  never to a pool that wedges two workers per retry forever.
- **Elastic capacity loss** — a worker that exits with the
  preemption signature (143 / SIGTERM) under ``elastic=True`` is
  *retired*, not respawned: pool capacity shrinks permanently and
  the in-flight job goes through the PR 9/10 reshard path in
  ``server.py`` (checkpoint resharded to the smaller sub-mesh).

**Sub-mesh packing** — a job asking for ``k`` ranks is dispatched to
``k`` idle workers; the packing is expressed as a
:class:`~..comm.GroupComm` partition of the pool (the job's workers
as one group, everyone else singleton), serialized into the work
item, and rebuilt inside the payload via :func:`job_comm` so job code
can run collectives over exactly its sub-mesh. ``server.py`` gates
each job's ``--verify`` proof at the *sub-mesh* world, and packs
concurrent jobs onto disjoint groups. By default workers are spawned
**un-meshed** (``rank_env(mesh=False)``: rank identity without shm
segment coordinates) so a single worker can be killed and respawned
without wedging segment peers; ``mesh=True`` spawns the pool as one
resident shm world for payloads that need real cross-worker
collectives.

Mailbox protocol (``m4t-work/1``), all writes tmp+fsync+rename (the
``ckpt.py`` idiom — an item/result either exists whole or not at
all)::

    POOL/
      pool.json                    # atomic controller state snapshot
      events-rank<k>.jsonl         # per-worker sink (heartbeats, pool
                                   #   lifecycle, payload emissions)
      worker<k>/
        inbox/<ns>-<item>.json     # work items, FIFO by filename
        current.json               # the claimed item (crash evidence)
        outbox/<item>.json         # results (m4t-result/1)
        STOP                       # drain sentinel: exit the loop

Worker entry point: ``python -m mpi4jax_tpu.serving.pool POOL --rank
K`` (spawned by :class:`WorkerPool`; runnable by hand for debugging).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

WORK_SCHEMA = "m4t-work/1"
RESULT_SCHEMA = "m4t-result/1"
POOL_SCHEMA = "m4t-pool/1"

STATE_NAME = "pool.json"
STOP_SENTINEL = "STOP"
INBOX_DIR = "inbox"
OUTBOX_DIR = "outbox"

#: rank exit signatures that read "preemption honored" (launch.py's)
_PREEMPT_RCS = (143, -signal.SIGTERM)

#: quarantine reasons that count as the job *wedging* its workers —
#: the strikes behind the poisoned-job rule. A plain worker crash
#: (``exited``) is the per-job retry budget's problem; a wedge
#: occupies workers until a deadline names it, which is what must not
#: be allowed to repeat indefinitely.
STRIKE_REASONS = frozenset({"wedged", "job_timeout"})

#: default quarantine policy knobs
DEFAULT_HEARTBEAT_S = 0.5
DEFAULT_MAX_STRIKES = 2

#: mailbox poll interval override (seconds): the profiler's
#: wasted-wakeup findings are actionable without a code edit
POLL_ENV = "M4T_POOL_POLL_S"

#: hardcoded-era defaults, kept as the documented fallbacks
DEFAULT_WORKER_POLL_S = 0.02
DEFAULT_CONTROLLER_POLL_S = 0.01


def resolve_poll_s(poll_s: Optional[float], fallback: float) -> float:
    """The mailbox poll interval: an explicit value wins, else
    ``M4T_POOL_POLL_S`` (read at call time, so a harness can set it
    after import), else ``fallback``. Explicit non-positive values are
    an error; a malformed or non-positive env value warns and falls
    back rather than wedging the pool (the ``config.py`` contract)."""
    if poll_s is not None:
        value = float(poll_s)
        if value <= 0.0:
            raise ValueError("poll interval must be > 0")
        return value
    raw = os.environ.get(POLL_ENV, "")
    if raw:
        try:
            value = float(raw)
        except ValueError:
            value = -1.0
        if value > 0.0:
            return value
        sys.stderr.write(
            f"m4t.pool: ignoring invalid {POLL_ENV}={raw!r} "
            f"(want a positive float); using {fallback}\n"
        )
    return fallback


def _write_json_atomic(path: str, obj: Any) -> str:
    """The spool/ckpt idiom: whole file or no file."""
    tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------
# worker side: the resident loop
# ---------------------------------------------------------------------


def worker_dir(root: str, rank: int) -> str:
    return os.path.join(os.fspath(root), f"worker{rank}")


def worker_sink(root: str, rank: int) -> str:
    return os.path.join(os.fspath(root), f"events-rank{rank}.jsonl")


def job_comm():
    """The :class:`~..comm.GroupComm` for the current work item's
    sub-mesh, or None outside a pool job. Payload helper: the job's
    workers form one group (the payload's collectives stay inside its
    sub-mesh), every other pool rank is a singleton."""
    raw = os.environ.get("M4T_POOL_GROUP", "")
    if not raw:
        return None
    info = json.loads(raw)
    ranks = [int(r) for r in info.get("ranks", [])]
    world = int(info.get("world", len(ranks)))
    members = set(ranks)
    groups = (tuple(ranks),) + tuple(
        (r,) for r in range(world) if r not in members
    )
    from ..comm import GroupComm

    return GroupComm(groups)


def job_group_rank() -> Optional[int]:
    """This worker's rank *within its job's sub-mesh* (None outside a
    pool job)."""
    raw = os.environ.get("M4T_POOL_GROUP", "")
    if not raw:
        return None
    return int(json.loads(raw).get("rank", 0))


def _exec_payload(item: Dict[str, Any]) -> None:
    """Run the job payload in-process — the whole point of the warm
    pool: ``sys.modules`` (jax included) and every compile cache the
    process accumulated stay hot across jobs."""
    import runpy

    module = item.get("module")
    cmd = list(item.get("cmd") or [])
    if module:
        sys.argv = [module] + cmd
        runpy.run_module(module, run_name="__main__", alter_sys=True)
        return
    if not cmd:
        raise ValueError("work item has neither 'module' nor 'cmd'")
    if cmd[0] == "-c":
        code = cmd[1] if len(cmd) > 1 else ""
        sys.argv = ["-c"] + cmd[2:]
        exec(compile(code, "<m4t-work-item>", "exec"),
             {"__name__": "__main__"})
        return
    sys.argv = list(cmd)
    runpy.run_path(cmd[0], run_name="__main__")


def hygiene_sweep(
    saved_env: Dict[str, str],
    *,
    had_plan: bool = False,
    applied_keys: Optional[set] = None,
) -> Dict[str, Any]:
    """The post-job state-bleed check, and the cleanup it verifies.

    Contract (``docs/serving.md``): after a payload returns, the
    worker must look like it never ran it — telemetry registry reset,
    no pending point-to-point sends, no fault plan armed, no new
    ``M4T_*`` environment. Each violation is *repaired* (drained /
    disarmed / rolled back) **and reported**: repair protects the next
    job if the controller is gone, the report gets this worker
    quarantined so the repair is never silently trusted.
    """
    report: Dict[str, Any] = {"clean": True}
    applied = applied_keys or set()

    # leaked point-to-point sends: a payload that traced a send with
    # no matching recv left poison for the next trace
    try:
        from .. import token

        leaks = token.drain_pending_sends()
        n = sum(len(rs) for _, rs in leaks)
        report["pending_sends"] = n
        if n:
            report["clean"] = False
    except Exception:
        report["pending_sends"] = None

    # fault-plan arming must not outlive the job that declared it;
    # a plan the *payload* armed itself is a violation either way
    try:
        from ..resilience import faults

        armed = faults.active_plan is not None
        faults.disarm()
        report["fault_armed"] = bool(armed and not had_plan)
        if report["fault_armed"]:
            report["clean"] = False
    except Exception:
        report["fault_armed"] = None

    # roll back the job's environment overlay; any *other* M4T_ key
    # the payload exported is named as bleed
    bleed = sorted(
        k for k in os.environ
        if k not in saved_env and k.startswith("M4T_")
        and k not in applied
    )
    os.environ.clear()
    os.environ.update(saved_env)
    report["env_bleed"] = bleed
    if bleed:
        report["clean"] = False

    # per-job telemetry counters: the next job starts at zero
    try:
        from ..observability import metrics

        metrics.reset()
        report["metrics_reset"] = True
    except Exception:
        report["metrics_reset"] = False
        report["clean"] = False
    return report


def run_item(
    item: Dict[str, Any], *, worker: int = 0, incarnation: int = 0
) -> Dict[str, Any]:
    """Execute one work item and return its ``m4t-result/1`` record
    (rc + error + hygiene report). Never raises: the worker loop must
    survive any payload."""
    t0 = time.monotonic()
    saved_env = dict(os.environ)
    saved_argv = list(sys.argv)
    group = item.get("group") or {}
    overlay: Dict[str, str] = {
        str(k): str(v) for k, v in (item.get("env") or {}).items()
    }
    if item.get("job"):
        overlay["M4T_JOB_ID"] = str(item["job"])
    if item.get("trace"):
        # the job's distributed trace id: every emission the payload
        # makes in this warm process is stamped with it (ops/_core.py),
        # which is what attributes this worker's shared sink records
        # to the submitting job
        overlay["M4T_TRACE_ID"] = str(item["trace"])
    if group:
        overlay["M4T_POOL_GROUP"] = json.dumps(group)
    if item.get("resume_step") is not None:
        overlay["M4T_RESUME_STEP"] = str(item["resume_step"])
    os.environ.update(overlay)

    rc, err = 0, None
    plan_spec = item.get("fault_plan")
    had_plan = plan_spec is not None
    if had_plan:
        try:
            from ..resilience import faults

            plan = (
                faults.FaultPlan.load(plan_spec)
                if isinstance(plan_spec, str)
                else faults.FaultPlan.parse(plan_spec)
            )
            faults.arm(
                plan,
                rank=int(group.get("rank", 0)),
                attempt=int(item.get("attempt", 0)),
            )
        except Exception as exc:
            rc, err = 2, f"fault plan failed to arm: {exc!r}"
    if rc == 0:
        try:
            _exec_payload(item)
        except SystemExit as exc:
            code = exc.code
            if code in (None, 0):
                rc = 0
            else:
                rc = code if isinstance(code, int) else 1
                err = f"SystemExit({code!r})"
        except BaseException as exc:  # noqa: BLE001 — worker survives all
            rc, err = 1, repr(exc)
    hygiene = hygiene_sweep(
        saved_env, had_plan=had_plan, applied_keys=set(overlay)
    )
    sys.argv = saved_argv
    return {
        "schema": RESULT_SCHEMA,
        "item": item.get("item"),
        "job": item.get("job"),
        "attempt": item.get("attempt", 0),
        "rc": rc,
        "error": err,
        "elapsed_s": round(time.monotonic() - t0, 6),
        "hygiene": hygiene,
        "worker": worker,
        "incarnation": incarnation,
    }


def _oldest_entry(inbox: str) -> Optional[str]:
    try:
        names = [
            n for n in os.listdir(inbox)
            if n.endswith(".json") and not n.startswith(".tmp-")
        ]
    except OSError:
        return None
    return min(names) if names else None


def worker_loop(
    root: str,
    rank: int,
    *,
    incarnation: int = 0,
    heartbeat_s: float = DEFAULT_HEARTBEAT_S,
    poll_s: Optional[float] = None,
) -> int:
    """The resident loop one pool worker runs until its STOP sentinel
    appears: heartbeat, claim the oldest inbox item, execute it
    in-process, write the result, sweep hygiene, repeat.

    ``poll_s`` defaults from ``M4T_POOL_POLL_S`` (else
    ``DEFAULT_WORKER_POLL_S``); see :func:`resolve_poll_s`."""
    from ..observability import events
    from . import profile as _profile

    poll_s = resolve_poll_s(poll_s, DEFAULT_WORKER_POLL_S)
    # workers are separate processes: each arms from the inherited
    # env and sinks to its own <pool_root>/cp_profile.jsonl
    _profile.arm_from_env(root)
    wdir = worker_dir(root, rank)
    inbox = os.path.join(wdir, INBOX_DIR)
    outbox = os.path.join(wdir, OUTBOX_DIR)
    for d in (inbox, outbox):
        os.makedirs(d, exist_ok=True)
    stop_path = os.path.join(wdir, STOP_SENTINEL)
    current = os.path.join(wdir, "current.json")

    # event-driven dispatch (PR 20): when the serve loop runs its
    # fastpath it exports M4T_DISPATCH_FASTPATH, and the worker arms a
    # wake wire on its inbox so the controller's item fan-out lands in
    # microseconds instead of a poll_s nap. The retained bounded wait
    # below is the lost-wakeup recovery; unset env means the classic
    # sleep, byte-for-byte.
    wake = None
    _fast = os.environ.get("M4T_DISPATCH_FASTPATH")
    if _fast:
        try:
            from . import dispatch as _dispatch

            wake = _dispatch.open_listener(
                inbox, advertise_dir=wdir,
                prefer=_fast if _fast in (
                    _dispatch.WIRE_INOTIFY, _dispatch.WIRE_SOCKET,
                    _dispatch.WIRE_POLL,
                ) else None,
            )
        except Exception:
            wake = None

    # the library heartbeat daemon into this worker's sink — the pool
    # doctor's liveness signal. Restarted after every job because a
    # payload may have replaced it (start_heartbeat is idempotent) or
    # silenced it (the wedge shape never returns here anyway).
    events.start_heartbeat(heartbeat_s, source="pool-worker")
    events.emit(events.event(
        "pool", event="worker_start", worker=rank,
        incarnation=incarnation, pid=os.getpid(), t=time.time(),
    ))
    served = 0
    while True:
        if os.path.exists(stop_path):
            events.emit(events.event(
                "pool", event="worker_stop", worker=rank,
                incarnation=incarnation, jobs=served, t=time.time(),
            ))
            if wake is not None:
                try:
                    wake.close()
                except Exception:
                    pass
            return 0
        prof = _profile.active
        t_poll = prof.t() if prof is not None else 0.0
        name = _oldest_entry(inbox)
        if name is None:
            if prof is not None:
                # a wasted wakeup: one listdir bought nothing
                prof.phase(
                    "pool.wakeup", t_poll, worker=rank, useful=False,
                )
            if wake is not None:
                wake.wait(poll_s)
            else:
                time.sleep(poll_s)
            continue
        try:
            os.replace(os.path.join(inbox, name), current)
        except OSError:
            continue  # swept by a respawn mid-claim
        try:
            with open(current) as f:
                item = json.load(f)
        except (OSError, json.JSONDecodeError):
            item = None
        if not isinstance(item, dict) or item.get("schema") != WORK_SCHEMA:
            try:
                os.unlink(current)
            except OSError:
                pass
            continue
        if prof is not None:
            prof.phase(
                "pool.wakeup", t_poll, worker=rank, useful=True,
            )
            # mailbox-write -> worker-claim lag, measured from the
            # item name's time_ns prefix (_write_item's stamp): the
            # worker_pickup leg of the dispatch hand-off
            try:
                lag = max(
                    0.0,
                    _profile.wall() - int(name.split("-", 1)[0]) / 1e9,
                )
            except (ValueError, IndexError):
                lag = 0.0
            prof.phase(
                "pool.pickup", dur_s=lag, worker=rank,
                job=item.get("job"), item=item.get("item"),
            )
        events.emit(events.event(
            "pool", event="job_start", worker=rank,
            job=item.get("job"), item=item.get("item"),
            attempt=item.get("attempt", 0), t=time.time(),
        ))
        # while the payload runs, heartbeats name the job occupying
        # this worker: a staleness verdict (HeartbeatTail deadline,
        # `wedged`/`job_timeout` quarantine) is then attributable to
        # the job that wedged the slot, not just the slot — the
        # evidence trail behind the two-strikes poisoning rule
        busy_fields: Dict[str, Any] = {}
        if item.get("job"):
            busy_fields["job"] = item["job"]
        if item.get("trace"):
            busy_fields["trace"] = item["trace"]
        events.start_heartbeat(
            heartbeat_s, source="pool-worker", **busy_fields
        )
        result = run_item(item, worker=rank, incarnation=incarnation)
        served += 1
        _write_json_atomic(
            os.path.join(outbox, f"{item.get('item')}.json"), result
        )
        try:
            os.unlink(current)
        except OSError:
            pass
        events.emit(events.event(
            "pool", event="job_done", worker=rank, job=item.get("job"),
            item=item.get("item"), rc=result["rc"],
            clean=result["hygiene"].get("clean"),
            elapsed_s=result["elapsed_s"], t=time.time(),
        ))
        events.start_heartbeat(heartbeat_s, source="pool-worker")


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.serving.pool",
        description="resident pool worker (spawned by WorkerPool)",
    )
    parser.add_argument("root")
    parser.add_argument("--rank", type=int, required=True)
    parser.add_argument("--incarnation", type=int, default=0)
    parser.add_argument("--heartbeat", type=float,
                        default=DEFAULT_HEARTBEAT_S)
    parser.add_argument(
        "--poll", "--poll-interval", type=float, default=None,
        metavar="S", dest="poll",
        help="mailbox poll interval in seconds (default: "
        f"${POLL_ENV} else {DEFAULT_WORKER_POLL_S})",
    )
    args = parser.parse_args(argv)

    # the warm import: everything a payload needs is resident before
    # the first work item arrives (and the shm world is joined here
    # when the pool was spawned meshed)
    import mpi4jax_tpu  # noqa: F401

    return worker_loop(
        args.root, args.rank,
        incarnation=args.incarnation,
        heartbeat_s=args.heartbeat,
        poll_s=args.poll,
    )


# ---------------------------------------------------------------------
# controller side: spawn, dispatch, doctor
# ---------------------------------------------------------------------


@dataclass
class PoolWorker:
    """Controller-side view of one worker slot."""

    rank: int
    state: str = "starting"  # starting|idle|busy|quarantined|retired
    handle: Any = None
    incarnation: int = 0
    jobs_served: int = 0
    quarantines: int = 0
    job: Optional[str] = None
    item: Optional[str] = None
    group_rank: Optional[int] = None
    spawned_t: float = 0.0
    last_rc: Optional[int] = None


class _Dispatch:
    """In-flight gang state for one job attempt."""

    def __init__(self, job: str, attempt: int, workers: List[PoolWorker]):
        self.job = job
        self.attempt = attempt
        self.workers = list(workers)
        self.results: Dict[int, Dict[str, Any]] = {}  # group rank ->
        self.failed: Optional[str] = None
        self.failed_rc: Optional[int] = None
        self.preempted: List[int] = []  # group ranks
        self.struck = False

    def group_index(self, pool_rank: int) -> int:
        for i, w in enumerate(self.workers):
            if w.rank == pool_rank:
                return i
        return -1


class WorkerPool:
    """Spawn, feed, watch, and heal a set of resident workers.

    ``spawn_fn(pool, worker) -> handle`` is the injectable seam that
    makes the whole controller device-free-testable (the selftest and
    most tests drive it with stubs and never fork a worker); a handle
    needs ``poll() -> rc|None``, ``terminate()``, ``kill()`` and may
    carry ``pid``. The default spawns ``python -m
    mpi4jax_tpu.serving.pool`` with an environment built by
    ``launch.rank_env`` — the same seam every other world in this
    repo is spawned through.
    """

    def __init__(
        self,
        root: str,
        size: int,
        *,
        spawn_fn: Optional[Callable[["WorkerPool", PoolWorker], Any]] = None,
        heartbeat_s: float = DEFAULT_HEARTBEAT_S,
        deadline_s: Optional[float] = None,
        start_deadline_s: Optional[float] = None,
        check_s: float = 0.05,
        poll_s: Optional[float] = None,
        acquire_timeout_s: float = 60.0,
        mesh: bool = False,
        plan_cache: Optional[str] = None,
        elastic: bool = False,
        max_strikes: int = DEFAULT_MAX_STRIKES,
        audit: Optional[Callable[..., None]] = None,
        span: Optional[Callable[..., None]] = None,
        log: Optional[Callable[[str], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if size < 1:
            raise ValueError("pool needs size >= 1")
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.size = int(size)
        self.heartbeat_s = float(heartbeat_s)
        #: quarantine deadline: this long without a *fresh* heartbeat
        #: means wedged (several missed beats, never a close call)
        self.deadline_s = (
            float(deadline_s) if deadline_s is not None
            else max(6.0 * self.heartbeat_s, 3.0)
        )
        #: a starting worker pays a cold import before its first beat
        self.start_deadline_s = (
            float(start_deadline_s) if start_deadline_s is not None
            else max(self.deadline_s, 30.0)
        )
        self.check_s = float(check_s)
        #: the poll interval spawned workers are told to use
        #: (explicit > $M4T_POOL_POLL_S > DEFAULT_CONTROLLER_POLL_S)
        self.poll_s = resolve_poll_s(poll_s, DEFAULT_CONTROLLER_POLL_S)
        self.acquire_timeout_s = float(acquire_timeout_s)
        self.mesh = bool(mesh)
        self.plan_cache = plan_cache
        self.elastic = bool(elastic)
        self.max_strikes = int(max_strikes)
        self._audit_fn = audit
        #: ``span(name, job=, t0=, t1=, trace=, **fields)`` — the
        #: Spool.span seam: the runner records one ``warm_dispatch``
        #: lifecycle span per attempt (mailbox hand-off latency, the
        #: warm analog of the cold path's ``spawn`` span)
        self._span_fn = span
        #: ``strike_fn(job, reason) -> cumulative strikes`` — the
        #: Spool.record_strike seam (wired by the federated Server):
        #: strikes persist on the spool, so a job that wedged server
        #: A's workers carries its record to server B
        self._strike_fn: Optional[Callable[[str, str], int]] = None
        #: ``poisoned_fn(job) -> bool`` — the Spool.poisoned seam:
        #: consult the spool-wide verdict alongside local state
        self._poisoned_fn: Optional[Callable[[str], bool]] = None
        self._log = log or (lambda msg: sys.stderr.write(
            f"m4t.pool: {msg}\n"
        ))
        self.clock = clock
        self._spawn_fn = spawn_fn or WorkerPool._default_spawn
        self.workers = [PoolWorker(rank=r) for r in range(self.size)]
        self.counters: Dict[str, Any] = {
            "quarantines": {}, "respawns": 0, "retired": 0,
            "dispatched": 0, "poisoned": 0,
        }
        self._strikes: Dict[str, int] = {}
        self._poisoned: set = set()
        self._dispatches: Dict[str, _Dispatch] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dirty = True
        from ..observability import live as _live

        self._tails = {
            w.rank: _live.HeartbeatTail(
                worker_sink(self.root, w.rank), clock=clock
            )
            for w in self.workers
        }
        import random
        import uuid

        self._shm_name = f"/m4t_pool_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        self._shm_gen = random.getrandbits(32) | 1

    # -- audit / state -------------------------------------------------

    def _audit(self, event: str, **fields: Any) -> None:
        if self._audit_fn is not None:
            try:
                self._audit_fn(event, **fields)
            except Exception:
                pass

    def _write_state(self, force: bool = False) -> None:
        with self._lock:
            if not (self._dirty or force):
                return
            self._dirty = False
            state = {
                "schema": POOL_SCHEMA,
                "t": time.time(),
                "size": self.size,
                "capacity": self.capacity(),
                "mesh": self.mesh,
                "heartbeat_s": self.heartbeat_s,
                "deadline_s": self.deadline_s,
                "counters": {
                    "quarantines": dict(self.counters["quarantines"]),
                    "respawns": self.counters["respawns"],
                    "retired": self.counters["retired"],
                    "dispatched": self.counters["dispatched"],
                    "poisoned": self.counters["poisoned"],
                },
                "poisoned_jobs": sorted(self._poisoned),
                "workers": [
                    {
                        "rank": w.rank,
                        "state": w.state,
                        "incarnation": w.incarnation,
                        "jobs_served": w.jobs_served,
                        "quarantines": w.quarantines,
                        "job": w.job,
                        "pid": getattr(w.handle, "pid", None),
                        "last_rc": w.last_rc,
                    }
                    for w in self.workers
                ],
            }
        try:
            _write_json_atomic(
                os.path.join(self.root, STATE_NAME), state
            )
        except OSError:
            pass  # state snapshots must never take the pool down

    # -- spawning ------------------------------------------------------

    @staticmethod
    def _default_spawn(pool: "WorkerPool", worker: PoolWorker):
        from .. import launch

        env = launch.rank_env(
            worker.rank, pool.size,
            shm_name=pool._shm_name,
            shm_gen=pool._shm_gen,
            events_dir=pool.root,
            heartbeat=pool.heartbeat_s,
            plan_cache=pool.plan_cache,
            mesh=pool.mesh,
            # a resident sink must not grow without bound; the tailers
            # are rotation-transparent
            extra_env={"M4T_TELEMETRY_MAX_MB": "8"},
        )
        cmd = [
            sys.executable, "-m", "mpi4jax_tpu.serving.pool",
            pool.root,
            "--rank", str(worker.rank),
            "--incarnation", str(worker.incarnation),
            "--heartbeat", str(pool.heartbeat_s),
            "--poll", str(pool.poll_s),
        ]
        return subprocess.Popen(cmd, env=env)

    def _clean_mailbox(self, worker: PoolWorker) -> None:
        wdir = worker_dir(self.root, worker.rank)
        for sub in (INBOX_DIR, OUTBOX_DIR):
            d = os.path.join(wdir, sub)
            os.makedirs(d, exist_ok=True)
            try:
                names = os.listdir(d)
            except OSError:
                names = []
            for name in names:
                try:
                    os.unlink(os.path.join(d, name))
                except OSError:
                    pass
        for name in (STOP_SENTINEL, "current.json"):
            try:
                os.unlink(os.path.join(wdir, name))
            except OSError:
                pass

    def _spawn(self, worker: PoolWorker) -> None:
        with self._lock:
            worker.incarnation += 1
            self._clean_mailbox(worker)
            worker.state = "starting"
            worker.job = None
            worker.item = None
            worker.group_rank = None
            worker.spawned_t = self.clock()
            worker.handle = self._spawn_fn(self, worker)
            self._dirty = True

    def start(self, *, doctor: bool = True) -> "WorkerPool":
        """Spawn every worker; with ``doctor=True`` also start the
        health-check thread (tests drive :meth:`check` by hand)."""
        self._audit(
            "pool_start", size=self.size, mesh=self.mesh,
            heartbeat_s=self.heartbeat_s, deadline_s=self.deadline_s,
            elastic=self.elastic,
        )
        self._log(
            f"starting {self.size} resident worker(s) in {self.root}"
            + (" (meshed)" if self.mesh else "")
        )
        for w in self.workers:
            self._spawn(w)
        if doctor:
            self._thread = threading.Thread(
                target=self._doctor_loop, name="m4t-pool-doctor",
                daemon=True,
            )
            self._thread.start()
        self._write_state(force=True)
        return self

    @staticmethod
    def _end_handle(handle: Any) -> None:
        for meth in ("terminate", "kill"):
            try:
                getattr(handle, meth)()
            except Exception:
                pass
        try:
            handle.wait(timeout=5.0)
        except Exception:
            pass

    def stop(self, *, grace_s: float = 5.0) -> None:
        """Drain the pool: STOP sentinels, a grace window for clean
        exits, then terminate/kill stragglers."""
        self._stop.set()
        with self._lock:
            workers = list(self.workers)
        for w in workers:
            try:
                with open(os.path.join(
                    worker_dir(self.root, w.rank), STOP_SENTINEL
                ), "w") as f:
                    f.write("pool stop\n")
            except OSError:
                pass
        deadline = self.clock() + grace_s
        while self.clock() < deadline:
            if all(
                w.handle is None or w.handle.poll() is not None
                for w in workers
            ):
                break
            time.sleep(0.02)
        for w in workers:
            if w.handle is not None and w.handle.poll() is None:
                self._end_handle(w.handle)
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self._audit(
            "pool_stop",
            jobs=sum(w.jobs_served for w in workers),
            respawns=self.counters["respawns"],
        )
        self._dirty = True
        self._write_state(force=True)

    # -- health --------------------------------------------------------

    def capacity(self) -> int:
        """Worker slots not permanently retired by preemption."""
        return sum(1 for w in self.workers if w.state != "retired")

    def idle_count(self) -> int:
        with self._lock:
            return sum(1 for w in self.workers if w.state == "idle")

    def poisoned(self, job_id: str) -> bool:
        with self._lock:
            if job_id in self._poisoned:
                return True
        if self._poisoned_fn is not None:
            try:
                return bool(self._poisoned_fn(job_id))
            except Exception:
                return False
        return False

    def strikes(self, job_id: str) -> int:
        with self._lock:
            return self._strikes.get(job_id, 0)

    def _doctor_loop(self) -> None:
        while not self._stop.wait(self.check_s):
            try:
                self.check()
            except Exception as exc:  # pragma: no cover — must not die
                self._log(f"doctor check failed: {exc!r}")
        self._write_state()

    def check(self) -> None:
        """One pool-doctor pass: reap exits, enforce heartbeat
        deadlines, flip started workers to idle. Called continuously
        by the doctor thread and by every in-flight dispatch wait (so
        single-threaded tests are deterministic)."""
        with self._lock:
            for tail in self._tails.values():
                tail.poll()
            now = self.clock()
            for w in self.workers:
                if w.state in ("quarantined", "retired"):
                    continue
                if w.handle is None:
                    continue
                try:
                    rc = w.handle.poll()
                except Exception:
                    rc = None
                if rc is not None:
                    w.last_rc = rc
                    if rc == 0 and self._stop.is_set():
                        continue  # clean drain exit
                    if self.elastic and rc in _PREEMPT_RCS:
                        self._retire(w, rc)
                    else:
                        self._quarantine(w, "exited", rc=rc)
                    continue
                tail = self._tails[w.rank]
                beat = tail.last_heartbeat_t
                fresh = beat is not None and beat >= w.spawned_t
                if w.state == "starting":
                    if fresh:
                        w.state = "idle"
                        self._dirty = True
                        self._log(
                            f"worker {w.rank} ready (incarnation "
                            f"{w.incarnation})"
                        )
                    elif now - w.spawned_t > self.start_deadline_s:
                        self._quarantine(w, "start_timeout")
                    continue
                ref = beat if fresh else w.spawned_t
                if now - ref > self.deadline_s:
                    self._quarantine(w, "wedged")
        self._write_state()

    def _retire(self, worker: PoolWorker, rc: int) -> None:
        """Preemption under ``elastic``: the slot is capacity lost,
        not a bug — never respawned. The in-flight job's attempt
        fails with the preempted group rank on record so the server's
        reshard path can shrink it."""
        worker.quarantines += 1
        self.counters["retired"] += 1
        self._dirty = True
        job = worker.job
        self._audit(
            "pool_retired", worker=worker.rank, rc=rc, job=job,
            incarnation=worker.incarnation, capacity=self.capacity() - 1,
        )
        self._log(
            f"worker {worker.rank} preempted (rc {rc}); retiring the "
            f"slot — pool capacity {self.capacity() - 1}"
        )
        worker.state = "retired"
        worker.handle = None
        if job:
            self._fail_dispatch(job, "preempted", worker, rc=rc)

    def _quarantine(
        self, worker: PoolWorker, reason: str, rc: Optional[int] = None
    ) -> None:
        """Kill + audit + respawn one worker; fail its in-flight
        dispatch (and respawn the gang peers the dead rank may have
        wedged)."""
        worker.quarantines += 1
        q = self.counters["quarantines"]
        q[reason] = q.get(reason, 0) + 1
        self._dirty = True
        job = worker.job
        self._audit(
            "pool_quarantine", worker=worker.rank, reason=reason,
            rc=rc, job=job, incarnation=worker.incarnation,
        )
        self._log(
            f"worker {worker.rank} quarantined ({reason}"
            + (f", rc {rc}" if rc is not None else "")
            + (f", job {job}" if job else "") + ")"
        )
        if worker.handle is not None:
            self._end_handle(worker.handle)
            worker.handle = None
        worker.state = "quarantined"
        if job:
            self._fail_dispatch(job, reason, worker, rc=rc)
        if not self._stop.is_set():
            self._spawn(worker)
            self.counters["respawns"] += 1
            self._audit(
                "pool_respawn", worker=worker.rank,
                incarnation=worker.incarnation,
            )

    def _fail_dispatch(
        self,
        job: str,
        reason: str,
        worker: PoolWorker,
        rc: Optional[int] = None,
    ) -> None:
        d = self._dispatches.get(job)
        if d is None:
            return
        idx = d.group_index(worker.rank)
        if rc is not None and rc in _PREEMPT_RCS and idx >= 0:
            if idx not in d.preempted:
                d.preempted.append(idx)
        already_failing = d.failed is not None
        if not already_failing:
            d.failed = reason
            d.failed_rc = rc
        if reason in STRIKE_REASONS and not d.struck:
            # one strike per attempt, however many workers it wedged
            d.struck = True
            n = self._strikes.get(job, 0) + 1
            if self._strike_fn is not None:
                # the spool's persistent count wins when higher: a
                # peer server may already have struck this job
                try:
                    n = max(n, int(self._strike_fn(job, reason)))
                except Exception:
                    pass
            self._strikes[job] = n
            self._audit(
                "pool_strike", job=job, strikes=n,
                max_strikes=self.max_strikes, reason=reason,
            )
            if n >= self.max_strikes and job not in self._poisoned:
                self._poisoned.add(job)
                self.counters["poisoned"] += 1
                self._audit(
                    "pool_poisoned", job=job, strikes=n,
                    reason=reason,
                )
                self._log(
                    f"job {job} poisoned after {n} wedged attempt(s); "
                    "further dispatch refused"
                )
        if not already_failing:
            # a gang member may be blocked on the lost rank forever;
            # fresh incarnations are the only safe retry substrate
            for peer in list(d.workers):
                if peer is worker:
                    continue
                if peer.state == "busy" and peer.job == job:
                    self._quarantine(peer, "peer_lost")

    # -- dispatch ------------------------------------------------------

    def _acquire(
        self, world: int, job: str
    ) -> Optional[List[PoolWorker]]:
        deadline = self.clock() + self.acquire_timeout_s
        while True:
            with self._lock:
                if job in self._poisoned:
                    return None
                if self.capacity() < world:
                    self._audit(
                        "pool_refused", job=job, reason="capacity",
                        capacity=self.capacity(), world=world,
                    )
                    return None
                idle = [w for w in self.workers if w.state == "idle"]
                if len(idle) >= world:
                    chosen = idle[:world]
                    for i, w in enumerate(chosen):
                        w.state = "busy"
                        w.job = job
                        w.group_rank = i
                    self._dirty = True
                    return chosen
            if self.clock() > deadline:
                self._audit(
                    "pool_refused", job=job, reason="busy_timeout",
                    world=world,
                )
                return None
            self.check()
            time.sleep(self.check_s)

    def _write_item(
        self, worker: PoolWorker, item: Dict[str, Any]
    ) -> None:
        inbox = os.path.join(
            worker_dir(self.root, worker.rank), INBOX_DIR
        )
        os.makedirs(inbox, exist_ok=True)
        name = f"{time.time_ns():020d}-{item['item']}.json"
        _write_json_atomic(os.path.join(inbox, name), item)

    def _timeout_job(self, job: str) -> None:
        with self._lock:
            d = self._dispatches.get(job)
            if d is None or d.failed is not None:
                return
            busy = [
                w for w in d.workers
                if w.state == "busy" and w.job == job
            ]
            for w in busy:
                self._quarantine(w, "job_timeout")

    def runner(
        self,
        spec: Any,
        world: int,
        events_dir: Optional[str],
        attempt: int,
        resume_step: Optional[int],
    ) -> Any:
        """The serving plane's ``Runner`` contract, warm: dispatch
        ``spec`` to ``world`` idle workers as work items and wait for
        the gang's results (or for the doctor to fail the attempt).
        Returns ``(exit_code, preempted_group_ranks)`` exactly like
        ``launch.spawn_world``."""
        job = str(spec.id)
        trace = getattr(spec, "trace", None)
        dispatch_t0 = time.time()
        if self.poisoned(job):
            self._audit("pool_refused", job=job, reason="poisoned")
            self._log(f"job {job}: dispatch refused (poisoned)")
            return 1, []
        workers = self._acquire(int(world), job)
        if workers is None:
            return 1, []
        d = _Dispatch(job, attempt, workers)
        with self._lock:
            self._dispatches[job] = d
            self.counters["dispatched"] += 1
            self._dirty = True
        ranks = [w.rank for w in workers]
        # the sub-mesh this job packs onto, validated as a real
        # GroupComm partition of the pool (job group + singletons)
        from ..comm import GroupComm

        members = set(ranks)
        GroupComm(
            (tuple(ranks),) + tuple(
                (r,) for r in range(self.size) if r not in members
            )
        )
        self._audit(
            "pool_dispatch", job=job, attempt=attempt, world=world,
            workers=ranks,
        )
        from . import profile as _profile

        prof = _profile.active
        t_deliver = prof.t() if prof is not None else 0.0
        for i, w in enumerate(workers):
            item_id = f"{job}.a{attempt:02d}.g{i:02d}"
            w.item = item_id
            self._write_item(w, {
                "schema": WORK_SCHEMA,
                "item": item_id,
                "job": job,
                "trace": trace,
                "attempt": attempt,
                "cmd": list(spec.cmd) if spec.cmd else None,
                "module": spec.module,
                "env": dict(spec.env) if spec.env else None,
                "fault_plan": spec.fault_plan,
                "resume_step": resume_step,
                "events_dir": events_dir,
                "timeout_s": spec.timeout_s,
                "group": {
                    "ranks": ranks, "rank": i, "size": len(ranks),
                    "world": self.size,
                },
            })
        if prof is not None:
            # the item fan-out: the mailbox_delivery leg of the warm
            # dispatch hand-off (tmp+fsync+rename per gang member)
            prof.phase(
                "pool.deliver", t_deliver, job=job,
                items=len(workers),
            )
        if os.environ.get("M4T_DISPATCH_FASTPATH"):
            # event-driven dispatch: wake each gang member's mailbox
            # listener — one datagram (or a free inotify event) beats
            # a poll_s nap of pickup latency. Best-effort: a missed
            # wake only costs the worker its retained bounded wait.
            from . import dispatch as _dispatch

            for w in workers:
                _dispatch.notify(
                    worker_dir(self.root, w.rank), job=job
                )
        if self._span_fn is not None:
            # acquire + item fan-out: the warm path's whole dispatch
            # cost — the number the cold path's `spawn` span is
            # measured against
            try:
                self._span_fn(
                    "warm_dispatch", job=job, t0=dispatch_t0,
                    t1=time.time(), trace=trace, attempt=attempt,
                    world=int(world), workers=ranks,
                )
            except Exception:
                pass
        timeout = float(getattr(spec, "timeout_s", 0.0) or 0.0)
        deadline = self.clock() + timeout if timeout > 0 else None
        rc: Optional[int] = None
        try:
            while rc is None:
                self.check()
                # collect results; release each worker as its slice
                # lands (hygiene-checked on the way out)
                with self._lock:
                    pending = [
                        w for w in d.workers
                        if w.group_rank is not None
                        and w.group_rank not in d.results
                        and w.state == "busy" and w.job == job
                    ]
                for w in pending:
                    path = os.path.join(
                        worker_dir(self.root, w.rank), OUTBOX_DIR,
                        f"{w.item}.json",
                    )
                    try:
                        with open(path) as f:
                            result = json.load(f)
                    except (OSError, json.JSONDecodeError):
                        continue
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    with self._lock:
                        d.results[w.group_rank] = result
                        w.state = "idle"
                        w.job = None
                        w.item = None
                        w.group_rank = None
                        w.jobs_served += 1
                        self._dirty = True
                    hygiene = result.get("hygiene") or {}
                    if not hygiene.get("clean", True):
                        self._audit(
                            "pool_hygiene", job=job, worker=w.rank,
                            report=hygiene,
                        )
                        self._quarantine(w, "hygiene")
                with self._lock:
                    if d.failed is not None:
                        if d.preempted and d.failed == "preempted":
                            rc = 143
                        elif d.failed in ("wedged", "job_timeout"):
                            rc = 124
                        else:
                            rc = d.failed_rc if d.failed_rc else 1
                        break
                    if len(d.results) >= len(d.workers):
                        rc = 0
                        for g in sorted(d.results):
                            r = int(d.results[g].get("rc", 1) or 0)
                            if r != 0:
                                rc = r
                                break
                        break
                if deadline is not None and self.clock() > deadline:
                    self._log(
                        f"job {job}: deadline {timeout:g}s exceeded; "
                        "quarantining its workers"
                    )
                    self._timeout_job(job)
                    continue
                time.sleep(min(self.check_s, 0.005))
        finally:
            with self._lock:
                self._dispatches.pop(job, None)
        return rc, sorted(d.preempted)


if __name__ == "__main__":
    sys.exit(main())
