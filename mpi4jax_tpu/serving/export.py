"""Queue-level OpenMetrics export for the serving plane.

Per-*run* metrics already exist: every job attempt writes a
``launch --events-dir``-layout directory, so the PR 8 live plane
(``observability/{live,export}.py``) renders per-op throughput,
achieved GB/s and verdicts for any single job. What it cannot see is
the *queue* — admission, rejection, depth, wait. This module adds
that layer, built from the spool's own artifacts (``serving.jsonl``
plus the done records), rendered through the same exposition helpers
(:mod:`..observability.export`), and written atomically to
``SPOOL/metrics.prom`` (plus an optional localhost HTTP endpoint via
``serve --metrics-port``).

Families (all prefixed ``m4t_serve_``)::

    m4t_serve_queue_depth                     gauge   pending jobs
    m4t_serve_queue_capacity                  gauge   bounded-queue cap
    m4t_serve_running                         gauge   claimed jobs
    m4t_serve_world                           gauge   mesh capacity (ranks)
    m4t_serve_draining                        gauge   1 while draining
    m4t_serve_jobs_total{outcome=}            counter submitted/admitted/
                                                      completed/failed
    m4t_serve_rejected_total{reason=}         counter load-shed by reason
    m4t_serve_job_queue_wait_seconds{job=,tenant=} gauge per finished job
    m4t_serve_job_run_seconds{job=,tenant=}   gauge   per finished job
    m4t_serve_job_attempts{job=,tenant=}      gauge   per finished job

Federation layer (multi-server spool — PR 14)::

    m4t_serve_servers_alive                   gauge   registered servers
                                                      with a fresh lease
    m4t_serve_server_lease_age{server=}       gauge   seconds since each
                                                      server's renewal
    m4t_serve_reclaims_total{reason=}         counter orphans requeued /
                                                      exhausted by reason
    m4t_serve_fenced_total                    counter zombie terminal
                                                      writes rejected

SLO attribution layer (``serving/slo.py`` — PR 12)::

    m4t_serve_job_latency_seconds{tenant=}    histogram completed-job
                                                      latency (queue
                                                      wait + run)
    m4t_serve_stage_seconds{tenant=,stage=,quantile=} gauge p50/p99 of
                                                      queue_wait / run
                                                      per tenant
    m4t_serve_slo_breaches_total{tenant=,objective=}  counter deduped
                                                      breach verdicts

With a resident warm pool (``serving/pool.py`` — ``serve --warm``),
per-worker health joins the exposition, read from the pool's atomic
``pool.json`` state snapshot plus the per-worker heartbeat sinks::

    m4t_pool_size / m4t_pool_capacity         gauge   slots / not retired
    m4t_pool_worker_alive{worker=}            gauge   1 = idle/busy now
    m4t_pool_worker_jobs_served{worker=}      gauge   payloads completed
    m4t_pool_worker_last_heartbeat_age{worker=} gauge seconds since beat
    m4t_pool_worker_incarnation{worker=}      gauge   respawn generation
    m4t_pool_quarantines_total{reason=}       counter by quarantine reason
    m4t_pool_respawns_total                   counter fresh incarnations
    m4t_pool_retired_total                    counter preempted slots
    m4t_pool_poisoned_total                   counter two-strikes jobs
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional, Union

from ..observability import export as _export
from .spool import Spool

PROM_NAME = "metrics.prom"

#: pool root inside the spool (``serve --warm`` convention)
POOL_DIR = "pool"

#: latency histogram bucket bounds in seconds (Prometheus-style
#: upper-inclusive ``le`` edges; +Inf is implicit)
LATENCY_BUCKETS_S = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(
        len(sorted_vals) - 1,
        max(0, int(round(q * (len(sorted_vals) - 1)))),
    )
    return sorted_vals[i]


def pool_snapshot(
    spool: Union[Spool, str],
) -> Optional[Dict[str, Any]]:
    """The warm pool's health, read entirely from its on-disk
    artifacts (``pool.json`` + per-worker sinks) so ``serving
    status`` and the exporter see the same truth a restarted server
    would. None when no pool ever ran in this spool."""
    root = spool.root if isinstance(spool, Spool) else os.path.abspath(spool)
    pool_root = os.path.join(root, POOL_DIR)
    state_path = os.path.join(pool_root, "pool.json")
    if not os.path.exists(state_path):
        return None
    import json

    from ..observability import events
    from . import pool as _pool

    try:
        with open(state_path) as f:
            state = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(state, dict) or state.get("schema") != _pool.POOL_SCHEMA:
        return None
    now = time.time()
    ages: Dict[str, Optional[float]] = {}
    for w in state.get("workers", []):
        rank = w.get("rank")
        last_t = None
        try:
            for rec in events.iter_records(
                _pool.worker_sink(pool_root, rank)
            ):
                if rec.get("kind") == "heartbeat":
                    t = rec.get("t")
                    if isinstance(t, (int, float)):
                        last_t = t
        except OSError:
            pass
        ages[str(rank)] = (
            None if last_t is None else max(0.0, now - last_t)
        )
    state["heartbeat_age_s"] = ages
    return state


def serving_snapshot(
    spool: Union[Spool, str],
    *,
    capacity: Optional[int] = None,
) -> Dict[str, Any]:
    """One coherent view of the queue: depth/running now (directory
    scan), cumulative outcome counters (audit scan), and per-finished-
    job wait/run durations (done records). ``capacity`` is the live
    server's current mesh world; when absent (offline render) the
    last audited world transition — or serve_start — wins."""
    if not isinstance(spool, Spool):
        spool = Spool(spool)
    counts: Dict[str, int] = {}
    rejected: Dict[str, int] = {}
    reclaims: Dict[str, int] = {}
    fenced = 0
    world = None
    for rec in spool.audit_records():
        event = rec.get("event")
        if event in ("submitted", "admitted", "completed", "failed"):
            counts[event] = counts.get(event, 0) + 1
        elif event == "rejected":
            reason = str(rec.get("reason", "?"))
            rejected[reason] = rejected.get(reason, 0) + 1
        elif event == "reclaim":
            reason = str(rec.get("reason", "?"))
            reclaims[reason] = reclaims.get(reason, 0) + 1
        elif event == "fenced":
            fenced += 1
        elif event == "serve_start":
            world = rec.get("world", world)
        elif event == "world":
            world = rec.get("next_world", world)
    jobs = []
    for rec in spool.done():
        jobs.append({
            "job": rec.get("id"),
            "tenant": rec.get("tenant"),
            "outcome": rec.get("outcome"),
            "queue_wait_s": rec.get("queue_wait_s"),
            "run_s": rec.get("run_s"),
            "attempts": rec.get("attempts"),
        })
    slo_breaches: Dict[Any, int] = {}
    try:
        from . import slo as _slo

        for rec in _slo.load_slo_verdicts([spool.root]):
            finding = rec.get("finding") or {}
            key = (
                str(finding.get("tenant", "?")),
                str(finding.get("objective", "?")),
            )
            slo_breaches[key] = slo_breaches.get(key, 0) + 1
    except Exception:
        pass
    return {
        "depth": spool.depth(),
        "capacity": spool.capacity,
        "running": len(spool.running()),
        "world": capacity if capacity is not None else world,
        "draining": spool.draining(),
        "counts": counts,
        "rejected": rejected,
        "reclaims": reclaims,
        "fenced": fenced,
        "servers": spool.servers(),
        "jobs": jobs,
        "slo_breaches": slo_breaches,
        "pool": pool_snapshot(spool),
        "cp": _cp_snapshot(spool),
        "dispatch": _dispatch_snapshot(spool),
    }


def _dispatch_snapshot(spool: Spool) -> Optional[Dict[str, Any]]:
    """The event-driven dispatch counters (``dispatch.json``) when a
    fastpath server has run against this spool, else None."""
    try:
        from . import dispatch as _dispatch

        return _dispatch.load_snapshot(spool.root)
    except Exception:
        return None


#: cp-report refresh throttle: the serve loop rewrites metrics.prom
#: every iteration, but a full profile report re-reads the whole cp
#: sink — recompute at most this often and reuse the cached block in
#: between (the profiler must not dominate the loop it measures).
#: Patchable; set to 0.0 for always-fresh (tests).
CP_SNAPSHOT_TTL_S = 2.0

_cp_cache: Dict[str, Any] = {}


def _cp_snapshot(spool: Spool) -> Optional[Dict[str, Any]]:
    """Control-plane profile report when a cp sink exists (the server
    ran armed with ``M4T_CP_PROFILE=1``), else None — at most
    :data:`CP_SNAPSHOT_TTL_S` stale. Best-effort: a torn or
    half-written sink never breaks the snapshot."""
    from . import profile as cp_profile

    now = time.monotonic()
    hit = _cp_cache.get(spool.root)
    if hit is not None and (now - hit[0]) < CP_SNAPSHOT_TTL_S:
        return hit[1]
    report: Optional[Dict[str, Any]] = None
    if cp_profile.profile_paths(spool.root):
        try:
            report = cp_profile.profile_report(spool.root)
            if not report["records"]:
                report = None
        except (OSError, ValueError):
            report = None
    _cp_cache[spool.root] = (now, report)
    return report


def render_serving_metrics(snap: Dict[str, Any]) -> str:
    """OpenMetrics 1.0 text (with the mandatory ``# EOF``) for a
    :func:`serving_snapshot`."""
    out: list = []
    g = _export._Family(out, "m4t_serve_queue_depth", "gauge",
                        "Jobs waiting in the spool's pending queue.")
    g.sample(snap.get("depth", 0))
    g = _export._Family(out, "m4t_serve_queue_capacity", "gauge",
                        "Bounded-queue capacity; submits past it are "
                        "rejected (queue_full).")
    g.sample(snap.get("capacity"))
    g = _export._Family(out, "m4t_serve_running", "gauge",
                        "Jobs currently claimed by a server.")
    g.sample(snap.get("running", 0))
    g = _export._Family(out, "m4t_serve_world", "gauge",
                        "Current mesh capacity in ranks (shrinks on "
                        "preemption under --elastic).")
    g.sample(snap.get("world"))
    g = _export._Family(out, "m4t_serve_draining", "gauge",
                        "1 while a drain is requested, else 0.")
    g.sample(1 if snap.get("draining") else 0)

    c = _export._Family(out, "m4t_serve_jobs_total", "counter",
                        "Jobs by lifecycle outcome.")
    for outcome in ("submitted", "admitted", "completed", "failed"):
        c.sample(snap.get("counts", {}).get(outcome, 0),
                 outcome=outcome)
    c = _export._Family(out, "m4t_serve_rejected_total", "counter",
                        "Load-shed and admission rejections by reason.")
    for reason, n in sorted(snap.get("rejected", {}).items()):
        c.sample(n, reason=reason)

    # -- federation layer (multi-server spool) -------------------------
    servers = snap.get("servers") or []
    g = _export._Family(out, "m4t_serve_servers_alive", "gauge",
                        "Registered servers whose heartbeat lease is "
                        "still fresh.")
    g.sample(sum(1 for s in servers if s.get("alive")))
    g = _export._Family(out, "m4t_serve_server_lease_age", "gauge",
                        "Seconds since each registered server renewed "
                        "its lease (an operator sees a dead server "
                        "here before the scavenger acts).")
    for s in servers:
        g.sample(s.get("lease_age_s"), server=str(s.get("id")))
    c = _export._Family(out, "m4t_serve_reclaims_total", "counter",
                        "Orphaned running entries reclaimed from dead "
                        "servers, by detection reason.")
    for reason, n in sorted((snap.get("reclaims") or {}).items()):
        c.sample(n, reason=reason)
    c = _export._Family(out, "m4t_serve_fenced_total", "counter",
                        "Late terminal writes from superseded claim "
                        "epochs (zombie servers) that were rejected.")
    c.sample(snap.get("fenced", 0))

    w = _export._Family(out, "m4t_serve_job_queue_wait_seconds",
                        "gauge",
                        "Queue wait (submit -> admit) per finished "
                        "job.")
    r = _export._Family(out, "m4t_serve_job_run_seconds", "gauge",
                        "Admit -> finish wall clock per finished job.")
    a = _export._Family(out, "m4t_serve_job_attempts", "gauge",
                        "World attempts each finished job consumed.")
    for job in snap.get("jobs", []):
        labels = {
            "job": job.get("job") or "?",
            "tenant": job.get("tenant") or "?",
        }
        w.sample(job.get("queue_wait_s"), **labels)
        r.sample(job.get("run_s"), **labels)
        a.sample(job.get("attempts"), **labels)

    # -- SLO attribution layer (serving/slo.py) ------------------------
    by_tenant: Dict[str, Dict[str, list]] = {}
    for job in snap.get("jobs", []):
        if job.get("outcome") != "completed":
            continue
        tenant = str(job.get("tenant") or "?")
        wait = float(job.get("queue_wait_s") or 0.0)
        run = float(job.get("run_s") or 0.0)
        t = by_tenant.setdefault(tenant, {"latency": [], "wait": [],
                                          "run": []})
        t["latency"].append(wait + run)
        t["wait"].append(wait)
        t["run"].append(run)
    out.append("# TYPE m4t_serve_job_latency_seconds histogram")
    out.append(
        "# HELP m4t_serve_job_latency_seconds Completed-job latency "
        "(queue wait + run) per tenant."
    )
    for tenant in sorted(by_tenant):
        latencies = by_tenant[tenant]["latency"]
        cumulative = 0
        for edge in LATENCY_BUCKETS_S:
            cumulative = sum(1 for v in latencies if v <= edge)
            out.append(
                "m4t_serve_job_latency_seconds_bucket"
                + _export._labels(sorted(
                    {"tenant": tenant, "le": _export._num(edge)}.items()
                ))
                + f" {cumulative}"
            )
        out.append(
            "m4t_serve_job_latency_seconds_bucket"
            + _export._labels(sorted(
                {"tenant": tenant, "le": "+Inf"}.items()
            ))
            + f" {len(latencies)}"
        )
        out.append(
            "m4t_serve_job_latency_seconds_count"
            + _export._labels([("tenant", tenant)])
            + f" {len(latencies)}"
        )
        out.append(
            "m4t_serve_job_latency_seconds_sum"
            + _export._labels([("tenant", tenant)])
            + f" {_export._num(sum(latencies))}"
        )
    g = _export._Family(out, "m4t_serve_stage_seconds", "gauge",
                        "Per-tenant stage latency quantiles "
                        "(queue_wait / run, p50 / p99).")
    for tenant in sorted(by_tenant):
        for stage, key in (("queue_wait", "wait"), ("run", "run")):
            vals = sorted(by_tenant[tenant][key])
            for quantile, q in (("p50", 0.50), ("p99", 0.99)):
                g.sample(_pct(vals, q), tenant=tenant, stage=stage,
                         quantile=quantile)
    c = _export._Family(out, "m4t_serve_slo_breaches_total", "counter",
                        "Deduped SLO-breach verdicts by tenant and "
                        "objective (serving/slo.py).")
    for (tenant, objective), n in sorted(
        (snap.get("slo_breaches") or {}).items()
    ):
        c.sample(n, tenant=tenant, objective=objective)

    pool = snap.get("pool")
    if pool:
        g = _export._Family(out, "m4t_pool_size", "gauge",
                            "Resident worker slots the pool was "
                            "started with.")
        g.sample(pool.get("size"))
        g = _export._Family(out, "m4t_pool_capacity", "gauge",
                            "Slots not permanently retired by "
                            "preemption.")
        g.sample(pool.get("capacity"))
        alive = _export._Family(out, "m4t_pool_worker_alive", "gauge",
                                "1 while the worker is idle or busy "
                                "(0: starting, quarantined, or "
                                "retired).")
        served = _export._Family(out, "m4t_pool_worker_jobs_served",
                                 "gauge",
                                 "Work items this worker slot has "
                                 "completed (across incarnations).")
        inc = _export._Family(out, "m4t_pool_worker_incarnation",
                              "gauge",
                              "Respawn generation of the slot's "
                              "current process.")
        age = _export._Family(out, "m4t_pool_worker_last_heartbeat_age",
                              "gauge",
                              "Seconds since the worker's last "
                              "heartbeat record.")
        ages = pool.get("heartbeat_age_s", {})
        for worker in pool.get("workers", []):
            labels = {"worker": str(worker.get("rank"))}
            alive.sample(
                1 if worker.get("state") in ("idle", "busy") else 0,
                **labels,
            )
            served.sample(worker.get("jobs_served"), **labels)
            inc.sample(worker.get("incarnation"), **labels)
            age.sample(ages.get(str(worker.get("rank"))), **labels)
        counters = pool.get("counters", {})
        c = _export._Family(out, "m4t_pool_quarantines_total",
                            "counter",
                            "Worker quarantines by reason (wedged, "
                            "exited, hygiene, job_timeout, "
                            "peer_lost, start_timeout).")
        for reason, n in sorted(
            (counters.get("quarantines") or {}).items()
        ):
            c.sample(n, reason=reason)
        c = _export._Family(out, "m4t_pool_respawns_total", "counter",
                            "Fresh worker incarnations spawned after "
                            "quarantines.")
        c.sample(counters.get("respawns", 0))
        c = _export._Family(out, "m4t_pool_retired_total", "counter",
                            "Slots permanently lost to preemption "
                            "(elastic).")
        c.sample(counters.get("retired", 0))
        c = _export._Family(out, "m4t_pool_poisoned_total", "counter",
                            "Jobs poisoned by the two-strikes rule.")
        c.sample(counters.get("poisoned", 0))

    disp = snap.get("dispatch")
    if disp:
        g = _export._Family(out, "m4t_dispatch_wire", "gauge",
                            "1 for the wake wire the event-driven "
                            "dispatch plane is running on (inotify, "
                            "socket, or poll-fallback).")
        g.sample(1, wire=str(disp.get("wire")))
        c = _export._Family(out, "m4t_dispatch_wakeups_total",
                            "counter",
                            "Wake-wire deliveries that woke the serve "
                            "loop, by wire.")
        for wire, n in sorted((disp.get("wakeups") or {}).items()):
            c.sample(n, wire=wire)
        c = _export._Family(out, "m4t_dispatch_batches_total",
                            "counter",
                            "Claim batches leased by claim_batch.")
        c.sample(disp.get("batches", 0))
        g = _export._Family(out, "m4t_dispatch_batch_size", "gauge",
                            "Jobs per claim batch (quantiles over "
                            "the server's lifetime).")
        for q, key in (("0.5", "batch_size_p50"),
                       ("0.9", "batch_size_p90"),
                       ("1.0", "batch_size_max")):
            if disp.get(key) is not None:
                g.sample(disp[key], quantile=q)
        c = _export._Family(out, "m4t_dispatch_coalesced_jobs_total",
                            "counter",
                            "Jobs that rode a shared sub-mesh "
                            "dispatch instead of their own.")
        c.sample(disp.get("coalesced_jobs", 0))
        c = _export._Family(out, "m4t_dispatch_group_commits_total",
                            "counter",
                            "Batched terminal-record flushes (one "
                            "fsync each).")
        c.sample(disp.get("group_commits", 0))
        if disp.get("fsyncs_per_job") is not None:
            g = _export._Family(out, "m4t_dispatch_fsyncs_per_job",
                                "gauge",
                                "Estimated fsyncs per job on the "
                                "fastpath (submit fsync + amortized "
                                "group commit).")
            g.sample(disp["fsyncs_per_job"])

    if snap.get("cp"):
        from . import profile as cp_profile

        cp_profile.render_cp_families(out, snap["cp"])

    out.append("# EOF")
    return "\n".join(out) + "\n"


def write_serving_prom(
    spool: Union[Spool, str],
    *,
    capacity: Optional[int] = None,
    path: Optional[str] = None,
) -> str:
    """Atomic ``metrics.prom`` snapshot in the spool root (tmp+rename
    via the shared exposition writer — a scraper never reads a torn
    file)."""
    if not isinstance(spool, Spool):
        spool = Spool(spool)
    snap = serving_snapshot(spool, capacity=capacity)
    text = render_serving_metrics(snap)
    if path is None:
        path = os.path.join(spool.root, PROM_NAME)
    return _export.write_prom(path, text)
