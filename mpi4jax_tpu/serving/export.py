"""Queue-level OpenMetrics export for the serving plane.

Per-*run* metrics already exist: every job attempt writes a
``launch --events-dir``-layout directory, so the PR 8 live plane
(``observability/{live,export}.py``) renders per-op throughput,
achieved GB/s and verdicts for any single job. What it cannot see is
the *queue* — admission, rejection, depth, wait. This module adds
that layer, built from the spool's own artifacts (``serving.jsonl``
plus the done records), rendered through the same exposition helpers
(:mod:`..observability.export`), and written atomically to
``SPOOL/metrics.prom`` (plus an optional localhost HTTP endpoint via
``serve --metrics-port``).

Families (all prefixed ``m4t_serve_``)::

    m4t_serve_queue_depth                     gauge   pending jobs
    m4t_serve_queue_capacity                  gauge   bounded-queue cap
    m4t_serve_running                         gauge   claimed jobs
    m4t_serve_world                           gauge   mesh capacity (ranks)
    m4t_serve_draining                        gauge   1 while draining
    m4t_serve_jobs_total{outcome=}            counter submitted/admitted/
                                                      completed/failed
    m4t_serve_rejected_total{reason=}         counter load-shed by reason
    m4t_serve_job_queue_wait_seconds{job=,tenant=} gauge per finished job
    m4t_serve_job_run_seconds{job=,tenant=}   gauge   per finished job
    m4t_serve_job_attempts{job=,tenant=}      gauge   per finished job
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Union

from ..observability import export as _export
from .spool import Spool

PROM_NAME = "metrics.prom"


def serving_snapshot(
    spool: Union[Spool, str],
    *,
    capacity: Optional[int] = None,
) -> Dict[str, Any]:
    """One coherent view of the queue: depth/running now (directory
    scan), cumulative outcome counters (audit scan), and per-finished-
    job wait/run durations (done records). ``capacity`` is the live
    server's current mesh world; when absent (offline render) the
    last audited world transition — or serve_start — wins."""
    if not isinstance(spool, Spool):
        spool = Spool(spool)
    counts: Dict[str, int] = {}
    rejected: Dict[str, int] = {}
    world = None
    for rec in spool.audit_records():
        event = rec.get("event")
        if event in ("submitted", "admitted", "completed", "failed"):
            counts[event] = counts.get(event, 0) + 1
        elif event == "rejected":
            reason = str(rec.get("reason", "?"))
            rejected[reason] = rejected.get(reason, 0) + 1
        elif event == "serve_start":
            world = rec.get("world", world)
        elif event == "world":
            world = rec.get("next_world", world)
    jobs = []
    for rec in spool.done():
        jobs.append({
            "job": rec.get("id"),
            "tenant": rec.get("tenant"),
            "outcome": rec.get("outcome"),
            "queue_wait_s": rec.get("queue_wait_s"),
            "run_s": rec.get("run_s"),
            "attempts": rec.get("attempts"),
        })
    return {
        "depth": spool.depth(),
        "capacity": spool.capacity,
        "running": len(spool.running()),
        "world": capacity if capacity is not None else world,
        "draining": spool.draining(),
        "counts": counts,
        "rejected": rejected,
        "jobs": jobs,
    }


def render_serving_metrics(snap: Dict[str, Any]) -> str:
    """OpenMetrics 1.0 text (with the mandatory ``# EOF``) for a
    :func:`serving_snapshot`."""
    out: list = []
    g = _export._Family(out, "m4t_serve_queue_depth", "gauge",
                        "Jobs waiting in the spool's pending queue.")
    g.sample(snap.get("depth", 0))
    g = _export._Family(out, "m4t_serve_queue_capacity", "gauge",
                        "Bounded-queue capacity; submits past it are "
                        "rejected (queue_full).")
    g.sample(snap.get("capacity"))
    g = _export._Family(out, "m4t_serve_running", "gauge",
                        "Jobs currently claimed by a server.")
    g.sample(snap.get("running", 0))
    g = _export._Family(out, "m4t_serve_world", "gauge",
                        "Current mesh capacity in ranks (shrinks on "
                        "preemption under --elastic).")
    g.sample(snap.get("world"))
    g = _export._Family(out, "m4t_serve_draining", "gauge",
                        "1 while a drain is requested, else 0.")
    g.sample(1 if snap.get("draining") else 0)

    c = _export._Family(out, "m4t_serve_jobs_total", "counter",
                        "Jobs by lifecycle outcome.")
    for outcome in ("submitted", "admitted", "completed", "failed"):
        c.sample(snap.get("counts", {}).get(outcome, 0),
                 outcome=outcome)
    c = _export._Family(out, "m4t_serve_rejected_total", "counter",
                        "Load-shed and admission rejections by reason.")
    for reason, n in sorted(snap.get("rejected", {}).items()):
        c.sample(n, reason=reason)

    w = _export._Family(out, "m4t_serve_job_queue_wait_seconds",
                        "gauge",
                        "Queue wait (submit -> admit) per finished "
                        "job.")
    r = _export._Family(out, "m4t_serve_job_run_seconds", "gauge",
                        "Admit -> finish wall clock per finished job.")
    a = _export._Family(out, "m4t_serve_job_attempts", "gauge",
                        "World attempts each finished job consumed.")
    for job in snap.get("jobs", []):
        labels = {
            "job": job.get("job") or "?",
            "tenant": job.get("tenant") or "?",
        }
        w.sample(job.get("queue_wait_s"), **labels)
        r.sample(job.get("run_s"), **labels)
        a.sample(job.get("attempts"), **labels)

    out.append("# EOF")
    return "\n".join(out) + "\n"


def write_serving_prom(
    spool: Union[Spool, str],
    *,
    capacity: Optional[int] = None,
    path: Optional[str] = None,
) -> str:
    """Atomic ``metrics.prom`` snapshot in the spool root (tmp+rename
    via the shared exposition writer — a scraper never reads a torn
    file)."""
    if not isinstance(spool, Spool):
        spool = Spool(spool)
    snap = serving_snapshot(spool, capacity=capacity)
    text = render_serving_metrics(snap)
    if path is None:
        path = os.path.join(spool.root, PROM_NAME)
    return _export.write_prom(path, text)
