"""Serving plane: a queue-draining multi-job supervisor.

Turns the one-shot launcher into something that sustains traffic: a
filesystem job spool with atomic claims and bounded backpressure
(:mod:`.spool`), FIFO + per-tenant round-robin scheduling
(:mod:`.scheduler`), a long-lived server that runs every job in its
own fault domain and survives overload, job failure and host loss
(:mod:`.server`), and a queue-level OpenMetrics exporter
(:mod:`.export`). CLI::

    python -m mpi4jax_tpu.serving serve  SPOOL -n 4 [--elastic ...]
    python -m mpi4jax_tpu.serving submit SPOOL --cmd script.py ...
    python -m mpi4jax_tpu.serving status SPOOL [--json]
    python -m mpi4jax_tpu.serving drain  SPOOL [--wait]
    python -m mpi4jax_tpu.serving --selftest

See ``docs/serving.md`` for the job-spec schema, the scheduler policy
table, backpressure semantics, and a drain walkthrough.
"""

from .scheduler import FairScheduler
from .server import Server
from .spool import (
    JOB_SCHEMA,
    JobSpec,
    JobSpecError,
    Spool,
    parse_job,
)

__all__ = [
    "JOB_SCHEMA",
    "FairScheduler",
    "JobSpec",
    "JobSpecError",
    "Server",
    "Spool",
    "parse_job",
]
