"""Serving plane: a queue-draining multi-job supervisor.

Turns the one-shot launcher into something that sustains traffic: a
filesystem job spool with atomic claims and bounded backpressure
(:mod:`.spool`), FIFO + per-tenant round-robin scheduling
(:mod:`.scheduler`), a long-lived server that runs every job in its
own fault domain and survives overload, job failure and host loss
(:mod:`.server`), and a queue-level OpenMetrics exporter
(:mod:`.export`). CLI::

    python -m mpi4jax_tpu.serving serve  SPOOL -n 4 [--warm] [--elastic ...]
    python -m mpi4jax_tpu.serving submit SPOOL --cmd script.py ...
    python -m mpi4jax_tpu.serving status SPOOL [--json]
    python -m mpi4jax_tpu.serving drain  SPOOL [--wait]
    python -m mpi4jax_tpu.serving --selftest

``serve --warm`` arms the self-healing resident worker pool
(:mod:`.pool`): rank processes spawned once that loop on filesystem
mailboxes, keeping imports/compile/plan caches warm across jobs,
watched by a pool doctor that quarantines and respawns wedged,
crashed, and leaky workers and poisons jobs that wedge workers twice.

Every submitted job carries a **trace id** (minted at submit, additive
``m4t-job/1`` field) that threads through every plane — lifecycle
spans on ``serving.jsonl`` (``observability/spans.py``), rank
environments (``M4T_TRACE_ID``), and armed per-emission telemetry
stamps — so ``trace --serve SPOOL`` renders one merged Perfetto file
per spool and ``serve --slo 'p99_latency_s=2.0'`` (:mod:`.slo`)
attributes SLO breaches to the stage that ate the time.

See ``docs/serving.md`` for the job-spec schema, the scheduler policy
table, backpressure semantics, the warm-pool lifecycle, the
SLO-config reference, and a drain walkthrough.
"""

from .scheduler import FairScheduler
from .server import Server
from .spool import (
    JOB_SCHEMA,
    JobSpec,
    JobSpecError,
    Spool,
    parse_job,
)

__all__ = [
    "JOB_SCHEMA",
    "FairScheduler",
    "JobSpec",
    "JobSpecError",
    "SLOWatch",
    "Server",
    "Spool",
    "WorkerPool",
    "job_comm",
    "parse_job",
    "parse_slo",
    "slo",
]


def __getattr__(name):
    # lazy on purpose: the worker entry point is `python -m
    # mpi4jax_tpu.serving.pool`, and an eager `from .pool import ...`
    # here would put the module in sys.modules before runpy executes
    # it as __main__ (the classic double-import warning)
    if name in ("WorkerPool", "job_comm"):
        from . import pool as _pool

        return getattr(_pool, name)
    if name in ("SLOWatch", "parse_slo", "slo"):
        # importlib on purpose: `from . import slo` inside
        # __getattr__("slo") re-enters this hook through the import
        # system's hasattr check — instant recursion
        import importlib

        _slo = importlib.import_module(".slo", __name__)
        return _slo if name == "slo" else getattr(_slo, name)
    raise AttributeError(name)
