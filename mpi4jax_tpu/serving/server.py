"""The queue-draining serving supervisor.

One long-lived process multiplexes many submitted SPMD jobs over one
machine's worth of mesh capacity, treating overload, job failure and
capacity loss as routine events:

- **Admission** — a claimed job can be gated through the static
  verifier (``analysis``'s ``launch --verify`` path): a program the
  schedule simulator cannot prove deadlock-free is *rejected* before
  it can wedge the shared mesh, with the finding on the audit trail.
- **Fair scheduling** — FIFO + per-tenant round-robin
  (:mod:`.scheduler`); one world runs at a time, sized
  ``min(job.nproc, current capacity)``.
- **Per-job fault domains** — each job runs under its *own*
  :class:`~..resilience.supervisor.Supervisor` with its own
  :class:`~..resilience.supervisor.RetryPolicy` budget: a MISMATCH
  (deterministic, per the doctor) fails that job only; transient
  verdicts (hang, crash, straggler) retry it from its own
  ``resume_dir`` checkpoints; the server keeps serving either way.
  A job's deadline (``timeout_s``) is enforced by the spawn path's
  hang watchdog — terminate, grace window for flight-recorder dumps,
  then kill — so a wedged job cannot hold the queue hostage.
- **Capacity loss** — a rank exiting with the preemption signature
  (``PREEMPT_EXIT`` 143 / SIGTERM) under ``--elastic`` means the mesh
  lost a host, not that the job is buggy: the server shrinks its
  capacity, reshards the resident job's newest ``m4t-ckpt/2``
  checkpoint to the smaller world through the bounded-memory planner
  (``resilience/reshard.py``), re-proves the program at the shrunk
  world when verification is on, resumes the job there, and serves
  every subsequent job at the smaller world. Every world transition
  is audited in ``serving.jsonl`` and narrated by the doctor.
- **Observability** — each job attempt gets its own events dir
  (``jobs/<id>/attempt<k>/``, the ``launch --events-dir`` layout), so
  the live plane, streaming doctor, and per-run OpenMetrics export
  all work per job; the queue-level exporter (:mod:`.export`) adds
  jobs-admitted/rejected/completed/failed counters and queue-depth
  gauges, refreshed into ``metrics.prom`` and optionally served on
  localhost HTTP.

The world-spawning side is injectable (``runner=``), which is what
makes the whole control plane device-free-testable: the selftest and
most tests drive it with a stub runner and never fork a rank.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..resilience.supervisor import RetryPolicy, Supervisor
from . import profile as _profile
from .scheduler import FairScheduler
from .spool import (
    DEFAULT_LEASE_S,
    DEFAULT_MAX_RECLAIMS,
    JobSpec,
    Spool,
)

#: a runner maps (spec, world, events_dir, attempt, resume_step) to
#: ``(exit_code, preempted_ranks)`` — the ``launch.spawn_world``
#: contract
Runner = Callable[
    [JobSpec, int, Optional[str], int, Optional[int]],
    Tuple[int, List[int]],
]


def _default_log(msg: str) -> None:
    sys.stderr.write(f"m4t.serving: {msg}\n")


class Server:
    """Claim jobs from a :class:`~.spool.Spool` and run each one to a
    final audited outcome. See the module docstring for semantics."""

    def __init__(
        self,
        spool: Spool,
        *,
        nproc: int,
        elastic: bool = False,
        min_ranks: int = 1,
        verify: bool = False,
        poll_s: float = 0.2,
        fastpath: Optional[str] = None,
        batch: int = 8,
        coalesce: bool = True,
        max_jobs: Optional[int] = None,
        idle_exit_s: Optional[float] = None,
        runner: Optional[Runner] = None,
        verify_fn: Optional[Callable[[JobSpec, int], bool]] = None,
        metrics_port: Optional[int] = None,
        pool: Optional[Any] = None,
        slo: Optional[Any] = None,
        server_id: Optional[str] = None,
        lease_s: float = DEFAULT_LEASE_S,
        max_reclaims: int = DEFAULT_MAX_RECLAIMS,
        clock: Callable[[], float] = time.time,
        log: Callable[[str], None] = _default_log,
    ):
        if nproc < 1:
            raise ValueError("serve needs nproc >= 1")
        if min_ranks < 1 or min_ranks > nproc:
            raise ValueError("min_ranks must be in [1, nproc]")
        if float(poll_s) <= 0.0:
            raise ValueError("poll_s must be > 0")
        self.spool = spool
        #: this serving loop's federation identity: its lease file,
        #: its claims' owner suffix, and the id the fence checks
        self.server_id = server_id or (
            f"s-{os.getpid():x}-{os.urandom(3).hex()}"
        )
        self.lease_s = float(lease_s)
        self.max_reclaims = int(max_reclaims)
        self._clock = clock
        self._last_renew = 0.0
        self._last_scavenge = 0.0
        self.capacity = int(nproc)
        self.elastic = bool(elastic)
        self.min_ranks = int(min_ranks)
        self.verify = bool(verify)
        self.poll_s = float(poll_s)
        #: the event-driven dispatch plane (serving/dispatch.py),
        #: strictly opt-in: None keeps the classic poll loop
        #: byte-identical; "auto" (or a wire name — "inotify" /
        #: "socket" / "poll") arms wake wires, batched claims,
        #: coalescing and group commit
        self.fastpath = fastpath
        if int(batch) < 1:
            raise ValueError("batch must be >= 1")
        self.batch = int(batch)
        self.coalesce = bool(coalesce)
        #: armed inside a fastpath batch: _finish fences now and
        #: buffers the terminal record for one group-commit fsync
        self._finish_buffer: Optional[List[Dict[str, Any]]] = None
        self._dispatch_stats: Optional[Any] = None
        self.max_jobs = max_jobs
        self.idle_exit_s = idle_exit_s
        self.scheduler = FairScheduler()
        #: the resident warm pool (serving/pool.py), if armed: jobs
        #: become work items on its mailboxes instead of spawned
        #: worlds, and the serve loop packs concurrent jobs onto
        #: disjoint sub-meshes
        self._pool = pool
        if pool is not None and runner is None:
            runner = pool.runner
        if pool is not None and getattr(pool, "_span_fn", None) is None:
            # the pool's warm_dispatch spans belong on the same trace
            # the server's chain spans land on — wire its span seam to
            # this spool unless a harness already did
            pool._span_fn = spool.span
        if pool is not None and getattr(pool, "_strike_fn", None) is None:
            # write dispatch-failure strikes through to the spool's
            # persistent verdicts: a job that wedges this server's
            # workers is refused by every peer, not just this pool
            pool._strike_fn = (
                lambda job, reason: spool.record_strike(
                    job, reason=reason, server=self.server_id,
                    max_strikes=getattr(pool, "max_strikes", 2),
                )
            )
        if pool is not None and getattr(
            pool, "_poisoned_fn", None
        ) is None:
            pool._poisoned_fn = spool.poisoned
        self._runner = runner or self._launch_runner
        self._verify_fn = verify_fn or self._launch_verify
        self.metrics_port = metrics_port
        self._http = None
        #: the SLO watch (serving/slo.py), if armed: evaluated after
        #: every finished job, breaches land as deduped verdict events
        self._slo = slo
        self._log = log
        self._metrics_lock = threading.Lock()
        self.jobs_served = 0
        #: set when capacity fell below min_ranks: serving cannot
        #: honestly continue, the loop exits nonzero
        self.capacity_lost = False

    # -- default spawn/verify backends (the launch.py reuse seam) ------

    def _world_args(self, spec: JobSpec, world: int):
        from .. import launch

        return launch.make_world_args(
            nproc=world,
            cmd=list(spec.cmd or []),
            module=spec.module,
            hang_timeout=float(spec.timeout_s or 0.0),
            # per-job trace context: every rank's telemetry records
            # join the job's span chain on this key
            trace_id=spec.trace,
            job_id=spec.id,
        )

    def _job_span(self, spec: JobSpec, name: str, t0: float, t1: float,
                  **fields: Any) -> None:
        """One lifecycle span on this job's trace (best-effort)."""
        try:
            self.spool.span(
                name, job=spec.id, t0=t0, t1=t1, trace=spec.trace,
                tenant=spec.tenant, **fields,
            )
        except Exception:
            pass

    def _launch_runner(
        self,
        spec: JobSpec,
        world: int,
        events_dir: Optional[str],
        attempt: int,
        resume_step: Optional[int],
    ) -> Tuple[int, List[int]]:
        from .. import launch

        args = self._world_args(spec, world)
        args.elastic = self.elastic  # preempt-first settle window
        fault_plan_env = None
        if spec.fault_plan is not None:
            fault_plan_env = (
                spec.fault_plan if isinstance(spec.fault_plan, str)
                else json.dumps(spec.fault_plan)
            )
        return launch.spawn_world(
            args,
            events_dir,
            attempt=attempt,
            resume_step=resume_step,
            fault_plan_env=fault_plan_env,
            world=world,
            extra_env=spec.env,
            span_fn=lambda name, t0, t1: self._job_span(
                spec, name, t0, t1, attempt=attempt, world=world,
            ),
        )

    def _launch_verify(self, spec: JobSpec, world: int) -> bool:
        """The admission gate: prove the job's declared entry points
        deadlock-free at ``world`` ranks before it touches the mesh
        (``launch --verify`` semantics, reused verbatim)."""
        from .. import launch

        args = self._world_args(spec, world)
        try:
            return launch._verify_prelaunch(args, world=world) == 0
        except Exception as exc:
            self._log(f"job {spec.id}: verify failed: {exc!r}")
            return False

    # -- metrics -------------------------------------------------------

    def _write_metrics(self) -> None:
        from . import export as _sexport

        try:
            with self._metrics_lock:
                _sexport.write_serving_prom(
                    self.spool, capacity=self.capacity,
                )
        except Exception:
            pass  # metrics must never take the queue down

    def _start_metrics(self) -> None:
        if self.metrics_port is None:
            return
        from ..observability import export as _oexport
        from . import export as _sexport

        def render() -> str:
            return _sexport.render_serving_metrics(
                _sexport.serving_snapshot(
                    self.spool, capacity=self.capacity
                )
            )

        self._http = _oexport.serve(render, port=self.metrics_port)
        self._log(
            "serving OpenMetrics on "
            f"http://127.0.0.1:{self._http.server_port}/metrics"
        )

    def _stop_metrics(self) -> None:
        if self._http is not None:
            try:
                self._http.shutdown()
            except Exception:
                pass
            self._http = None

    # -- elastic capacity ----------------------------------------------

    def _set_capacity(self, new_world: int, **audit: Any) -> None:
        old = self.capacity
        if new_world == old:
            return
        self.capacity = int(new_world)
        self.spool.audit(
            "world", world=old, next_world=self.capacity, **audit
        )
        self._log(
            f"mesh capacity {old} -> {self.capacity} rank(s)"
        )

    def _ckpt_resume(
        self, spec: JobSpec, world: int
    ) -> Tuple[Optional[int], Optional[Dict[str, Any]]]:
        """The newest step ``spec`` can resume from at ``world``, and
        the reshard source (``{"step", "world"}``) when the candidate
        had to go through the bounded-memory planner first. Shared by
        the elastic shrink path and reclaimed-job resume — both are
        "pick up mid-flight work at whatever world I have now"."""
        resume = None
        reshard_src = None
        if not spec.resume_dir:
            return resume, reshard_src
        try:
            from ..resilience import reshard as _reshard
            from ..resilience.ckpt import CheckpointManager

            mgr = CheckpointManager(spec.resume_dir, world=world)
            info = mgr.latest_valid(world=world, allow_reshard=True)
            if info is None:
                self._log(
                    f"job {spec.id}: no valid checkpoint to carry "
                    "over; resuming from step 0"
                )
            elif not info.world_mismatch:
                resume = info.step
            elif not info.sharded:
                self._log(
                    f"job {spec.id}: checkpoint step {info.step} "
                    f"predates m4t-ckpt/2 and cannot be resharded; "
                    "resuming from step 0"
                )
            else:
                reshard_t0 = time.time()
                new_info = _reshard.reshard_checkpoint(
                    mgr, info, world,
                    log=lambda m: self._log(f"job {spec.id}: {m}"),
                )
                resume = new_info.step
                reshard_src = {
                    "step": info.step, "world": info.world,
                }
                self._job_span(
                    spec, "reshard", reshard_t0, time.time(),
                    from_world=info.world, to_world=world,
                    step=info.step,
                )
        except Exception as exc:
            self._log(
                f"job {spec.id}: reshard failed ({exc!r}); "
                "resuming from step 0"
            )
            resume = None
        return resume, reshard_src

    def _shrink_for(self, spec: JobSpec, state: Dict[str, Any]):
        """Preemption mid-job under ``--elastic``: shrink capacity to
        the survivors, reshard the job's newest checkpoint to the new
        world, re-verify there, and return the step the next attempt
        resumes from (None = from scratch). Mirrors the launcher's
        elastic path; the difference is that the shrink outlives the
        job — every later job serves at the smaller world too."""
        old_world = state["world"]
        lost = len(state["preempted"])
        if self._pool is not None:
            # the pool already retired the preempted slots; capacity
            # is whatever survives, and the job resumes at the
            # largest sub-mesh that still fits it
            new_cap = self._pool.capacity()
            new_world = min(old_world, new_cap)
        else:
            new_world = old_world - lost
            new_cap = new_world
        pre = ",".join(str(p) for p in state["preempted"])
        self._log(
            f"job {spec.id}: {lost} rank(s) preempted ({pre}); "
            f"draining and shrinking world {old_world} -> {new_world}"
        )
        if new_cap < self.min_ranks:
            state["blocked"] = (
                f"only {new_cap} survivor(s) — below "
                f"--min-ranks {self.min_ranks}"
            )
            self._set_capacity(
                max(new_cap, 0), job=spec.id,
                reason="preempted_below_min",
            )
            self.capacity_lost = True
            self._log(f"job {spec.id}: {state['blocked']}; giving up")
            return None
        resume, reshard_src = self._ckpt_resume(spec, new_world)
        if (self.verify or spec.verify) and not self._verify_fn(
            spec, new_world
        ):
            state["blocked"] = (
                f"verify failed at the shrunk world {new_world}"
            )
            self._log(f"job {spec.id}: {state['blocked']}; giving up")
            self._set_capacity(new_cap, job=spec.id)
            return None
        state["transition"] = {
            "world": old_world,
            "next_world": new_world,
            "resharded_from": reshard_src,
        }
        state["world"] = new_world
        audit: Dict[str, Any] = {"job": spec.id, "preempted_ranks":
                                 list(state["preempted"])}
        if reshard_src:
            audit["resharded_from_step"] = reshard_src["step"]
            audit["resharded_from_world"] = reshard_src["world"]
        self._set_capacity(new_cap, **audit)
        return resume

    # -- federation: lease, scavenge, fence ----------------------------

    def _register(self) -> None:
        now = self._clock()
        try:
            self.spool.register_server(
                self.server_id, lease_s=self.lease_s, now=now,
                world=self.capacity,
            )
        except Exception as exc:
            self._log(f"server registration failed: {exc!r}")
        self._last_renew = now
        self._last_scavenge = now

    def _deregister(self) -> None:
        try:
            self.spool.deregister_server(
                self.server_id, jobs=self.jobs_served,
            )
        except Exception:
            pass

    def _federation_tick(self) -> None:
        """Once per loop turn: renew this server's lease (at a third
        of the lease period, so two missed renewals still beat
        expiry) and scavenge peers' orphans (at a quarter — failover
        latency is bounded by lease + scavenge cadence)."""
        now = self._clock()
        if now - self._last_renew >= self.lease_s / 3.0:
            self._last_renew = now
            try:
                self.spool.renew_lease(self.server_id, now=now)
            except Exception:
                pass
        if now - self._last_scavenge >= self.lease_s / 4.0:
            self._last_scavenge = now
            try:
                for act in self.spool.reclaim(
                    now=now, by=self.server_id,
                    max_reclaims=self.max_reclaims,
                ):
                    self._log(
                        f"job {act.get('job')}: {act.get('action')} "
                        f"(owner {act.get('from_server')}, "
                        f"{act.get('reason')})"
                    )
            except Exception as exc:
                self._log(f"scavenger pass failed: {exc!r}")

    def _finish(self, spec: JobSpec, outcome: str, **extra: Any) -> bool:
        """Write ``spec``'s terminal record under this server's claim
        epoch. False means this server was fenced — the job was
        reclaimed while we ran it, its story belongs to the claimant
        now, and *nothing* more may be written for it. A spec claimed
        without an owner (single-server harnesses driving
        :meth:`run_job` directly) takes the unfenced legacy path.

        Inside a fastpath batch (``_finish_buffer`` armed) the fence
        still happens *now* — the exactly-once arbiter and the audits
        it gates stay truthful — but the durable write is buffered for
        one group-commit fsync (``Spool.finish_batch``) at the end of
        the batch."""
        if self._finish_buffer is not None:
            token = self.spool.fence(
                spec, outcome, server=spec.owner, epoch=spec.epoch,
            )
            if token is None:
                self._log(
                    f"job {spec.id}: fenced — claim epoch "
                    f"{spec.epoch} was superseded; dropping late "
                    f"'{outcome}' record"
                )
                return False
            self._finish_buffer.append({
                "spec": spec, "outcome": outcome, "extra": dict(extra),
                "token": token,
            })
            return True
        if spec.owner is None:
            self.spool.finish(spec, outcome, **extra)
            return True
        ok = self.spool.finish(
            spec, outcome, server=spec.owner, epoch=spec.epoch,
            **extra,
        )
        if not ok:
            self._log(
                f"job {spec.id}: fenced — claim epoch "
                f"{spec.epoch} was superseded; dropping late "
                f"'{outcome}' record"
            )
        return ok

    # -- one job -------------------------------------------------------

    def run_job(self, spec: JobSpec) -> str:
        """Run one claimed job to a final outcome; returns it
        (``completed`` / ``failed`` / ``rejected``). Never raises —
        a job is its own fault domain."""
        try:
            outcome = self._run_job(spec)
        except Exception as exc:
            self._log(f"job {spec.id}: internal error: {exc!r}")
            try:
                if self._finish(
                    spec, "failed", reason="internal_error",
                    error=repr(exc),
                ):
                    self.spool.audit(
                        "failed", job=spec.id, tenant=spec.tenant,
                        reason="internal_error", error=repr(exc),
                    )
            except Exception:
                pass
            outcome = "failed"
        self._check_slo()
        return outcome

    def _check_slo(self) -> None:
        """Evaluate the armed SLO config over the finished jobs; new
        breaches land as verdict events (serving/slo.py). Best-effort
        like metrics: attribution must never take the queue down."""
        if self._slo is None:
            return
        try:
            for breach in self._slo.check():
                self._log(self._slo.narrate(breach))
        except Exception:
            pass

    def _run_job(self, spec: JobSpec) -> str:
        t0 = time.time()
        wait_s = max(0.0, t0 - (spec.submitted_t or t0))
        world = min(spec.nproc, self.capacity)
        if self.spool.poisoned(spec.id):
            # the spool-wide verdict (written when this job wedged
            # *some* server's workers) outranks local state: refuse
            # dispatch even if this server never saw it misbehave
            self._log(
                f"job {spec.id}: refused — poisoned verdict on the "
                "spool"
            )
            if self._finish(
                spec, "failed", reason="poisoned", refused=True,
                queue_wait_s=round(wait_s, 6),
            ):
                self.spool.audit(
                    "failed", job=spec.id, tenant=spec.tenant,
                    reason="poisoned", refused=True,
                )
            return "failed"
        resume0: Optional[int] = None
        admit_extra: Dict[str, Any] = {}
        if spec.reclaims > 0:
            # reclaimed from a dead server: pick up its mid-flight
            # work at whatever world this server has (resharding
            # through the planner when the worlds differ)
            resume0, _ = self._ckpt_resume(spec, world)
            admit_extra["reclaims"] = spec.reclaims
            if resume0 is not None:
                admit_extra["resume_step"] = resume0
        self.spool.audit(
            "admitted", job=spec.id, tenant=spec.tenant, world=world,
            requested_nproc=spec.nproc, queue_wait_s=round(wait_s, 6),
            trace=spec.trace, **admit_extra,
        )
        # the chain spans share boundary clock reads on purpose:
        # queued.t1 == verify.t0 == ... — gaplessness by construction,
        # which is exactly what the span-chain property test asserts
        self._job_span(
            spec, "queued", (spec.submitted_t or t0), t0,
            depth_wait_s=round(wait_s, 6),
        )
        t_gate = t0
        if self.verify or spec.verify:
            verified = self._verify_fn(spec, world)
            t_gate = time.time()
            self._job_span(
                spec, "verify", t0, t_gate, world=world,
                passed=verified,
            )
            if not verified:
                # the unprovable program never touches the shared mesh
                if self._finish(
                    spec, "rejected", reason="verify_failed",
                    world=world, queue_wait_s=wait_s,
                ):
                    self.spool.audit(
                        "rejected", job=spec.id, tenant=spec.tenant,
                        reason="verify_failed", world=world,
                    )
                return "rejected"

        jobdir = self.spool.job_dir(spec.id)
        state: Dict[str, Any] = {
            "world": world, "world_ran": world, "preempted": [],
            "transition": None, "blocked": None, "dir": None,
        }

        def attempt_dir(attempt: int) -> str:
            d = os.path.join(jobdir, f"attempt{attempt:02d}")
            os.makedirs(d, exist_ok=True)
            return d

        def run_fn(attempt: int, resume_step: Optional[int]) -> int:
            if state["blocked"]:
                self._log(
                    f"job {spec.id}: attempt {attempt} not spawned: "
                    f"{state['blocked']}"
                )
                return 1
            d = attempt_dir(attempt)
            state["dir"] = d
            state["world_ran"] = state["world"]
            self._log(
                f"job {spec.id}: attempt {attempt} "
                f"(world {state['world']})"
                + (f", resuming from step {resume_step}"
                   if resume_step is not None else "")
            )
            rc, preempted = self._runner(
                spec, state["world"], d, attempt, resume_step
            )
            state["preempted"] = list(preempted or [])
            return rc

        def diagnose_fn(attempt: int):
            d = state.get("dir")
            if not d:
                return None
            try:
                from ..observability import doctor

                return doctor.diagnose([d])
            except Exception:
                return None

        def resume_fn():
            try:
                if self.elastic and state["preempted"]:
                    return self._shrink_for(spec, state)
                if spec.resume_dir:
                    from ..resilience.ckpt import CheckpointManager

                    info = CheckpointManager(
                        spec.resume_dir, world=state["world"]
                    ).latest_valid(world=state["world"])
                    return None if info is None else info.step
            except Exception as exc:
                self._log(
                    f"job {spec.id}: checkpoint scan failed: {exc!r}"
                )
            return None

        def extra_fn(attempt: int) -> Dict[str, Any]:
            rec: Dict[str, Any] = {
                "job": spec.id, "tenant": spec.tenant,
                "world": state["world_ran"],
            }
            if state["preempted"]:
                rec["preempted_ranks"] = list(state["preempted"])
            transition = state["transition"]
            if transition is not None:
                rec["next_world"] = transition["next_world"]
                src = transition.get("resharded_from")
                if src:
                    rec["resharded_from_step"] = src["step"]
                    rec["resharded_from_world"] = src["world"]
                state["transition"] = None
            if state["blocked"]:
                rec["elastic_blocked"] = state["blocked"]
            return rec

        def abort_fn(attempt: int) -> Optional[str]:
            # the pool's two-strikes rule: a job that keeps wedging
            # workers is poisoned — retrying it would degrade the
            # pool, so the remaining budget is vetoed. The spool-wide
            # verdict counts too: a peer server's strikes and ours
            # accumulate against the same job.
            if self._pool is not None and self._pool.poisoned(spec.id):
                return "poisoned"
            if self.spool.poisoned(spec.id):
                return "poisoned"
            return None

        sup = Supervisor(
            run_fn,
            policy=RetryPolicy(
                retries=spec.retries, backoff_s=spec.backoff_s
            ),
            diagnose_fn=diagnose_fn,
            resume_fn=resume_fn,
            extra_fn=extra_fn,
            abort_fn=abort_fn,
            span_fn=lambda name, s0, s1, **f: self._job_span(
                spec, name, s0, s1, **f
            ),
            audit_path=self.spool.audit_path,
            log=self._log,
        )
        t_run = time.time()
        self._job_span(spec, "dispatch", t_gate, t_run, world=world)
        rc = sup.run(resume0)
        t_run_end = time.time()
        self._job_span(
            spec, "run", t_run, t_run_end,
            attempts=len(sup.attempts), exit_code=rc,
            world=state["world_ran"],
        )
        run_s = time.time() - t0
        last = sup.attempts[-1] if sup.attempts else {}
        common = dict(
            world=state["world_ran"],
            attempts=len(sup.attempts),
            queue_wait_s=round(wait_s, 6),
            run_s=round(run_s, 6),
        )
        if rc == 0:
            if not self._finish(spec, "completed", **common):
                return "fenced"
            self.spool.audit(
                "completed", job=spec.id, tenant=spec.tenant, **common
            )
            self._job_span(
                spec, "result", t_run_end, time.time(),
                outcome="completed",
            )
            return "completed"
        if (
            self._pool is not None and self._pool.poisoned(spec.id)
        ) or self.spool.poisoned(spec.id):
            # however the last attempt's exit classified, the final
            # word on a poisoned job is "poisoned"
            reason = "poisoned"
        else:
            reason = state["blocked"] or last.get(
                "reason", "exit_nonzero"
            )
        if not self._finish(
            spec, "failed", exit_code=rc, klass=last.get("klass"),
            reason=reason, **common,
        ):
            return "fenced"
        self.spool.audit(
            "failed", job=spec.id, tenant=spec.tenant, exit_code=rc,
            klass=last.get("klass"), reason=reason, **common,
        )
        self._job_span(
            spec, "result", t_run_end, time.time(),
            outcome="failed", reason=reason,
        )
        return "failed"

    # -- the loop ------------------------------------------------------

    def serve(self) -> int:
        """Drain the queue until told to stop. Exits 0 after a drain
        (or ``max_jobs`` / ``idle_exit_s`` bound, for harnesses);
        exits 1 when capacity fell below ``min_ranks`` — the mesh can
        no longer honestly serve."""
        self.spool.audit(
            "serve_start", world=self.capacity,
            capacity=self.spool.capacity, pid=os.getpid(),
            elastic=self.elastic, verify=self.verify,
            server=self.server_id,
            warm_pool=(self._pool.size if self._pool is not None
                       else None),
        )
        self._log(
            f"serving from {self.spool.root} as {self.server_id} at "
            f"world {self.capacity} (queue capacity "
            f"{self.spool.capacity}"
            + (", elastic" if self.elastic else "")
            + (", verify" if self.verify else "")
            + (f", warm pool of {self._pool.size}"
               if self._pool is not None else "")
            + ")"
        )
        self._start_metrics()
        self._register()
        if self.fastpath:
            try:
                return self._serve_fastpath()
            finally:
                self._deregister()
                self._stop_metrics()
        if self._pool is not None:
            try:
                return self._serve_concurrent()
            finally:
                self._deregister()
                self._stop_metrics()
        idle_since = time.monotonic()
        rc = 0
        try:
            while True:
                prof = _profile.active
                t_iter = prof.t() if prof is not None else 0.0
                self._federation_tick()
                if (
                    self.max_jobs is not None
                    and self.jobs_served >= self.max_jobs
                ):
                    self._log(f"served {self.jobs_served} job(s); done")
                    break
                t_scan = prof.t() if prof is not None else 0.0
                pending = self.spool.pending()
                if prof is not None:
                    prof.phase(
                        "loop.scan", t_scan, server=self.server_id,
                        depth=len(pending),
                    )
                spec = self.scheduler.pick(pending)
                if spec is None:
                    if self.spool.draining():
                        self.spool.audit(
                            "drained", jobs=self.jobs_served,
                            world=self.capacity,
                        )
                        self._log(
                            "drained: queue empty after "
                            f"{self.jobs_served} job(s); exiting"
                        )
                        break
                    if (
                        self.idle_exit_s is not None
                        and time.monotonic() - idle_since
                        > self.idle_exit_s
                    ):
                        self._log("idle bound reached; exiting")
                        break
                    self._write_metrics()
                    if prof is not None:
                        # a wasted wakeup: woke, scanned, found nothing
                        prof.phase(
                            "loop.wakeup", t_iter,
                            server=self.server_id, useful=False,
                        )
                    time.sleep(self.poll_s)
                    continue
                idle_since = time.monotonic()
                claimed = self.spool.claim(spec, server=self.server_id)
                if claimed is None:
                    # a peer server won the rename: put the tenant's
                    # turn back so losing a race costs no fairness
                    self.scheduler.revert()
                    continue
                if prof is not None:
                    prof.phase(
                        "loop.wakeup", t_iter, server=self.server_id,
                        useful=True, job=claimed.id,
                    )
                self.run_job(claimed)
                self.jobs_served += 1
                self._write_metrics()
                if self.capacity_lost:
                    self._log(
                        "capacity below --min-ranks; cannot keep "
                        "serving"
                    )
                    rc = 1
                    break
        except KeyboardInterrupt:
            self._log("interrupted; exiting")
            rc = 130
        finally:
            self._deregister()
            self._write_metrics()
            self._stop_metrics()
        return rc

    # -- the warm-pool loop: concurrent jobs on disjoint sub-meshes ----

    def _serve_concurrent(self) -> int:
        """The serve loop when a resident pool is armed. Claimed jobs
        run in their own threads (each still under its own per-job
        Supervisor — the fault-domain contract is unchanged) so that
        several jobs can occupy disjoint sub-meshes of the pool at
        once; the head of the queue is never skipped (a job that does
        not fit yet blocks later jobs — FIFO fairness over packing
        greed)."""
        pool = self._pool
        running: Dict[str, threading.Thread] = {}
        idle_since = time.monotonic()
        rc = 0
        try:
            while True:
                prof = _profile.active
                t_iter = prof.t() if prof is not None else 0.0
                self._federation_tick()
                # one pool-doctor pass per loop turn: reap worker
                # exits, enforce heartbeat deadlines, flip started
                # workers idle (the doctor thread does this too when
                # armed; harnesses without it stay deterministic)
                try:
                    pool.check()
                except Exception:
                    pass
                # reap finished job threads
                done = [j for j, t in running.items()
                        if not t.is_alive()]
                for j in done:
                    running.pop(j).join()
                    self.jobs_served += 1
                    self._write_metrics()
                if self.capacity_lost and not running:
                    self._log(
                        "capacity below --min-ranks; cannot keep "
                        "serving"
                    )
                    rc = 1
                    break
                if (
                    self.max_jobs is not None
                    and self.jobs_served + len(running) >= self.max_jobs
                ):
                    if running:
                        time.sleep(self.poll_s)
                        continue
                    self._log(f"served {self.jobs_served} job(s); done")
                    break
                t_scan = prof.t() if prof is not None else 0.0
                pending = self.spool.pending()
                if prof is not None:
                    prof.phase(
                        "loop.scan", t_scan, server=self.server_id,
                        depth=len(pending),
                    )
                spec = self.scheduler.pick(pending)
                if spec is None:
                    if not running:
                        if self.spool.draining():
                            self.spool.audit(
                                "drained", jobs=self.jobs_served,
                                world=self.capacity,
                            )
                            self._log(
                                "drained: queue empty after "
                                f"{self.jobs_served} job(s); exiting"
                            )
                            break
                        if (
                            self.idle_exit_s is not None
                            and time.monotonic() - idle_since
                            > self.idle_exit_s
                        ):
                            self._log("idle bound reached; exiting")
                            break
                        self._write_metrics()
                    if prof is not None:
                        prof.phase(
                            "loop.wakeup", t_iter,
                            server=self.server_id, useful=False,
                        )
                    time.sleep(self.poll_s)
                    continue
                idle_since = time.monotonic()
                world = min(spec.nproc, max(self.capacity, 1))
                if pool.idle_count() < world:
                    # head-of-line job does not fit yet: wait for a
                    # sub-mesh, don't leapfrog it
                    time.sleep(self.poll_s)
                    continue
                claimed = self.spool.claim(spec, server=self.server_id)
                if claimed is None:
                    self.scheduler.revert()
                    continue  # a peer server won the rename
                if prof is not None:
                    prof.phase(
                        "loop.wakeup", t_iter, server=self.server_id,
                        useful=True, job=claimed.id,
                    )
                t = threading.Thread(
                    target=self.run_job, args=(claimed,),
                    name=f"m4t-job-{claimed.id}",
                )
                t.start()
                running[claimed.id] = t
        except KeyboardInterrupt:
            self._log("interrupted; exiting")
            rc = 130
        finally:
            for t in running.values():
                t.join(timeout=10.0)
            self._write_metrics()
        return rc

    # -- the event-driven loop: wake wires, batched claims, coalescing,
    #    group commit (serving/dispatch.py; opt-in via fastpath=) ------

    def _serve_fastpath(self) -> int:
        """The serve loop with the poll/fsync/scan tax removed: idle
        waits block on a wake wire (bounded by ``poll_s`` — the
        retained poll is the lost-wakeup recovery), the scheduler
        picks a fair *batch* leased in one ``claim_batch``, same-shape
        jobs coalesce into one sub-mesh dispatch, and the batch's
        terminal records flush with one group-commit fsync. The spool
        stays the durable source of truth throughout; federation,
        fencing and poison semantics are exactly the classic loop's."""
        from . import dispatch as _dispatch

        prefer = (
            None if self.fastpath in (True, "auto", "1")
            else str(self.fastpath)
        )
        listener = _dispatch.open_listener(
            os.path.join(self.spool.root, "pending"),
            advertise_dir=self.spool.root,
            prefer=prefer,
        )
        stats = _dispatch.DispatchStats(wire=listener.wire)
        self._dispatch_stats = stats
        self.spool.audit(
            "dispatch_armed", server=self.server_id,
            wire=listener.wire, batch=self.batch,
            coalesce=self.coalesce,
        )
        self._log(
            f"event-driven dispatch armed (wire {listener.wire}, "
            f"batch <= {self.batch}"
            + (", coalescing" if self.coalesce else "")
            + ")"
        )
        stats.write(self.spool.root)
        idle_since = time.monotonic()
        rc = 0
        try:
            while True:
                prof = _profile.active
                t_iter = prof.t() if prof is not None else 0.0
                self._federation_tick()
                if self._pool is not None:
                    try:
                        self._pool.check()
                    except Exception:
                        pass
                if (
                    self.max_jobs is not None
                    and self.jobs_served >= self.max_jobs
                ):
                    self._log(f"served {self.jobs_served} job(s); done")
                    break
                t_scan = prof.t() if prof is not None else 0.0
                pending = self.spool.pending()
                if prof is not None:
                    prof.phase(
                        "loop.scan", t_scan, server=self.server_id,
                        depth=len(pending),
                    )
                k = self.batch
                if self.max_jobs is not None:
                    k = min(k, self.max_jobs - self.jobs_served)
                picked = self.scheduler.pick_batch(pending, k)
                if picked and self._pool is not None:
                    head_world = min(
                        picked[0].nproc, max(self.capacity, 1)
                    )
                    if self._pool.idle_count() < head_world:
                        # head-of-line job does not fit yet: wait for
                        # a sub-mesh, don't leapfrog it
                        time.sleep(self.poll_s)
                        continue
                if not picked:
                    if self.spool.draining():
                        self.spool.audit(
                            "drained", jobs=self.jobs_served,
                            world=self.capacity,
                        )
                        self._log(
                            "drained: queue empty after "
                            f"{self.jobs_served} job(s); exiting"
                        )
                        break
                    if (
                        self.idle_exit_s is not None
                        and time.monotonic() - idle_since
                        > self.idle_exit_s
                    ):
                        self._log("idle bound reached; exiting")
                        break
                    self._write_metrics()
                    if prof is not None:
                        prof.phase(
                            "loop.wakeup", t_iter,
                            server=self.server_id, useful=False,
                        )
                    events = listener.wait(self.poll_s)
                    if events:
                        stats.wakeup(listener.wire, len(events))
                        if prof is not None:
                            t_now = _profile.wall()
                            for ev in events:
                                sent = ev.get("t")
                                prof.phase(
                                    "wake_latency",
                                    dur_s=(
                                        max(0.0, t_now - float(sent))
                                        if sent is not None else 0.0
                                    ),
                                    job=ev.get("job"),
                                    wire=ev.get("wire", listener.wire),
                                )
                    continue
                idle_since = time.monotonic()
                won = self.spool.claim_batch(
                    picked, server=self.server_id
                )
                self.scheduler.commit_batch(won)
                if not won:
                    continue  # peers took the whole batch
                if prof is not None:
                    prof.phase(
                        "loop.wakeup", t_iter, server=self.server_id,
                        useful=True, batch=len(won),
                    )
                stats.batch(len(won))
                groups = (
                    _dispatch.coalesce(won) if self.coalesce
                    else [[w] for w in won]
                )
                buffer: List[Dict[str, Any]] = []
                self._finish_buffer = buffer
                try:
                    for group in groups:
                        stats.group(len(group))
                        if len(group) == 1:
                            self.run_job(group[0])
                        else:
                            self._run_coalesced(group)
                        self.jobs_served += len(group)
                finally:
                    self._finish_buffer = None
                stats.group_commit(self.spool.finish_batch(buffer))
                self._check_slo()
                self._write_metrics()
                stats.write(self.spool.root)
                if self.capacity_lost:
                    self._log(
                        "capacity below --min-ranks; cannot keep "
                        "serving"
                    )
                    rc = 1
                    break
        except KeyboardInterrupt:
            self._log("interrupted; exiting")
            rc = 130
        finally:
            self._finish_buffer = None
            try:
                listener.close()
            except Exception:
                pass
            stats.write(self.spool.root)
            self._write_metrics()
        return rc

    def _run_coalesced(self, group: List[JobSpec]) -> str:
        """Run one coalesced group: several same-fingerprint jobs
        (``dispatch.coalesce_key``) fused into a single sub-mesh
        dispatch, the way continuous-batching servers fuse requests.
        One world executes — the leader's spec, which is
        indistinguishable from every member's — while every member
        keeps its own id, trace, audits, span chain and terminal
        record. Member spans share boundary clock reads (queued ends,
        dispatch/run/result start and end on the same stamps), so each
        member's chain is gapless by construction; the additive
        ``coalesced``/``batch``/``leader`` fields mark the sharing for
        readers without changing any pinned schema on the classic
        path. Poisoned members are refused individually before the
        shared dispatch; fencing per member keeps every id terminal
        exactly once."""
        t0 = time.time()
        live: List[JobSpec] = []
        for spec in group:
            wait_s = max(0.0, t0 - (spec.submitted_t or t0))
            if self.spool.poisoned(spec.id):
                self._log(
                    f"job {spec.id}: refused — poisoned verdict on "
                    "the spool"
                )
                if self._finish(
                    spec, "failed", reason="poisoned", refused=True,
                    queue_wait_s=round(wait_s, 6),
                ):
                    self.spool.audit(
                        "failed", job=spec.id, tenant=spec.tenant,
                        reason="poisoned", refused=True,
                    )
                continue
            live.append(spec)
        if not live:
            return "failed"
        leader = live[0]
        world = min(leader.nproc, self.capacity)
        n = len(live)
        for spec in live:
            wait_s = max(0.0, t0 - (spec.submitted_t or t0))
            self.spool.audit(
                "admitted", job=spec.id, tenant=spec.tenant,
                world=world, requested_nproc=spec.nproc,
                queue_wait_s=round(wait_s, 6), trace=spec.trace,
                coalesced=True, batch=n, leader=leader.id,
            )
            self._job_span(
                spec, "queued", (spec.submitted_t or t0), t0,
                depth_wait_s=round(wait_s, 6), coalesced=True,
            )
        t_gate = t0
        if self.verify:
            # per-job verify opts a spec out of coalescing entirely
            # (coalesce_key), so only the server-wide gate runs here —
            # once, for the shared shape
            verified = self._verify_fn(leader, world)
            t_gate = time.time()
            for spec in live:
                self._job_span(
                    spec, "verify", t0, t_gate, world=world,
                    passed=verified, coalesced=True,
                )
            if not verified:
                for spec in live:
                    wait_s = max(0.0, t0 - (spec.submitted_t or t0))
                    if self._finish(
                        spec, "rejected", reason="verify_failed",
                        world=world, queue_wait_s=wait_s,
                    ):
                        self.spool.audit(
                            "rejected", job=spec.id,
                            tenant=spec.tenant,
                            reason="verify_failed", world=world,
                        )
                return "rejected"

        jobdir = self.spool.job_dir(leader.id)
        state: Dict[str, Any] = {
            "world": world, "world_ran": world, "preempted": [],
            "transition": None, "blocked": None, "dir": None,
        }

        def run_fn(attempt: int, resume_step: Optional[int]) -> int:
            if state["blocked"]:
                self._log(
                    f"job {leader.id}: attempt {attempt} not "
                    f"spawned: {state['blocked']}"
                )
                return 1
            d = os.path.join(jobdir, f"attempt{attempt:02d}")
            os.makedirs(d, exist_ok=True)
            state["dir"] = d
            state["world_ran"] = state["world"]
            self._log(
                f"job {leader.id}: attempt {attempt} "
                f"(world {state['world']}, coalesced x{n})"
            )
            rc, preempted = self._runner(
                leader, state["world"], d, attempt, resume_step
            )
            state["preempted"] = list(preempted or [])
            return rc

        def diagnose_fn(attempt: int):
            d = state.get("dir")
            if not d:
                return None
            try:
                from ..observability import doctor

                return doctor.diagnose([d])
            except Exception:
                return None

        def resume_fn():
            # coalescible specs carry no resume_dir by definition;
            # only the elastic shrink path can move the next attempt
            try:
                if self.elastic and state["preempted"]:
                    return self._shrink_for(leader, state)
            except Exception as exc:
                self._log(
                    f"job {leader.id}: elastic shrink failed: {exc!r}"
                )
            return None

        def extra_fn(attempt: int) -> Dict[str, Any]:
            rec: Dict[str, Any] = {
                "job": leader.id, "tenant": leader.tenant,
                "world": state["world_ran"], "coalesced": True,
                "batch": n,
            }
            if state["preempted"]:
                rec["preempted_ranks"] = list(state["preempted"])
            if state["blocked"]:
                rec["elastic_blocked"] = state["blocked"]
            return rec

        def abort_fn(attempt: int) -> Optional[str]:
            if (
                self._pool is not None
                and self._pool.poisoned(leader.id)
            ):
                return "poisoned"
            if self.spool.poisoned(leader.id):
                return "poisoned"
            return None

        sup = Supervisor(
            run_fn,
            policy=RetryPolicy(
                retries=leader.retries, backoff_s=leader.backoff_s
            ),
            diagnose_fn=diagnose_fn,
            resume_fn=resume_fn,
            extra_fn=extra_fn,
            abort_fn=abort_fn,
            span_fn=lambda name, s0, s1, **f: self._job_span(
                leader, name, s0, s1, **f
            ),
            audit_path=self.spool.audit_path,
            log=self._log,
        )
        t_run = time.time()
        for spec in live:
            self._job_span(
                spec, "dispatch", t_gate, t_run, world=world,
                coalesced=True, batch=n, leader=leader.id,
            )
        rc = sup.run(None)
        t_run_end = time.time()
        for spec in live:
            self._job_span(
                spec, "run", t_run, t_run_end,
                attempts=len(sup.attempts), exit_code=rc,
                world=state["world_ran"], coalesced=True,
            )
        run_s = time.time() - t0
        last = sup.attempts[-1] if sup.attempts else {}
        t_result = time.time()
        outcome = "completed" if rc == 0 else "failed"
        for spec in live:
            wait_s = max(0.0, t0 - (spec.submitted_t or t0))
            common = dict(
                world=state["world_ran"], attempts=len(sup.attempts),
                queue_wait_s=round(wait_s, 6), run_s=round(run_s, 6),
                coalesced=True, batch=n, leader=leader.id,
            )
            if rc == 0:
                if not self._finish(spec, "completed", **common):
                    continue  # fenced: this member's story moved on
                self.spool.audit(
                    "completed", job=spec.id, tenant=spec.tenant,
                    **common,
                )
                self._job_span(
                    spec, "result", t_run_end, t_result,
                    outcome="completed", coalesced=True,
                )
                continue
            if self.spool.poisoned(leader.id):
                reason = "poisoned"
            else:
                reason = state["blocked"] or last.get(
                    "reason", "exit_nonzero"
                )
            if not self._finish(
                spec, "failed", exit_code=rc, klass=last.get("klass"),
                reason=reason, **common,
            ):
                continue
            self.spool.audit(
                "failed", job=spec.id, tenant=spec.tenant,
                exit_code=rc, klass=last.get("klass"), reason=reason,
                **common,
            )
            self._job_span(
                spec, "result", t_run_end, t_result,
                outcome="failed", reason=reason, coalesced=True,
            )
        return outcome
