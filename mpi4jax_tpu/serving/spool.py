"""Filesystem job spool: durable submit, atomic claim, audited finish.

The serving plane's queue is a directory, on purpose. Submitters and
the serving supervisor are different processes (often different
shells, possibly different machines sharing a filesystem), and the
spool must survive any of them dying mid-operation:

- **Submit** writes a validated job spec to ``pending/`` through the
  ``ckpt.py`` tmp+rename idiom — a spec either exists whole or not at
  all; a submitter killed mid-write leaves only ``.tmp-*`` litter,
  swept on the next submit.
- **Claim** is a single ``os.replace`` of the spec from ``pending/``
  to ``running/`` — atomic on POSIX, so two servers racing for the
  same job cannot both win (the loser's rename raises and it moves
  on).
- **Finish** writes the final record (spec + outcome) to ``done/``
  and removes the ``running/`` entry, so every job is in exactly one
  of pending/running/done at any instant a scanner looks.
- **Backpressure is bounded and explicit**: a submit that would push
  the queue past the configured capacity is *rejected* with
  ``{"status": "rejected", "reason": "queue_full"}`` and a load-shed
  audit record — the queue can never grow without bound, and every
  shed job is on the record rather than silently dropped.
- **Drain** is a sentinel file: once requested, new submits are
  rejected (``reason: "draining"``) while the server finishes what is
  already queued and running, then exits.

Every transition appends to ``serving.jsonl`` (the JSONL event schema
the rest of the repo speaks — the doctor narrates it, the exporter
counts it), keyed by job id, so the audit accounts for every job ever
submitted: each id ends ``completed``, ``failed``, or ``rejected``.

Entry filenames are ``<20-digit submit time_ns>-<job id>.json``: the
lexicographic directory order *is* FIFO submit order, which is what
the fair scheduler's per-tenant queues are built from.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

JOB_SCHEMA = "m4t-job/1"
SPOOL_SCHEMA = "m4t-spool/1"

PENDING_DIR = "pending"
RUNNING_DIR = "running"
DONE_DIR = "done"
JOBS_DIR = "jobs"
AUDIT_NAME = "serving.jsonl"
CONFIG_NAME = "spool.json"
DRAIN_SENTINEL = "DRAIN"

#: default bounded-queue capacity (pending jobs) when the spool was
#: never configured; ``serve --queue-cap`` / ``Spool.configure`` pin it
DEFAULT_CAPACITY = 16

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")
_TRACE_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,127}$")
_ENTRY_RE = re.compile(r"^(\d{20})-(.+)\.json$")

#: job-spec fields accepted by :func:`parse_job`; anything else is a
#: typo caught at submit time, not a knob that silently does nothing
_JOB_FIELDS = frozenset({
    "schema", "id", "tenant", "cmd", "module", "nproc", "timeout_s",
    "retries", "backoff_s", "verify", "resume_dir", "fault_plan", "env",
    "submitted_t", "trace",
})


class JobSpecError(ValueError):
    """A job spec that cannot mean what was written."""


@dataclass
class JobSpec:
    """One validated job: what to run, at what size, under which
    tenant, with what per-job recovery budget."""

    id: str
    tenant: str = "default"
    cmd: Optional[List[str]] = None    # argv appended to `python`
    module: Optional[str] = None       # or: run a module (python -m)
    nproc: int = 1
    timeout_s: float = 0.0             # per-job deadline (0 = none)
    retries: int = 0                   # per-job RetryPolicy budget
    backoff_s: float = 0.5
    verify: bool = False               # per-job admission gate opt-in
    resume_dir: Optional[str] = None   # per-job CheckpointManager root
    fault_plan: Any = None             # chaos: per-job M4T_FAULT_PLAN
    env: Optional[Dict[str, str]] = None
    submitted_t: Optional[float] = None
    #: distributed trace id (additive ``m4t-job/1`` field): minted at
    #: submit when absent, exported to every rank / work item as
    #: ``M4T_TRACE_ID``, stamped on every span and audit record — the
    #: one key all of this job's telemetry joins on
    trace: Optional[str] = None
    #: spool entry filename (set by the spool, never serialized)
    entry: str = field(default="", compare=False)

    @property
    def target(self) -> str:
        """What ``analysis --verify`` should import: the module, or
        the first argv element (a script path)."""
        return self.module if self.module else (self.cmd or ["?"])[0]

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "schema": JOB_SCHEMA,
            "tenant": self.tenant,
            "nproc": self.nproc,
            "timeout_s": self.timeout_s,
            "retries": self.retries,
            "backoff_s": self.backoff_s,
            "verify": self.verify,
        }
        if self.id:
            out["id"] = self.id
        if self.cmd is not None:
            out["cmd"] = list(self.cmd)
        if self.module is not None:
            out["module"] = self.module
        if self.resume_dir is not None:
            out["resume_dir"] = self.resume_dir
        if self.fault_plan is not None:
            out["fault_plan"] = self.fault_plan
        if self.env:
            out["env"] = dict(self.env)
        if self.submitted_t is not None:
            out["submitted_t"] = self.submitted_t
        if self.trace is not None:
            out["trace"] = self.trace
        return out


def _want(obj: Dict[str, Any], key: str, default: Any) -> Any:
    value = obj.get(key, default)
    return default if value is None else value


def parse_job(obj: Any, *, job_id: Optional[str] = None) -> JobSpec:
    """Validate a decoded job spec (or JSON string) into a
    :class:`JobSpec`; raises :class:`JobSpecError` naming the field
    that is wrong, never a bare traceback."""
    if isinstance(obj, (str, bytes)):
        try:
            obj = json.loads(obj)
        except json.JSONDecodeError as e:
            raise JobSpecError(f"job spec is not valid JSON: {e}")
    if not isinstance(obj, dict):
        raise JobSpecError("job spec must be a JSON object")
    unknown = set(obj) - _JOB_FIELDS
    if unknown:
        raise JobSpecError(f"job spec: unknown field(s) {sorted(unknown)}")
    schema = obj.get("schema", JOB_SCHEMA)
    if schema != JOB_SCHEMA:
        raise JobSpecError(
            f"job spec: schema {schema!r} != {JOB_SCHEMA!r}"
        )
    jid = obj.get("id", job_id)
    if jid is not None and (
        not isinstance(jid, str) or not _ID_RE.match(jid)
    ):
        raise JobSpecError(
            f"job spec: id must match {_ID_RE.pattern} (got {jid!r})"
        )
    tenant = _want(obj, "tenant", "default")
    if not isinstance(tenant, str) or not _ID_RE.match(tenant):
        raise JobSpecError(
            f"job spec: tenant must match {_ID_RE.pattern} "
            f"(got {tenant!r})"
        )
    cmd = obj.get("cmd")
    module = obj.get("module")
    if (cmd is None) == (module is None):
        raise JobSpecError(
            "job spec: exactly one of 'cmd' (argv list) or 'module' "
            "is required"
        )
    if cmd is not None and (
        not isinstance(cmd, list) or not cmd
        or not all(isinstance(c, str) for c in cmd)
    ):
        raise JobSpecError(
            f"job spec: cmd must be a non-empty list of strings "
            f"(got {cmd!r})"
        )
    if module is not None and (
        not isinstance(module, str) or not module
    ):
        raise JobSpecError("job spec: module must be a non-empty string")
    nproc = _want(obj, "nproc", 1)
    if not isinstance(nproc, int) or isinstance(nproc, bool) or nproc < 1:
        raise JobSpecError(
            f"job spec: nproc must be a positive integer (got {nproc!r})"
        )
    timeout_s = _want(obj, "timeout_s", 0.0)
    if not isinstance(timeout_s, (int, float)) or isinstance(
        timeout_s, bool
    ) or timeout_s < 0:
        raise JobSpecError(
            f"job spec: timeout_s must be a non-negative number "
            f"(got {timeout_s!r})"
        )
    retries = _want(obj, "retries", 0)
    if not isinstance(retries, int) or isinstance(retries, bool) or (
        retries < 0
    ):
        raise JobSpecError(
            f"job spec: retries must be a non-negative integer "
            f"(got {retries!r})"
        )
    backoff_s = _want(obj, "backoff_s", 0.5)
    if not isinstance(backoff_s, (int, float)) or isinstance(
        backoff_s, bool
    ) or backoff_s < 0:
        raise JobSpecError(
            f"job spec: backoff_s must be a non-negative number "
            f"(got {backoff_s!r})"
        )
    verify = _want(obj, "verify", False)
    if not isinstance(verify, bool):
        raise JobSpecError("job spec: verify must be a boolean")
    resume_dir = obj.get("resume_dir")
    if resume_dir is not None and not isinstance(resume_dir, str):
        raise JobSpecError("job spec: resume_dir must be a string path")
    fault_plan = obj.get("fault_plan")
    if fault_plan is not None:
        # parse now so a chaos job with a typo'd plan is rejected at
        # submit, not after it claimed mesh time
        from ..resilience.faults import FaultPlan, FaultPlanError

        try:
            if isinstance(fault_plan, str):
                FaultPlan.load(fault_plan)
            else:
                FaultPlan.parse(fault_plan)
        except FaultPlanError as e:
            raise JobSpecError(f"job spec: fault_plan: {e}")
    env = obj.get("env")
    if env is not None and (
        not isinstance(env, dict)
        or not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in env.items()
        )
    ):
        raise JobSpecError(
            "job spec: env must map strings to strings"
        )
    submitted_t = obj.get("submitted_t")
    if submitted_t is not None and (
        not isinstance(submitted_t, (int, float))
        or isinstance(submitted_t, bool)
    ):
        raise JobSpecError("job spec: submitted_t must be a number")
    trace = obj.get("trace")
    if trace is not None and (
        not isinstance(trace, str) or not _TRACE_RE.match(trace)
    ):
        raise JobSpecError(
            f"job spec: trace must match {_TRACE_RE.pattern} "
            f"(got {trace!r})"
        )
    return JobSpec(
        id=jid or "",
        tenant=tenant,
        cmd=None if cmd is None else list(cmd),
        module=module,
        nproc=nproc,
        timeout_s=float(timeout_s),
        retries=retries,
        backoff_s=float(backoff_s),
        verify=verify,
        resume_dir=resume_dir,
        fault_plan=fault_plan,
        env=None if env is None else dict(env),
        submitted_t=None if submitted_t is None else float(submitted_t),
        trace=trace,
    )


class Spool:
    """The on-disk queue. Safe for concurrent submitters and one (or
    more — claims are atomic) serving supervisors."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        for sub in (PENDING_DIR, RUNNING_DIR, DONE_DIR, JOBS_DIR):
            os.makedirs(os.path.join(self.root, sub), exist_ok=True)
        self.audit_path = os.path.join(self.root, AUDIT_NAME)
        # the warm pool's serve loop audits from concurrent job
        # threads; one writer at a time keeps lines whole
        self._audit_lock = threading.Lock()

    # -- audit --------------------------------------------------------

    def audit(self, event: str, **fields: Any) -> None:
        """Append one ``kind="serving"`` record to ``serving.jsonl``.
        Best-effort: auditing must never mask the outcome it records."""
        from ..observability import events

        try:
            with self._audit_lock:
                events.EventLog(self.audit_path).append(
                    events.event("serving", event=event, t=time.time(),
                                 **fields)
                )
        except OSError:
            pass

    def audit_records(self) -> List[Dict[str, Any]]:
        from ..observability import events

        try:
            return [
                r for r in events.iter_records(self.audit_path)
                if r.get("kind") == "serving"
            ]
        except OSError:
            return []

    # -- spans ---------------------------------------------------------

    def span(
        self,
        name: str,
        *,
        job: str,
        t0: float,
        t1: float,
        trace: Optional[str] = None,
        tenant: Optional[str] = None,
        **fields: Any,
    ) -> None:
        """Append one ``kind="span"`` lifecycle record
        (``observability/spans.py``, schema ``m4t-span/1``) to
        ``serving.jsonl``. Same best-effort contract as :meth:`audit`:
        the queue must keep serving even when its trace cannot be
        written."""
        from ..observability import events, spans as _spans

        try:
            with self._audit_lock:
                events.EventLog(self.audit_path).append(
                    _spans.span_record(
                        name, job=job, t0=t0, t1=t1, trace=trace,
                        tenant=tenant, **fields,
                    )
                )
        except OSError:
            pass

    def span_records(self) -> List[Dict[str, Any]]:
        from ..observability import events

        try:
            return [
                r for r in events.iter_records(self.audit_path)
                if r.get("kind") == "span"
            ]
        except OSError:
            return []

    # -- capacity / drain ---------------------------------------------

    def configure(self, capacity: int) -> None:
        """Pin the bounded-queue capacity (atomic tmp+rename)."""
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError("spool capacity must be >= 1")
        path = os.path.join(self.root, CONFIG_NAME)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({
                "schema": SPOOL_SCHEMA, "capacity": capacity,
                "t": time.time(),
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    @property
    def capacity(self) -> int:
        try:
            with open(os.path.join(self.root, CONFIG_NAME)) as f:
                cap = json.load(f).get("capacity")
            return int(cap) if cap else DEFAULT_CAPACITY
        except (OSError, json.JSONDecodeError, TypeError, ValueError):
            return DEFAULT_CAPACITY

    def request_drain(self, note: str = "") -> None:
        path = os.path.join(self.root, DRAIN_SENTINEL)
        if not os.path.exists(path):
            with open(path, "w") as f:
                f.write(note or "drain requested\n")
            self.audit("drain_requested", note=note)

    def draining(self) -> bool:
        return os.path.exists(os.path.join(self.root, DRAIN_SENTINEL))

    # -- paths --------------------------------------------------------

    def _dir(self, sub: str) -> str:
        return os.path.join(self.root, sub)

    def job_dir(self, job_id: str) -> str:
        d = os.path.join(self.root, JOBS_DIR, job_id)
        os.makedirs(d, exist_ok=True)
        return d

    def _entries(self, sub: str) -> List[str]:
        try:
            names = os.listdir(self._dir(sub))
        except OSError:
            return []
        return sorted(n for n in names if _ENTRY_RE.match(n))

    def _known_ids(self) -> set:
        ids = set()
        for sub in (PENDING_DIR, RUNNING_DIR, DONE_DIR):
            for name in self._entries(sub):
                m = _ENTRY_RE.match(name)
                if m:
                    ids.add(m.group(2))
        return ids

    # -- submit -------------------------------------------------------

    def _sweep_tmp(self, sub: str) -> None:
        d = self._dir(sub)
        try:
            names = os.listdir(d)
        except OSError:
            return
        for name in names:
            if name.startswith(".tmp-"):
                try:
                    os.unlink(os.path.join(d, name))
                except OSError:
                    pass

    def submit(self, obj: Any) -> Dict[str, Any]:
        """Validate and enqueue one job. Returns a response dict::

            {"job": <id>, "status": "queued"}
            {"job": <id>, "status": "rejected", "reason": ...}

        Overload (``queue_full``), drain (``draining``) and duplicate
        ids (``duplicate_id``) are *rejections* — explicit, audited
        load-shed, never silent drops or unbounded queue growth. A
        spec that does not validate raises :class:`JobSpecError`
        instead (there may be no id to account for)."""
        spec = parse_job(obj)
        now = time.time()
        t_ns = time.time_ns()
        if not spec.id:
            spec.id = f"job-{t_ns:x}-{os.getpid() % 0xFFFF:04x}"
        if not spec.trace:
            # the trace id is born here, at admission to the system:
            # everything downstream (spans, rank env, emission stamps)
            # inherits it rather than minting its own
            spec.trace = f"tr-{t_ns:x}-{os.getpid() % 0xFFFF:04x}"
        spec.submitted_t = now
        if self.draining():
            self.audit(
                "rejected", job=spec.id, tenant=spec.tenant,
                reason="draining",
            )
            return {
                "job": spec.id, "status": "rejected",
                "reason": "draining",
            }
        depth = len(self._entries(PENDING_DIR))
        cap = self.capacity
        if depth >= cap:
            # the load-shed record: who was shed, at what depth,
            # against what cap — overload is routine, not invisible
            self.audit(
                "rejected", job=spec.id, tenant=spec.tenant,
                reason="queue_full", depth=depth, capacity=cap,
            )
            return {
                "job": spec.id, "status": "rejected",
                "reason": "queue_full", "depth": depth, "capacity": cap,
            }
        if spec.id in self._known_ids():
            self.audit(
                "rejected", job=spec.id, tenant=spec.tenant,
                reason="duplicate_id",
            )
            return {
                "job": spec.id, "status": "rejected",
                "reason": "duplicate_id",
            }
        self._sweep_tmp(PENDING_DIR)
        entry = f"{t_ns:020d}-{spec.id}.json"
        spec.entry = entry
        final = os.path.join(self._dir(PENDING_DIR), entry)
        tmp = os.path.join(self._dir(PENDING_DIR), f".tmp-{entry}")
        with open(tmp, "w") as f:
            json.dump(spec.to_json(), f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        self.audit(
            "submitted", job=spec.id, tenant=spec.tenant,
            nproc=spec.nproc, depth=depth + 1, trace=spec.trace,
        )
        return {"job": spec.id, "status": "queued", "trace": spec.trace}

    # -- scanning -----------------------------------------------------

    def _load_entry(self, sub: str, name: str) -> Optional[JobSpec]:
        try:
            with open(os.path.join(self._dir(sub), name)) as f:
                obj = json.load(f)
            spec = parse_job(obj)
        except (OSError, json.JSONDecodeError, JobSpecError):
            return None  # claimed by a peer mid-read, or torn by hand
        spec.entry = name
        return spec

    def pending(self) -> List[JobSpec]:
        """Queued jobs in FIFO submit order (entries that vanish
        mid-scan were claimed by a peer — skipped, not fatal)."""
        out = []
        for name in self._entries(PENDING_DIR):
            spec = self._load_entry(PENDING_DIR, name)
            if spec is not None:
                out.append(spec)
        return out

    def running(self) -> List[JobSpec]:
        out = []
        for name in self._entries(RUNNING_DIR):
            spec = self._load_entry(RUNNING_DIR, name)
            if spec is not None:
                out.append(spec)
        return out

    def done(self) -> List[Dict[str, Any]]:
        """Finished job records (spec + outcome fields), oldest first."""
        out = []
        for name in self._entries(DONE_DIR):
            try:
                with open(os.path.join(self._dir(DONE_DIR), name)) as f:
                    out.append(json.load(f))
            except (OSError, json.JSONDecodeError):
                continue
        return out

    def depth(self) -> int:
        return len(self._entries(PENDING_DIR))

    # -- claim / finish -----------------------------------------------

    def claim(self, spec: JobSpec) -> Optional[JobSpec]:
        """Atomically move ``spec`` from pending to running; None if a
        peer won the race (its rename already consumed the entry)."""
        src = os.path.join(self._dir(PENDING_DIR), spec.entry)
        dst = os.path.join(self._dir(RUNNING_DIR), spec.entry)
        try:
            os.replace(src, dst)
        except OSError:
            return None
        self.audit("claimed", job=spec.id, tenant=spec.tenant)
        return spec

    def finish(self, spec: JobSpec, outcome: str, **extra: Any) -> None:
        """Record the final outcome (``completed`` / ``failed`` /
        ``rejected``) in ``done/`` and clear the running entry."""
        record = dict(spec.to_json())
        record.update(outcome=outcome, finished_t=time.time(), **extra)
        final = os.path.join(self._dir(DONE_DIR), spec.entry)
        tmp = os.path.join(self._dir(DONE_DIR), f".tmp-{spec.entry}")
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        try:
            os.unlink(os.path.join(self._dir(RUNNING_DIR), spec.entry))
        except OSError:
            pass

    # -- status -------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        done = self.done()
        outcomes: Dict[str, int] = {}
        for rec in done:
            key = str(rec.get("outcome", "?"))
            outcomes[key] = outcomes.get(key, 0) + 1
        return {
            "root": self.root,
            "capacity": self.capacity,
            "draining": self.draining(),
            "depth": self.depth(),
            "pending": [
                {"job": s.id, "tenant": s.tenant, "nproc": s.nproc}
                for s in self.pending()
            ],
            "running": [
                {"job": s.id, "tenant": s.tenant, "nproc": s.nproc}
                for s in self.running()
            ],
            "done": [
                {
                    "job": rec.get("id"),
                    "tenant": rec.get("tenant"),
                    "outcome": rec.get("outcome"),
                }
                for rec in done
            ],
            "outcomes": outcomes,
        }
