"""Filesystem job spool: durable submit, atomic claim, audited finish.

The serving plane's queue is a directory, on purpose. Submitters and
the serving supervisor are different processes (often different
shells, possibly different machines sharing a filesystem), and the
spool must survive any of them dying mid-operation:

- **Submit** writes a validated job spec to ``pending/`` through the
  ``ckpt.py`` tmp+rename idiom — a spec either exists whole or not at
  all; a submitter killed mid-write leaves only ``.tmp-*`` litter,
  swept on the next submit.
- **Claim** is a single ``os.replace`` of the spec from ``pending/``
  to ``running/`` — atomic on POSIX, so two servers racing for the
  same job cannot both win (the loser's rename raises and it moves
  on).
- **Finish** writes the final record (spec + outcome) to ``done/``
  and removes the ``running/`` entry, so every job is in exactly one
  of pending/running/done at any instant a scanner looks.
- **Backpressure is bounded and explicit**: a submit that would push
  the queue past the configured capacity is *rejected* with
  ``{"status": "rejected", "reason": "queue_full"}`` and a load-shed
  audit record — the queue can never grow without bound, and every
  shed job is on the record rather than silently dropped.
- **Drain** is a sentinel file: once requested, new submits are
  rejected (``reason: "draining"``) while the server finishes what is
  already queued and running, then exits.

Every transition appends to ``serving.jsonl`` (the JSONL event schema
the rest of the repo speaks — the doctor narrates it, the exporter
counts it), keyed by job id, so the audit accounts for every job ever
submitted: each id ends ``completed``, ``failed``, or ``rejected``.

Entry filenames are ``<20-digit submit time_ns>-<job id>.json``: the
lexicographic directory order *is* FIFO submit order, which is what
the fair scheduler's per-tenant queues are built from.

**Federation** (several servers draining one spool) adds three pieces
on top of the same primitives, all optional — a spool never touched by
a federated server is byte-identical to the single-server layout:

- **Server registry + leases**: each serving loop registers under a
  unique ``server_id`` (``servers/<id>.json``, tmp+fsync+rename) and
  renews a heartbeat lease. A claim made on behalf of a server renames
  the entry to ``running/<entry>@<server_id>@<epoch>`` so every
  running entry names its owner and claim epoch (``@`` cannot appear
  in an id, so the suffix is unambiguous).
- **Orphan reclamation**: :meth:`Spool.reclaim` detects running
  entries whose owner lease expired (or whose owner vanished), and
  requeues them with ``reclaims``/``reclaimed_from`` provenance under
  a per-job cap — past the cap the job ends terminal
  ``failed: reclaim_exhausted`` instead of cycling forever.
- **Zombie fencing**: a federated :meth:`Spool.finish` must first win
  an atomic rename of *its own* claim instance
  (``@<server>@<epoch>``). A revived server whose job was reclaimed
  finds its claim gone, gets a ``fenced`` audit record, and writes no
  terminal record — every id still ends terminal exactly once.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import profile as _profile

JOB_SCHEMA = "m4t-job/1"
SPOOL_SCHEMA = "m4t-spool/1"

PENDING_DIR = "pending"
RUNNING_DIR = "running"
DONE_DIR = "done"
JOBS_DIR = "jobs"
SERVERS_DIR = "servers"
VERDICTS_DIR = "verdicts"
AUDIT_NAME = "serving.jsonl"
#: the group-commit journal: a batch of terminal records becomes
#: durable here with one fsync before the per-job done/ files are
#: materialized (Spool.finish_batch)
COMMIT_NAME = "commit.jsonl"
CONFIG_NAME = "spool.json"
DRAIN_SENTINEL = "DRAIN"

SERVER_SCHEMA = "m4t-server/1"
VERDICT_SCHEMA = "m4t-verdict/1"

#: default bounded-queue capacity (pending jobs) when the spool was
#: never configured; ``serve --queue-cap`` / ``Spool.configure`` pin it
DEFAULT_CAPACITY = 16

#: default heartbeat lease: a server silent this long is presumed dead
DEFAULT_LEASE_S = 15.0

#: default per-job reclaim cap: a job orphaned more times than this is
#: terminal ``failed: reclaim_exhausted``, never a hot potato
DEFAULT_MAX_RECLAIMS = 3

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")
_TRACE_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,127}$")
_ENTRY_RE = re.compile(r"^(\d{20})-(.+)\.json$")
#: running-dir entry: the pending name, optionally suffixed with the
#: claiming ``@<server_id>@<epoch>`` (ids may contain dots, never @)
_RUN_RE = re.compile(
    r"^(\d{20})-(.+)\.json(?:@([A-Za-z0-9][A-Za-z0-9._-]{0,63})@(\d+))?$"
)

#: job-spec fields accepted by :func:`parse_job`; anything else is a
#: typo caught at submit time, not a knob that silently does nothing
_JOB_FIELDS = frozenset({
    "schema", "id", "tenant", "cmd", "module", "nproc", "timeout_s",
    "retries", "backoff_s", "verify", "resume_dir", "fault_plan", "env",
    "submitted_t", "trace", "reclaims", "reclaimed_from",
})


class JobSpecError(ValueError):
    """A job spec that cannot mean what was written."""


@dataclass
class JobSpec:
    """One validated job: what to run, at what size, under which
    tenant, with what per-job recovery budget."""

    id: str
    tenant: str = "default"
    cmd: Optional[List[str]] = None    # argv appended to `python`
    module: Optional[str] = None       # or: run a module (python -m)
    nproc: int = 1
    timeout_s: float = 0.0             # per-job deadline (0 = none)
    retries: int = 0                   # per-job RetryPolicy budget
    backoff_s: float = 0.5
    verify: bool = False               # per-job admission gate opt-in
    resume_dir: Optional[str] = None   # per-job CheckpointManager root
    fault_plan: Any = None             # chaos: per-job M4T_FAULT_PLAN
    env: Optional[Dict[str, str]] = None
    submitted_t: Optional[float] = None
    #: distributed trace id (additive ``m4t-job/1`` field): minted at
    #: submit when absent, exported to every rank / work item as
    #: ``M4T_TRACE_ID``, stamped on every span and audit record — the
    #: one key all of this job's telemetry joins on
    trace: Optional[str] = None
    #: times this job was reclaimed from a dead server (additive
    #: ``m4t-job/1`` field: serialized only when non-zero, so a spool
    #: never touched by federation stays byte-identical)
    reclaims: int = 0
    #: reclaim provenance: one ``{"server", "epoch", "reason", ...}``
    #: dict per reclaim, oldest first
    reclaimed_from: Optional[List[Dict[str, Any]]] = None
    #: spool entry filename (set by the spool, never serialized)
    entry: str = field(default="", compare=False)
    #: claiming server id / claim epoch (set by a federated claim or
    #: a running-dir scan, never serialized)
    owner: Optional[str] = field(default=None, compare=False)
    epoch: Optional[int] = field(default=None, compare=False)

    @property
    def target(self) -> str:
        """What ``analysis --verify`` should import: the module, or
        the first argv element (a script path)."""
        return self.module if self.module else (self.cmd or ["?"])[0]

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "schema": JOB_SCHEMA,
            "tenant": self.tenant,
            "nproc": self.nproc,
            "timeout_s": self.timeout_s,
            "retries": self.retries,
            "backoff_s": self.backoff_s,
            "verify": self.verify,
        }
        if self.id:
            out["id"] = self.id
        if self.cmd is not None:
            out["cmd"] = list(self.cmd)
        if self.module is not None:
            out["module"] = self.module
        if self.resume_dir is not None:
            out["resume_dir"] = self.resume_dir
        if self.fault_plan is not None:
            out["fault_plan"] = self.fault_plan
        if self.env:
            out["env"] = dict(self.env)
        if self.submitted_t is not None:
            out["submitted_t"] = self.submitted_t
        if self.trace is not None:
            out["trace"] = self.trace
        if self.reclaims:
            out["reclaims"] = self.reclaims
        if self.reclaimed_from:
            out["reclaimed_from"] = [dict(r) for r in self.reclaimed_from]
        return out


def _want(obj: Dict[str, Any], key: str, default: Any) -> Any:
    value = obj.get(key, default)
    return default if value is None else value


def parse_job(obj: Any, *, job_id: Optional[str] = None) -> JobSpec:
    """Validate a decoded job spec (or JSON string) into a
    :class:`JobSpec`; raises :class:`JobSpecError` naming the field
    that is wrong, never a bare traceback."""
    if isinstance(obj, (str, bytes)):
        try:
            obj = json.loads(obj)
        except json.JSONDecodeError as e:
            raise JobSpecError(f"job spec is not valid JSON: {e}")
    if not isinstance(obj, dict):
        raise JobSpecError("job spec must be a JSON object")
    unknown = set(obj) - _JOB_FIELDS
    if unknown:
        raise JobSpecError(f"job spec: unknown field(s) {sorted(unknown)}")
    schema = obj.get("schema", JOB_SCHEMA)
    if schema != JOB_SCHEMA:
        raise JobSpecError(
            f"job spec: schema {schema!r} != {JOB_SCHEMA!r}"
        )
    jid = obj.get("id", job_id)
    if jid is not None and (
        not isinstance(jid, str) or not _ID_RE.match(jid)
    ):
        raise JobSpecError(
            f"job spec: id must match {_ID_RE.pattern} (got {jid!r})"
        )
    tenant = _want(obj, "tenant", "default")
    if not isinstance(tenant, str) or not _ID_RE.match(tenant):
        raise JobSpecError(
            f"job spec: tenant must match {_ID_RE.pattern} "
            f"(got {tenant!r})"
        )
    cmd = obj.get("cmd")
    module = obj.get("module")
    if (cmd is None) == (module is None):
        raise JobSpecError(
            "job spec: exactly one of 'cmd' (argv list) or 'module' "
            "is required"
        )
    if cmd is not None and (
        not isinstance(cmd, list) or not cmd
        or not all(isinstance(c, str) for c in cmd)
    ):
        raise JobSpecError(
            f"job spec: cmd must be a non-empty list of strings "
            f"(got {cmd!r})"
        )
    if module is not None and (
        not isinstance(module, str) or not module
    ):
        raise JobSpecError("job spec: module must be a non-empty string")
    nproc = _want(obj, "nproc", 1)
    if not isinstance(nproc, int) or isinstance(nproc, bool) or nproc < 1:
        raise JobSpecError(
            f"job spec: nproc must be a positive integer (got {nproc!r})"
        )
    timeout_s = _want(obj, "timeout_s", 0.0)
    if not isinstance(timeout_s, (int, float)) or isinstance(
        timeout_s, bool
    ) or timeout_s < 0:
        raise JobSpecError(
            f"job spec: timeout_s must be a non-negative number "
            f"(got {timeout_s!r})"
        )
    retries = _want(obj, "retries", 0)
    if not isinstance(retries, int) or isinstance(retries, bool) or (
        retries < 0
    ):
        raise JobSpecError(
            f"job spec: retries must be a non-negative integer "
            f"(got {retries!r})"
        )
    backoff_s = _want(obj, "backoff_s", 0.5)
    if not isinstance(backoff_s, (int, float)) or isinstance(
        backoff_s, bool
    ) or backoff_s < 0:
        raise JobSpecError(
            f"job spec: backoff_s must be a non-negative number "
            f"(got {backoff_s!r})"
        )
    verify = _want(obj, "verify", False)
    if not isinstance(verify, bool):
        raise JobSpecError("job spec: verify must be a boolean")
    resume_dir = obj.get("resume_dir")
    if resume_dir is not None and not isinstance(resume_dir, str):
        raise JobSpecError("job spec: resume_dir must be a string path")
    fault_plan = obj.get("fault_plan")
    if fault_plan is not None:
        # parse now so a chaos job with a typo'd plan is rejected at
        # submit, not after it claimed mesh time
        from ..resilience.faults import FaultPlan, FaultPlanError

        try:
            if isinstance(fault_plan, str):
                FaultPlan.load(fault_plan)
            else:
                FaultPlan.parse(fault_plan)
        except FaultPlanError as e:
            raise JobSpecError(f"job spec: fault_plan: {e}")
    env = obj.get("env")
    if env is not None and (
        not isinstance(env, dict)
        or not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in env.items()
        )
    ):
        raise JobSpecError(
            "job spec: env must map strings to strings"
        )
    submitted_t = obj.get("submitted_t")
    if submitted_t is not None and (
        not isinstance(submitted_t, (int, float))
        or isinstance(submitted_t, bool)
    ):
        raise JobSpecError("job spec: submitted_t must be a number")
    trace = obj.get("trace")
    if trace is not None and (
        not isinstance(trace, str) or not _TRACE_RE.match(trace)
    ):
        raise JobSpecError(
            f"job spec: trace must match {_TRACE_RE.pattern} "
            f"(got {trace!r})"
        )
    reclaims = _want(obj, "reclaims", 0)
    if not isinstance(reclaims, int) or isinstance(reclaims, bool) or (
        reclaims < 0
    ):
        raise JobSpecError(
            f"job spec: reclaims must be a non-negative integer "
            f"(got {reclaims!r})"
        )
    reclaimed_from = obj.get("reclaimed_from")
    if reclaimed_from is not None and (
        not isinstance(reclaimed_from, list)
        or not all(isinstance(r, dict) for r in reclaimed_from)
    ):
        raise JobSpecError(
            "job spec: reclaimed_from must be a list of objects"
        )
    return JobSpec(
        id=jid or "",
        tenant=tenant,
        cmd=None if cmd is None else list(cmd),
        module=module,
        nproc=nproc,
        timeout_s=float(timeout_s),
        retries=retries,
        backoff_s=float(backoff_s),
        verify=verify,
        resume_dir=resume_dir,
        fault_plan=fault_plan,
        env=None if env is None else dict(env),
        submitted_t=None if submitted_t is None else float(submitted_t),
        trace=trace,
        reclaims=reclaims,
        reclaimed_from=(
            None if reclaimed_from is None
            else [dict(r) for r in reclaimed_from]
        ),
    )


class Spool:
    """The on-disk queue. Safe for concurrent submitters and one (or
    more — claims are atomic) serving supervisors."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        for sub in (PENDING_DIR, RUNNING_DIR, DONE_DIR, JOBS_DIR,
                    SERVERS_DIR, VERDICTS_DIR):
            os.makedirs(os.path.join(self.root, sub), exist_ok=True)
        self.audit_path = os.path.join(self.root, AUDIT_NAME)
        # the warm pool's serve loop audits from concurrent job
        # threads; one writer at a time keeps lines whole
        self._audit_lock = threading.Lock()
        # control-plane micro-span profiling arms here when
        # M4T_CP_PROFILE is set; unarmed, every instrumented site
        # below pays one falsy check (serving/profile.py)
        _profile.arm_from_env(self.root)

    # -- audit --------------------------------------------------------

    def audit(self, event: str, **fields: Any) -> None:
        """Append one ``kind="serving"`` record to ``serving.jsonl``.
        Best-effort: auditing must never mask the outcome it records."""
        from ..observability import events

        try:
            with self._audit_lock:
                events.EventLog(self.audit_path).append(
                    events.event("serving", event=event, t=time.time(),
                                 **fields)
                )
        except OSError:
            pass

    def audit_many(
        self, records: List[Tuple[str, Dict[str, Any]]]
    ) -> None:
        """Append a batch of serving audit records in one lock
        acquisition and one file open — the group-commit shape for the
        event-driven loop's per-batch bookkeeping. Each entry is
        ``(event, fields)``; schema and best-effort contract are
        exactly :meth:`audit`'s."""
        if not records:
            return
        from ..observability import events

        try:
            lines = []
            for event, fields in records:
                rec = events.event(
                    "serving", event=event, t=time.time(), **fields
                )
                rec.setdefault("ts", events.utc_stamp())
                lines.append(json.dumps(rec, default=str))
            with self._audit_lock:
                with open(self.audit_path, "a") as f:
                    f.write("\n".join(lines) + "\n")
        except OSError:
            pass

    def audit_records(self) -> List[Dict[str, Any]]:
        from ..observability import events

        try:
            return [
                r for r in events.iter_records(self.audit_path)
                if r.get("kind") == "serving"
            ]
        except OSError:
            return []

    # -- spans ---------------------------------------------------------

    def span(
        self,
        name: str,
        *,
        job: str,
        t0: float,
        t1: float,
        trace: Optional[str] = None,
        tenant: Optional[str] = None,
        **fields: Any,
    ) -> None:
        """Append one ``kind="span"`` lifecycle record
        (``observability/spans.py``, schema ``m4t-span/1``) to
        ``serving.jsonl``. Same best-effort contract as :meth:`audit`:
        the queue must keep serving even when its trace cannot be
        written."""
        from ..observability import events, spans as _spans

        try:
            with self._audit_lock:
                events.EventLog(self.audit_path).append(
                    _spans.span_record(
                        name, job=job, t0=t0, t1=t1, trace=trace,
                        tenant=tenant, **fields,
                    )
                )
        except OSError:
            pass

    def span_records(self) -> List[Dict[str, Any]]:
        from ..observability import events

        try:
            return [
                r for r in events.iter_records(self.audit_path)
                if r.get("kind") == "span"
            ]
        except OSError:
            return []

    # -- capacity / drain ---------------------------------------------

    def configure(self, capacity: int) -> None:
        """Pin the bounded-queue capacity (atomic tmp+rename)."""
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError("spool capacity must be >= 1")
        path = os.path.join(self.root, CONFIG_NAME)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({
                "schema": SPOOL_SCHEMA, "capacity": capacity,
                "t": time.time(),
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    @property
    def capacity(self) -> int:
        try:
            with open(os.path.join(self.root, CONFIG_NAME)) as f:
                cap = json.load(f).get("capacity")
            return int(cap) if cap else DEFAULT_CAPACITY
        except (OSError, json.JSONDecodeError, TypeError, ValueError):
            return DEFAULT_CAPACITY

    def request_drain(self, note: str = "") -> None:
        path = os.path.join(self.root, DRAIN_SENTINEL)
        if not os.path.exists(path):
            with open(path, "w") as f:
                f.write(note or "drain requested\n")
            self.audit("drain_requested", note=note)

    def draining(self) -> bool:
        return os.path.exists(os.path.join(self.root, DRAIN_SENTINEL))

    # -- paths --------------------------------------------------------

    def _dir(self, sub: str) -> str:
        return os.path.join(self.root, sub)

    def job_dir(self, job_id: str) -> str:
        d = os.path.join(self.root, JOBS_DIR, job_id)
        os.makedirs(d, exist_ok=True)
        return d

    def _entries(self, sub: str) -> List[str]:
        try:
            names = os.listdir(self._dir(sub))
        except OSError:
            return []
        # running entries may carry an @server@epoch owner suffix;
        # pending/done entries never do
        regex = _RUN_RE if sub == RUNNING_DIR else _ENTRY_RE
        return sorted(n for n in names if regex.match(n))

    def _known_ids(self) -> set:
        ids = set()
        for sub in (PENDING_DIR, RUNNING_DIR, DONE_DIR):
            regex = _RUN_RE if sub == RUNNING_DIR else _ENTRY_RE
            for name in self._entries(sub):
                m = regex.match(name)
                if m:
                    ids.add(m.group(2))
        return ids

    # -- submit -------------------------------------------------------

    def _sweep_tmp(self, sub: str) -> None:
        d = self._dir(sub)
        try:
            names = os.listdir(d)
        except OSError:
            return
        for name in names:
            if name.startswith(".tmp-"):
                try:
                    os.unlink(os.path.join(d, name))
                except OSError:
                    pass

    def submit(self, obj: Any) -> Dict[str, Any]:
        """Validate and enqueue one job. Returns a response dict::

            {"job": <id>, "status": "queued"}
            {"job": <id>, "status": "rejected", "reason": ...}

        Overload (``queue_full``), drain (``draining``) and duplicate
        ids (``duplicate_id``) are *rejections* — explicit, audited
        load-shed, never silent drops or unbounded queue growth. A
        spec that does not validate raises :class:`JobSpecError`
        instead (there may be no id to account for)."""
        spec = parse_job(obj)
        now = time.time()
        t_ns = time.time_ns()
        if not spec.id:
            spec.id = f"job-{t_ns:x}-{os.getpid() % 0xFFFF:04x}"
        if not spec.trace:
            # the trace id is born here, at admission to the system:
            # everything downstream (spans, rank env, emission stamps)
            # inherits it rather than minting its own
            spec.trace = f"tr-{t_ns:x}-{os.getpid() % 0xFFFF:04x}"
        spec.submitted_t = now
        prof = _profile.active
        t_sub = prof.t() if prof is not None else 0.0
        if self.draining():
            self.audit(
                "rejected", job=spec.id, tenant=spec.tenant,
                reason="draining",
            )
            return {
                "job": spec.id, "status": "rejected",
                "reason": "draining",
            }
        t_scan = prof.t() if prof is not None else 0.0
        depth = len(self._entries(PENDING_DIR))
        cap = self.capacity
        if depth >= cap:
            # the load-shed record: who was shed, at what depth,
            # against what cap — overload is routine, not invisible
            self.audit(
                "rejected", job=spec.id, tenant=spec.tenant,
                reason="queue_full", depth=depth, capacity=cap,
            )
            return {
                "job": spec.id, "status": "rejected",
                "reason": "queue_full", "depth": depth, "capacity": cap,
            }
        if spec.id in self._known_ids():
            self.audit(
                "rejected", job=spec.id, tenant=spec.tenant,
                reason="duplicate_id",
            )
            return {
                "job": spec.id, "status": "rejected",
                "reason": "duplicate_id",
            }
        self._sweep_tmp(PENDING_DIR)
        if prof is not None:
            # n=5 listdirs: the depth count, the 3 known-id dirs, and
            # the tmp sweep — the submit path's whole scan budget
            prof.phase("submit.scan", t_scan, job=spec.id, n=5)
        entry = f"{t_ns:020d}-{spec.id}.json"
        spec.entry = entry
        final = os.path.join(self._dir(PENDING_DIR), entry)
        tmp = os.path.join(self._dir(PENDING_DIR), f".tmp-{entry}")
        t0 = prof.t() if prof is not None else 0.0
        with open(tmp, "w") as f:
            json.dump(spec.to_json(), f, indent=1)
            if prof is not None:
                prof.phase("submit.write", t0, job=spec.id)
                t0 = prof.t()
            f.flush()
            os.fsync(f.fileno())
        if prof is not None:
            prof.phase("submit.fsync", t0, job=spec.id)
            t0 = prof.t()
        os.replace(tmp, final)
        if prof is not None:
            prof.phase("submit.rename", t0, job=spec.id)
        # wake whoever listens on this spool's wire — strictly after
        # the rename (the event must never precede the durable fact),
        # strictly best-effort (one failed stat when nobody listens;
        # an event-driven server's retained poll recovers any loss)
        from . import dispatch as _dispatch

        _dispatch.notify(self.root, job=spec.id)
        self.audit(
            "submitted", job=spec.id, tenant=spec.tenant,
            nproc=spec.nproc, depth=depth + 1, trace=spec.trace,
        )
        if prof is not None:
            # the total's wall stamp is the submit-visible boundary
            # the queue-wait decomposition keys on
            prof.phase(
                "submit", t_sub, job=spec.id, tenant=spec.tenant,
                depth=depth + 1,
            )
        return {"job": spec.id, "status": "queued", "trace": spec.trace}

    # -- scanning -----------------------------------------------------

    def _load_entry(self, sub: str, name: str) -> Optional[JobSpec]:
        try:
            with open(os.path.join(self._dir(sub), name)) as f:
                obj = json.load(f)
            spec = parse_job(obj)
        except (OSError, json.JSONDecodeError, JobSpecError):
            return None  # claimed by a peer mid-read, or torn by hand
        spec.entry = name
        if sub == RUNNING_DIR:
            m = _RUN_RE.match(name)
            if m and m.group(3):
                spec.owner = m.group(3)
                spec.epoch = int(m.group(4))
        return spec

    def pending(self) -> List[JobSpec]:
        """Queued jobs in FIFO submit order (entries that vanish
        mid-scan were claimed by a peer — skipped, not fatal)."""
        out = []
        for name in self._entries(PENDING_DIR):
            spec = self._load_entry(PENDING_DIR, name)
            if spec is not None:
                out.append(spec)
        return out

    def running(self) -> List[JobSpec]:
        out = []
        for name in self._entries(RUNNING_DIR):
            spec = self._load_entry(RUNNING_DIR, name)
            if spec is not None:
                out.append(spec)
        return out

    def done(self) -> List[Dict[str, Any]]:
        """Finished job records (spec + outcome fields), oldest first."""
        out = []
        for name in self._entries(DONE_DIR):
            try:
                with open(os.path.join(self._dir(DONE_DIR), name)) as f:
                    out.append(json.load(f))
            except (OSError, json.JSONDecodeError):
                continue
        return out

    def depth(self) -> int:
        return len(self._entries(PENDING_DIR))

    # -- claim / finish -----------------------------------------------

    def claim(
        self, spec: JobSpec, *, server: Optional[str] = None
    ) -> Optional[JobSpec]:
        """Atomically move ``spec`` from pending to running; None if a
        peer won the race (its rename already consumed the entry).

        With ``server=`` the running entry is named
        ``<entry>@<server>@<epoch>`` (epoch = reclaims so far + 1) so
        the owner is on disk for the scavenger and the fence; without
        it, the single-server layout is unchanged."""
        src = os.path.join(self._dir(PENDING_DIR), spec.entry)
        dst_name = spec.entry
        epoch: Optional[int] = None
        if server is not None:
            if not _ID_RE.match(server):
                raise ValueError(
                    f"server id must match {_ID_RE.pattern} "
                    f"(got {server!r})"
                )
            epoch = int(spec.reclaims) + 1
            dst_name = f"{spec.entry}@{server}@{epoch}"
        dst = os.path.join(self._dir(RUNNING_DIR), dst_name)
        prof = _profile.active
        t0 = prof.t() if prof is not None else 0.0
        try:
            os.replace(src, dst)
        except OSError:
            if prof is not None:
                # the contention signal: this rename lost to a peer
                prof.phase(
                    "claim.lost", t0, job=spec.id, server=server,
                )
            return None
        spec.entry = dst_name
        spec.owner = server
        spec.epoch = epoch
        if server is None:
            self.audit("claimed", job=spec.id, tenant=spec.tenant)
        else:
            self.audit(
                "claimed", job=spec.id, tenant=spec.tenant,
                server=server, epoch=epoch,
            )
        if prof is not None:
            # rename + claim audit; the wall stamp is the claim
            # boundary the queue-wait decomposition keys on
            prof.phase(
                "claim", t0, job=spec.id, server=server, epoch=epoch,
            )
        return spec

    def claim_batch(
        self,
        specs: Any,
        *,
        server: Optional[str] = None,
    ) -> List[JobSpec]:
        """Lease up to K jobs in one batch under the same owner/epoch
        fencing as :meth:`claim`. ``specs`` is the scheduler-picked
        batch (``FairScheduler.pick_batch`` keeps tenant round-robin
        fairness across the batch boundary) or an int K, which leases
        the first K pending jobs FIFO.

        Each lease is still its own atomic pending->running rename —
        the exactly-once arbiter is unchanged, so racing servers
        partition a batch instead of duplicating it; entries lost to a
        peer are skipped. Returns the claimed specs in pick order.
        Armed, the whole batch is bracketed by one ``claim_batch``
        cp record (``k=``/``won=``) while the per-job ``claim`` /
        ``claim.lost`` records keep the rename accounting and the
        queue-wait decomposition exact."""
        if isinstance(specs, int):
            specs = self.pending()[: max(0, specs)]
        specs = list(specs)
        prof = _profile.active
        t0 = prof.t() if prof is not None else 0.0
        won: List[JobSpec] = []
        for spec in specs:
            got = self.claim(spec, server=server)
            if got is not None:
                won.append(got)
        if prof is not None:
            prof.phase(
                "claim_batch", t0, k=len(specs), won=len(won),
                server=server,
            )
        return won

    @staticmethod
    def _entry_base(entry: str) -> str:
        """The pending/done filename for a (possibly owned) entry."""
        return entry.split("@", 1)[0]

    def _running_holder(self, job_id: str) -> Optional[Dict[str, Any]]:
        """Who currently holds ``job_id``'s running entry, if anyone."""
        for name in self._entries(RUNNING_DIR):
            m = _RUN_RE.match(name)
            if m and m.group(2) == job_id:
                return {
                    "server": m.group(3),
                    "epoch": int(m.group(4)) if m.group(4) else None,
                }
        return None

    def finish(
        self,
        spec: JobSpec,
        outcome: str,
        *,
        server: Optional[str] = None,
        epoch: Optional[int] = None,
        **extra: Any,
    ) -> bool:
        """Record the final outcome (``completed`` / ``failed`` /
        ``rejected``) in ``done/`` and clear the running entry.

        A federated finish (``server=``) must first *take* its own
        claim instance — an atomic rename of
        ``running/<base>@<server>@<epoch>`` to a private tombstone.
        If that rename fails the claim was superseded (the job was
        reclaimed while this server was wedged): the late terminal
        record is rejected, a ``fenced`` audit record names the zombie
        and the current holder, and the method returns False without
        writing anything. Returns True when the record landed."""
        prof = _profile.active
        t_fin = prof.t() if prof is not None else 0.0
        base = self._entry_base(spec.entry) if spec.entry else spec.entry
        token: Optional[str] = None
        if server is not None:
            if epoch is None:
                epoch = (
                    spec.epoch if spec.epoch is not None
                    else int(spec.reclaims) + 1
                )
            running = os.path.join(
                self._dir(RUNNING_DIR), f"{base}@{server}@{epoch}"
            )
            token = os.path.join(
                self.job_dir(spec.id), f".terminal@{server}@{epoch}"
            )
            t0 = prof.t() if prof is not None else 0.0
            try:
                os.replace(running, token)
            except OSError:
                # this claim instance no longer exists: reclaimed out
                # from under a zombie, or already finished — either
                # way the terminal story belongs to someone else now
                self.audit(
                    "fenced", job=spec.id, tenant=spec.tenant,
                    server=server, epoch=int(epoch),
                    outcome_rejected=outcome,
                    holder=self._running_holder(spec.id),
                )
                return False
            if prof is not None:
                prof.phase(
                    "finish.fence", t0, job=spec.id, server=server,
                )
        record = dict(spec.to_json())
        record.update(outcome=outcome, finished_t=time.time(), **extra)
        final = os.path.join(self._dir(DONE_DIR), base)
        tmp = os.path.join(self._dir(DONE_DIR), f".tmp-{base}")
        t0 = prof.t() if prof is not None else 0.0
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1, default=str)
            if prof is not None:
                prof.phase("finish.write", t0, job=spec.id)
                t0 = prof.t()
            f.flush()
            os.fsync(f.fileno())
        if prof is not None:
            prof.phase("finish.fsync", t0, job=spec.id)
            t0 = prof.t()
        os.replace(tmp, final)
        if prof is not None:
            prof.phase("finish.rename", t0, job=spec.id)
        if token is not None:
            try:
                os.unlink(token)
            except OSError:
                pass
        else:
            try:
                os.unlink(
                    os.path.join(self._dir(RUNNING_DIR), spec.entry)
                )
            except OSError:
                pass
        if prof is not None:
            prof.phase("finish", t_fin, job=spec.id, outcome=outcome)
        return True

    # -- group commit (the event-driven finish path) -------------------

    def fence(
        self,
        spec: JobSpec,
        outcome: str,
        *,
        server: Optional[str] = None,
        epoch: Optional[int] = None,
    ) -> Optional[str]:
        """Atomically take ``spec``'s claim instance ahead of a
        buffered group commit — the same exactly-once arbiter
        :meth:`finish` runs first, split out so the event-driven loop
        can fence *now* (audits and spans stay truthful) and flush the
        terminal records *later* in one :meth:`finish_batch` fsync.

        Returns the private tombstone path to hand to
        :meth:`finish_batch` (empty string for an unowned,
        single-server claim — there is nothing to take), or None when
        this claim epoch was superseded: the ``fenced`` audit record
        lands immediately and the caller must write nothing more for
        the job. A crash between a successful fence and the flush
        leaves the tombstone for :meth:`reclaim`'s interrupted-
        transition sweep — the job is requeued and still ends terminal
        exactly once."""
        if server is None:
            return ""
        if epoch is None:
            epoch = (
                spec.epoch if spec.epoch is not None
                else int(spec.reclaims) + 1
            )
        base = self._entry_base(spec.entry) if spec.entry else spec.entry
        running = os.path.join(
            self._dir(RUNNING_DIR), f"{base}@{server}@{epoch}"
        )
        token = os.path.join(
            self.job_dir(spec.id), f".terminal@{server}@{epoch}"
        )
        prof = _profile.active
        t0 = prof.t() if prof is not None else 0.0
        try:
            os.replace(running, token)
        except OSError:
            self.audit(
                "fenced", job=spec.id, tenant=spec.tenant,
                server=server, epoch=int(epoch),
                outcome_rejected=outcome,
                holder=self._running_holder(spec.id),
            )
            return None
        if prof is not None:
            prof.phase("finish.fence", t0, job=spec.id, server=server)
        return token

    def finish_batch(
        self, items: List[Dict[str, Any]],
    ) -> int:
        """Group commit: flush a batch of already-fenced terminal
        records with **one** fsync. Each item is
        ``{"spec", "outcome", "extra", "token"}`` where ``token`` came
        from :meth:`fence` ('""' for unowned claims).

        Durability order: (1) every record is appended to
        ``commit.jsonl`` and fsynced once — the commit point; (2) each
        ``done/`` record is then materialized tmp+rename *without* a
        per-file fsync (its bytes are already durable in the journal,
        and the rename is atomic so scanners never see a torn record);
        (3) tombstones / running entries are cleared. A process killed
        anywhere in between loses nothing: fenced-but-unflushed jobs
        are requeued by the interrupted-transition sweep and re-run to
        their single terminal record. Returns the number of records
        landed."""
        if not items:
            return 0
        prof = _profile.active
        now = time.time()
        batch: List[Dict[str, Any]] = []
        for item in items:
            spec = item["spec"]
            record = dict(spec.to_json())
            record.update(
                outcome=item["outcome"], finished_t=now,
                **(item.get("extra") or {}),
            )
            batch.append(record)
        # (1) the commit point: one append, one fsync for the batch
        journal_ok = True
        t0 = prof.t() if prof is not None else 0.0
        try:
            with open(os.path.join(self.root, COMMIT_NAME), "a") as f:
                for record in batch:
                    f.write(json.dumps(record, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            journal_ok = False
        if prof is not None and journal_ok:
            prof.phase("finish.fsync", t0, n=1, jobs=len(batch))
        landed = 0
        for item, record in zip(items, batch):
            spec = item["spec"]
            base = (
                self._entry_base(spec.entry) if spec.entry
                else spec.entry
            )
            final = os.path.join(self._dir(DONE_DIR), base)
            tmp = os.path.join(self._dir(DONE_DIR), f".tmp-{base}")
            t0 = prof.t() if prof is not None else 0.0
            try:
                with open(tmp, "w") as f:
                    json.dump(record, f, indent=1, default=str)
                    if not journal_ok:
                        # no journal to lean on: fall back to the
                        # per-record durability finish() provides
                        f.flush()
                        os.fsync(f.fileno())
                        if prof is not None:
                            prof.phase("finish.fsync", t0, job=spec.id)
                            t0 = prof.t()
                os.replace(tmp, final)
            except OSError:
                continue
            if prof is not None:
                prof.phase("finish.rename", t0, job=spec.id)
            token = item.get("token")
            try:
                if token:
                    os.unlink(token)
                elif spec.entry:
                    os.unlink(
                        os.path.join(self._dir(RUNNING_DIR), spec.entry)
                    )
            except OSError:
                pass
            landed += 1
            if prof is not None:
                prof.phase(
                    "finish", dur_s=0.0, job=spec.id,
                    outcome=item["outcome"], batched=True,
                )
        return landed

    # -- server registry / leases -------------------------------------

    def _server_path(self, server_id: str) -> str:
        return os.path.join(self.root, SERVERS_DIR, f"{server_id}.json")

    def _write_json_atomic(self, path: str, obj: Dict[str, Any]) -> None:
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def register_server(
        self,
        server_id: str,
        *,
        lease_s: float = DEFAULT_LEASE_S,
        now: Optional[float] = None,
        **meta: Any,
    ) -> Dict[str, Any]:
        """Register a serving loop (``servers/<id>.json``) and audit
        ``server_register``. ``now`` is injectable for tests."""
        if not _ID_RE.match(server_id):
            raise ValueError(
                f"server id must match {_ID_RE.pattern} "
                f"(got {server_id!r})"
            )
        t = time.time() if now is None else float(now)
        rec: Dict[str, Any] = {
            "schema": SERVER_SCHEMA, "id": server_id,
            "lease_s": float(lease_s), "started_t": t, "renewed_t": t,
            "pid": os.getpid(),
        }
        rec.update(meta)
        self._write_json_atomic(self._server_path(server_id), rec)
        self.audit(
            "server_register", server=server_id, lease_s=float(lease_s),
            **meta,
        )
        return rec

    def renew_lease(
        self, server_id: str, *, now: Optional[float] = None
    ) -> None:
        """Refresh the heartbeat. A server whose registry file was
        removed (scavenged as dead, operator cleanup) re-registers —
        its old claims are already forfeit, but its next ones count."""
        prof = _profile.active
        if prof is None:
            return self._renew_lease(server_id, now=now)
        t0 = prof.t()
        try:
            return self._renew_lease(server_id, now=now)
        finally:
            prof.phase("lease.renew", t0, server=server_id)

    def _renew_lease(
        self, server_id: str, *, now: Optional[float] = None
    ) -> None:
        t = time.time() if now is None else float(now)
        path = self._server_path(server_id)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            self.register_server(server_id, now=t)
            return
        rec["renewed_t"] = t
        self._write_json_atomic(path, rec)

    def deregister_server(
        self, server_id: str, **fields: Any
    ) -> None:
        """Clean shutdown: drop the lease file, audit ``server_stop``."""
        try:
            os.unlink(self._server_path(server_id))
        except OSError:
            pass
        self.audit("server_stop", server=server_id, **fields)

    def servers(self, *, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Registered servers, each with ``lease_age_s`` and ``alive``
        (lease not yet expired) computed against ``now``."""
        t = time.time() if now is None else float(now)
        out: List[Dict[str, Any]] = []
        d = os.path.join(self.root, SERVERS_DIR)
        try:
            names = sorted(os.listdir(d))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json") or name.startswith("."):
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    rec = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if not isinstance(rec, dict) or "id" not in rec:
                continue
            age = t - float(rec.get("renewed_t", 0.0))
            rec["lease_age_s"] = age
            rec["alive"] = age <= float(rec.get("lease_s", DEFAULT_LEASE_S))
            out.append(rec)
        return out

    # -- orphan reclamation -------------------------------------------

    def _requeue_or_exhaust(
        self,
        token: str,
        base: str,
        *,
        owner: Optional[str],
        epoch: Optional[int],
        reason: str,
        by: Optional[str],
        max_reclaims: int,
        now: float,
    ) -> Optional[Dict[str, Any]]:
        """Finish a reclaim transition: the claim instance has already
        been renamed to ``token`` (the atomic take), so this path owns
        the job. Requeue it with provenance, or — past the cap —
        write its terminal ``failed: reclaim_exhausted`` record."""
        try:
            with open(token) as f:
                spec = parse_job(json.load(f))
        except (OSError, json.JSONDecodeError, JobSpecError):
            # a torn spec cannot be requeued; leave the token for an
            # operator, but never crash the scavenger
            return None
        action: Dict[str, Any] = {
            "job": spec.id, "tenant": spec.tenant,
            "from_server": owner, "epoch": epoch, "reason": reason,
        }
        if spec.reclaims >= max_reclaims:
            rec = dict(spec.to_json())
            rec.update(
                outcome="failed", reason="reclaim_exhausted",
                finished_t=now,
            )
            self._write_json_atomic(
                os.path.join(self._dir(DONE_DIR), base), rec
            )
            try:
                os.unlink(token)
            except OSError:
                pass
            action["action"] = "exhausted"
            self.audit(
                "reclaim", job=spec.id, tenant=spec.tenant,
                from_server=owner, epoch=epoch, reason=reason,
                action="exhausted", by=by, reclaims=spec.reclaims,
            )
            self.audit(
                "failed", job=spec.id, tenant=spec.tenant,
                reason="reclaim_exhausted", reclaims=spec.reclaims,
            )
            return action
        spec.reclaims += 1
        prov = list(spec.reclaimed_from or [])
        prov.append({
            "server": owner, "epoch": epoch, "reason": reason,
            "by": by, "t": now,
        })
        spec.reclaimed_from = prov
        # requeue under the original entry name: the job keeps its
        # FIFO position (it already waited once)
        self._write_json_atomic(
            os.path.join(self._dir(PENDING_DIR), base), spec.to_json()
        )
        try:
            os.unlink(token)
        except OSError:
            pass
        action["action"] = "requeued"
        action["reclaims"] = spec.reclaims
        self.audit(
            "reclaim", job=spec.id, tenant=spec.tenant,
            from_server=owner, epoch=epoch, reason=reason,
            action="requeued", by=by, reclaims=spec.reclaims,
        )
        return action

    def reclaim(
        self,
        *,
        now: Optional[float] = None,
        by: Optional[str] = None,
        max_reclaims: int = DEFAULT_MAX_RECLAIMS,
        grace_s: float = 0.0,
    ) -> List[Dict[str, Any]]:
        """One scavenger pass: requeue running entries whose owner is
        dead (lease expired, or registry file gone), and sweep
        transition tokens left by a finisher/scavenger that crashed
        mid-transition. Returns a list of action dicts
        (``action: requeued | exhausted | swept``).

        The atomic take (rename of the claim instance to a private
        token) is the race arbiter: a zombie's own :meth:`finish` and
        a scavenger reclaiming the same claim cannot both win.
        Unowned (single-server era) running entries are never touched.
        ``by`` names the scavenging server so it skips its own claims.
        """
        prof = _profile.active
        if prof is None:
            return self._reclaim(
                now=now, by=by, max_reclaims=max_reclaims,
                grace_s=grace_s,
            )
        t0 = prof.t()
        actions = self._reclaim(
            now=now, by=by, max_reclaims=max_reclaims, grace_s=grace_s,
        )
        prof.phase("scavenge", t0, by=by, actions=len(actions))
        return actions

    def _reclaim(
        self,
        *,
        now: Optional[float] = None,
        by: Optional[str] = None,
        max_reclaims: int = DEFAULT_MAX_RECLAIMS,
        grace_s: float = 0.0,
    ) -> List[Dict[str, Any]]:
        t = time.time() if now is None else float(now)
        servers = {rec["id"]: rec for rec in self.servers(now=t)}
        actions: List[Dict[str, Any]] = []
        expired_audited: set = set()

        def owner_dead(owner: str) -> Optional[str]:
            rec = servers.get(owner)
            if rec is None:
                return "server_gone"
            age = float(rec["lease_age_s"])
            if age <= float(rec.get("lease_s", DEFAULT_LEASE_S)) + grace_s:
                return None
            if owner not in expired_audited:
                expired_audited.add(owner)
                self.audit(
                    "lease_expired", server=owner,
                    lease_age_s=round(age, 3), by=by,
                )
            return "lease_expired"

        for name in self._entries(RUNNING_DIR):
            m = _RUN_RE.match(name)
            if not m or not m.group(3):
                continue  # unowned: a single-server claim, not ours
            owner, epoch = m.group(3), int(m.group(4))
            if by is not None and owner == by:
                continue
            reason = owner_dead(owner)
            if reason is None:
                continue
            base = self._entry_base(name)
            job_id = m.group(2)
            token = os.path.join(
                self.job_dir(job_id), f".reclaim@{owner}@{epoch}"
            )
            try:
                os.replace(os.path.join(self._dir(RUNNING_DIR), name),
                           token)
            except OSError:
                continue  # lost the race (zombie finished, peer took it)
            act = self._requeue_or_exhaust(
                token, base, owner=owner, epoch=epoch, reason=reason,
                by=by, max_reclaims=max_reclaims, now=t,
            )
            if act:
                actions.append(act)

        # interrupted transitions: a finisher or scavenger that died
        # after the atomic take but before its done/pending write left
        # a token behind; resolve it once its creator's lease is gone
        jobs_root = os.path.join(self.root, JOBS_DIR)
        try:
            job_ids = sorted(os.listdir(jobs_root))
        except OSError:
            job_ids = []
        done_ids = {
            _ENTRY_RE.match(n).group(2)
            for n in self._entries(DONE_DIR)
        }
        pending_ids = {
            _ENTRY_RE.match(n).group(2)
            for n in self._entries(PENDING_DIR)
        }
        for job_id in job_ids:
            jdir = os.path.join(jobs_root, job_id)
            try:
                names = os.listdir(jdir)
            except OSError:
                continue
            for name in names:
                kind = None
                if name.startswith(".terminal@"):
                    kind = "terminal"
                elif name.startswith(".reclaim@"):
                    kind = "reclaim"
                if kind is None:
                    continue
                parts = name.split("@")
                owner = parts[1] if len(parts) == 3 else ""
                if owner and owner_dead(owner) is None:
                    continue  # creator is alive: transition in flight
                token = os.path.join(jdir, name)
                if job_id in done_ids or job_id in pending_ids:
                    # the transition completed (or the job moved on);
                    # the token is litter
                    try:
                        os.unlink(token)
                    except OSError:
                        pass
                    actions.append({
                        "job": job_id, "action": "swept", "token": name,
                    })
                    continue
                # the taker died holding the job: neither terminal nor
                # pending. We cannot know a dead finisher's intended
                # outcome, so the job goes back to the queue.
                epoch = None
                if len(parts) == 3 and parts[2].isdigit():
                    epoch = int(parts[2])
                # atomic take of the token itself: two scavengers
                # sweeping the same leftover cannot both resolve it
                take = f"{token}.take"
                try:
                    os.replace(token, take)
                except OSError:
                    continue
                try:
                    with open(take) as f:
                        obj = json.load(f)
                except (OSError, json.JSONDecodeError):
                    continue
                if not isinstance(obj, dict):
                    continue
                # the original entry name is gone with the rename; a
                # fresh one from submitted_t keeps FIFO order close
                sub_t = obj.get("submitted_t") or t
                base = f"{int(float(sub_t) * 1e9):020d}-{job_id}.json"
                act = self._requeue_or_exhaust(
                    take, base, owner=owner or None, epoch=epoch,
                    reason="interrupted_transition", by=by,
                    max_reclaims=max_reclaims, now=t,
                )
                if act:
                    actions.append(act)
        return actions

    # -- poisoned-job verdicts ----------------------------------------

    def _verdict_path(self, job_id: str) -> str:
        return os.path.join(self.root, VERDICTS_DIR, f"{job_id}.json")

    def record_strike(
        self,
        job_id: str,
        *,
        reason: str = "",
        server: Optional[str] = None,
        max_strikes: int = 2,
    ) -> int:
        """Persist one dispatch-failure strike against ``job_id``;
        at ``max_strikes`` the verdict flips to poisoned, so *every*
        server — including ones that never saw the job — refuses it.
        Returns the cumulative strike count."""
        path = self._verdict_path(job_id)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            rec = {}
        n = int(rec.get("strikes", 0)) + 1
        out = {
            "schema": VERDICT_SCHEMA, "job": job_id, "strikes": n,
            "poisoned": bool(rec.get("poisoned")) or n >= max_strikes,
            "t": time.time(),
        }
        if reason:
            out["reason"] = reason
        if server:
            out["server"] = server
        self._write_json_atomic(path, out)
        return n

    def poisoned(self, job_id: str) -> bool:
        """True when the spool-wide verdict says ``job_id`` wedges
        workers — server-independent, survives restarts."""
        try:
            with open(self._verdict_path(job_id)) as f:
                return bool(json.load(f).get("poisoned"))
        except (OSError, json.JSONDecodeError):
            return False

    def strikes(self, job_id: str) -> int:
        try:
            with open(self._verdict_path(job_id)) as f:
                return int(json.load(f).get("strikes", 0))
        except (OSError, json.JSONDecodeError, TypeError, ValueError):
            return 0

    def verdicts(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        d = os.path.join(self.root, VERDICTS_DIR)
        try:
            names = sorted(os.listdir(d))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json") or name.startswith("."):
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    rec = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if isinstance(rec, dict):
                out.append(rec)
        return out

    # -- status -------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        done = self.done()
        outcomes: Dict[str, int] = {}
        for rec in done:
            key = str(rec.get("outcome", "?"))
            outcomes[key] = outcomes.get(key, 0) + 1
        return {
            "root": self.root,
            "capacity": self.capacity,
            "draining": self.draining(),
            "depth": self.depth(),
            "pending": [
                {"job": s.id, "tenant": s.tenant, "nproc": s.nproc}
                for s in self.pending()
            ],
            "running": [
                {
                    "job": s.id, "tenant": s.tenant, "nproc": s.nproc,
                    "server": s.owner, "epoch": s.epoch,
                }
                for s in self.running()
            ],
            "servers": [
                {
                    "id": rec.get("id"),
                    "alive": rec.get("alive"),
                    "lease_s": rec.get("lease_s"),
                    "lease_age_s": round(
                        float(rec.get("lease_age_s", 0.0)), 3
                    ),
                    "pid": rec.get("pid"),
                }
                for rec in self.servers()
            ],
            "done": [
                {
                    "job": rec.get("id"),
                    "tenant": rec.get("tenant"),
                    "outcome": rec.get("outcome"),
                }
                for rec in done
            ],
            "outcomes": outcomes,
        }
