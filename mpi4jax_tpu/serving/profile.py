"""Control-plane micro-span profiler: where the serving fast path burns time.

BENCH r11/r14 left the serving plane with an embarrassing shape: a warm
job *executes* in 0.015 s but *waits* 0.16–0.25 s (p50), and ROADMAP
item 4 blames the filesystem control plane — per-job fsync'd renames,
polling mailboxes, one-claim-at-a-time dispatch. PR 12's SLO
attribution can say "83% queue-wait -> capacity" but not *which*
control-plane operation burned the wait. Before the lock-free dispatch
refactor rebuilds this path, it gets the PR 16 treatment the fabric
got: measure it, attribute it per operation, gate on it.

**Arming.** ``M4T_CP_PROFILE=1`` (or :func:`arm`). The standard is
``resilience/faults.py``'s: unarmed, every instrumented site pays one
module-attribute falsy check (``profile.active is None``) and nothing
else — no clock reads, no allocation, and the unarmed record schemas
on ``serving.jsonl`` are byte-identical to the disarmed build
(drift-pinned in ``tests/test_cp_profile.py``). Armed, hot-path
operations bracket themselves with ``time.monotonic()`` reads and
append ``kind: "cp"`` micro-span records (schema ``m4t-cp/1``) to a
*separate* sink, ``<root>/cp_profile.jsonl`` — the audit/span streams
never change shape, they just gain a sibling file. Pool workers arm
from the same env var (inherited through spawn) and write to their own
``<pool_root>/cp_profile.jsonl``; the loaders read both.

**Phase vocabulary** (the instrumented sites)::

    submit / submit.scan / submit.write / submit.fsync / submit.rename
    claim                the winning pending->running rename
    claim.lost           a rename lost to a peer (the contention signal)
    finish / finish.fence / finish.write / finish.fsync / finish.rename
    lease.renew          one federated heartbeat write
    scavenge             one reclaim pass
    sched.pick           one scheduler decision (picked= names the job)
    loop.scan            one Spool.pending() directory scan
    loop.wakeup          one serve-loop iteration (useful= bool)
    pool.wakeup          one worker mailbox poll (useful= bool)
    pool.deliver         controller item fan-out for one job
    pool.pickup          mailbox write -> worker claim lag (per item)
    claim_batch          one batched lease (k= asked, won= leased)
    wake_latency         submit rename -> wake-wire delivery (wire=)

**Queue-wait decomposition.** Each job's PR 12 ``queued`` span is
split into named control-plane phases whose boundaries are the cp
records' wall-clock stamps::

    submit_visible   submit() entry -> entry durable in pending/
    wake_latency     durable -> the wake wire woke the serve loop
                     (zero on the poll path — the wake IS the scan)
    scan_wait        wake -> the winning scheduler pick started
    sched_pick       the pick decision itself
    claim_rename     pick -> the claim rename landed
    residual         claim -> the server's queued-span boundary clock

The six phases telescope — their sum equals the measured queue span
exactly (float rounding aside), which :func:`decompose_job` self-checks
(``ok``) and reports as ``coverage`` (the non-residual share; the
acceptance bar is >= 90%). The warm pool's post-claim hand-off
(``mailbox_delivery``, ``worker_pickup``) is reported alongside — it
lives inside the ``dispatch`` span, not ``queued``, and the one
definition of dispatch both this module and ``serve_loadgen`` use is
:func:`dispatch_durations` (asserted equal in tests, so BENCH cohorts
and ``profile`` reports can never disagree).

CLI::

    python -m mpi4jax_tpu.serving profile SPOOL [--json]
    python -m mpi4jax_tpu.serving.profile SPOOL [--json]
    python -m mpi4jax_tpu.serving.profile --selftest

plus OpenMetrics families (``m4t_cp_*``) merged into the serving
exposition, a per-server control-plane track in ``trace --serve``,
doctor narration ("job j7: queue-wait 0.21 s = 71% scan wait + 18%
submit fsync"), and the ``serve_controlplane`` BENCH variant
(``benchmarks/serve_loadgen.py --profile``) wired into ``perf gate``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

CP_SCHEMA = "m4t-cp/1"
REPORT_SCHEMA = "m4t-cp-report/1"

#: the arming switch (any non-empty value arms)
ENV_VAR = "M4T_CP_PROFILE"

#: the profiler's own sink, beside serving.jsonl — a sibling file so
#: the unarmed audit/span schemas stay byte-identical by construction
PROFILE_NAME = "cp_profile.jsonl"

#: every phase an instrumented site may emit; a typo'd phase name is a
#: bug the selftest should catch, not a silently separate bucket
PHASES = frozenset({
    "submit", "submit.scan", "submit.write", "submit.fsync",
    "submit.rename",
    "claim", "claim.lost",
    "finish", "finish.fence", "finish.write", "finish.fsync",
    "finish.rename",
    "lease.renew", "scavenge",
    "sched.pick", "loop.scan", "loop.wakeup",
    "pool.wakeup", "pool.deliver", "pool.pickup",
    # the event-driven dispatch plane (PR 20): one claim_batch record
    # brackets each batched lease, one wake_latency record stamps a
    # wake-wire delivery (submit rename -> listener woke; wire= names
    # the channel)
    "claim_batch", "wake_latency",
})

#: the queue-wait decomposition, in lifecycle order. ``wake_latency``
#: is zero on the poll path (the wake *is* the scan that found the
#: job); under an event-driven server it splits the old scan wait into
#: "the wire delivering" and "the loop getting to the job"
QUEUE_PHASES = (
    "submit_visible", "wake_latency", "scan_wait", "sched_pick",
    "claim_rename", "residual",
)

#: dispatch-side hand-off phases (inside the ``dispatch`` span)
DISPATCH_PHASES = ("mailbox_delivery", "worker_pickup")

#: the telescoped phase sum must equal the queue span to float
#: rounding; anything past this is a decomposition bug, not jitter
SUM_TOLERANCE_S = 1e-6

#: how many syscalls of each kind one record of a phase represents
#: (records may override with an explicit ``n`` field, e.g. the
#: submit scan's 4 listdirs or a scavenge pass's variable scan count)
FSYNC_PHASES = frozenset({
    "submit.fsync", "finish.fsync", "lease.renew",
})
RENAME_PHASES = frozenset({
    "submit.rename", "claim", "claim.lost", "finish.fence",
    "finish.rename", "lease.renew",
})
DIR_SCAN_PHASES = frozenset({
    "submit.scan", "loop.scan", "pool.wakeup", "scavenge",
})

#: patchable clocks: ``wall`` places records on the span plane's
#: timeline (``spans.now`` convention), ``clock`` measures durations
wall = time.time
clock = time.monotonic


def cp_record(
    phase: str, *, dur_s: float, t: float, **fields: Any
) -> Dict[str, Any]:
    """Build one ``m4t-cp/1`` record. ``t`` is the wall clock at the
    *end* of the phase; ``dur_s`` is monotonic-measured, so the phase
    started at roughly ``t - dur_s`` on the span timeline."""
    rec: Dict[str, Any] = {
        "kind": "cp",
        "schema": CP_SCHEMA,
        "phase": str(phase),
        "t": float(t),
        "dur_s": round(max(0.0, float(dur_s)), 9),
    }
    for key, value in fields.items():
        if value is not None:
            rec[key] = value
    return rec


class CPProfiler:
    """The armed profiler: a thread-safe append-only JSONL writer.

    Every ``phase()`` is best-effort — the control plane must keep
    serving when its profile cannot be written — and cheap: one dict,
    one ``json.dumps``, one appended line, no fsync (losing the tail
    of a *profile* on a crash is fine; the audit stream is the durable
    one)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.path = os.path.join(self.root, PROFILE_NAME)
        self._lock = threading.Lock()
        #: lazily opened, held for the profiler's lifetime — an
        #: open/close per record would dominate the cost it measures
        self._f = None

    def t(self) -> float:
        """A monotonic phase-start stamp (pass back to :meth:`phase`)."""
        return clock()

    def phase(
        self,
        name: str,
        t0: Optional[float] = None,
        *,
        dur_s: Optional[float] = None,
        **fields: Any,
    ) -> Optional[Dict[str, Any]]:
        """Record one finished phase: ``t0`` is a :meth:`t` stamp
        (duration measured now), or pass ``dur_s`` directly."""
        if dur_s is None:
            dur_s = (clock() - t0) if t0 is not None else 0.0
        rec = cp_record(name, dur_s=dur_s, t=wall(), **fields)
        try:
            line = json.dumps(rec, default=str)
            with self._lock:
                if self._f is None:
                    # O_APPEND: pool workers are separate processes
                    # sharing one sink; whole-line appends interleave
                    # without tearing (the loader skips torn tails)
                    self._f = open(self.path, "a")
                self._f.write(line + "\n")
                self._f.flush()
        except (OSError, ValueError):
            return None
        return rec

    def mark(self, name: str, **fields: Any) -> Optional[Dict[str, Any]]:
        """A zero-duration counter record (e.g. a wasted wakeup)."""
        return self.phase(name, dur_s=0.0, **fields)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


# ---------------------------------------------------------------------
# arming (the faults.py standard)
# ---------------------------------------------------------------------

#: the armed profiler, or None. Every instrumented hot-path site gates
#: on ``profile.active is not None`` — the whole unarmed cost.
active: Optional[CPProfiler] = None

_env_checked = False


def arm(root: str) -> CPProfiler:
    """Activate profiling for this process, sinking to
    ``<root>/cp_profile.jsonl`` (tests and benches; served processes
    arm from ``M4T_CP_PROFILE`` automatically at spool/pool init)."""
    global active, _env_checked
    prof = CPProfiler(root)
    if active is not None:
        active.close()
    active = prof
    _env_checked = True
    return prof


def disarm() -> None:
    global active, _env_checked
    if active is not None:
        active.close()
    active = None
    _env_checked = False


def arm_from_env(root: str) -> Optional[CPProfiler]:
    """Arm for ``root`` when ``M4T_CP_PROFILE`` is set. Called from
    ``Spool.__init__`` / the pool worker loop, so whichever root the
    process actually serves gets the sink — re-arming to a new root is
    deliberate (one profiler per process, latest spool wins; the
    federated loadgen shares one spool across its whole fleet)."""
    global _env_checked
    _env_checked = True
    if not os.environ.get(ENV_VAR, ""):
        return None
    root = os.path.abspath(root)
    if active is not None and active.root == root:
        return active
    return arm(root)


# ---------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------


def profile_paths(root: str) -> List[str]:
    """The cp sinks under a spool root: the server's own plus the warm
    pool's (workers are separate processes with their own files)."""
    root = os.path.abspath(root)
    cands = [
        os.path.join(root, PROFILE_NAME),
        os.path.join(root, "pool", PROFILE_NAME),
    ]
    return [p for p in cands if os.path.exists(p)]


def load_cp(root: str) -> List[Dict[str, Any]]:
    """Every ``kind == "cp"`` record under a spool root, sorted by
    wall-clock stamp (the two sinks interleave on one timeline)."""
    records: List[Dict[str, Any]] = []
    for path in profile_paths(root):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(rec, dict) and rec.get("kind") == "cp":
                        records.append(rec)
        except OSError:
            continue
    records.sort(key=lambda r: float(r.get("t") or 0.0))
    return records


def dispatch_durations(
    span_records: Iterable[Dict[str, Any]],
) -> List[float]:
    """THE definition of per-job dispatch latency: the lifecycle
    ``dispatch`` span's duration (claim -> supervisor start), sorted.
    ``serve_loadgen``'s ``dispatch_p50/p99_s`` and the profile report
    both call this — one definition, asserted equal in tests, so BENCH
    cohorts and ``profile`` output cannot drift apart."""
    return sorted(
        float(s.get("dur_s") or 0.0)
        for s in span_records
        if s.get("kind") == "span" and s.get("span") == "dispatch"
    )


# ---------------------------------------------------------------------
# queue-wait decomposition
# ---------------------------------------------------------------------


def decompose_job(
    queued: Dict[str, Any],
    cp: Iterable[Dict[str, Any]],
) -> Dict[str, Any]:
    """Split one job's ``queued`` span into :data:`QUEUE_PHASES` using
    the job's cp records as boundary stamps. Returns::

        {"job", "tenant", "queue_wait_s", "phases": {...},
         "sum_s", "ok", "coverage", "claim_lost_s", "claim_losses",
         "mailbox_delivery_s", "worker_pickup_s"}

    The phases telescope (see module docstring), so ``ok`` asserts
    ``|sum - queue_wait| <= SUM_TOLERANCE_S`` and ``coverage`` is the
    non-residual share — what the profiler *named*, vs the hand-off
    sliver it could only bound."""
    job = str(queued.get("job"))
    tq0 = float(queued.get("t0") or 0.0)
    tq1 = float(queued.get("t1") or 0.0)
    span = max(0.0, tq1 - tq0)
    mine = [r for r in cp if str(r.get("job") or "") == job]
    picks = sorted(
        (r for r in cp
         if r.get("phase") == "sched.pick"
         and str(r.get("picked") or "") == job),
        key=lambda r: float(r.get("t") or 0.0),
    )

    def last(phase: str) -> Optional[Dict[str, Any]]:
        recs = [r for r in mine if r.get("phase") == phase]
        return recs[-1] if recs else None

    sub = last("submit")
    won = last("claim")
    ts = float(sub["t"]) if sub else tq0
    if won is not None:
        tc = float(won["t"])
        dc = float(won.get("dur_s") or 0.0)
        before = [p for p in picks if float(p["t"]) <= tc + 1e-9]
        pick = before[-1] if before else None
    else:
        tc, dc, pick = tq1, 0.0, (picks[-1] if picks else None)
    if pick is not None:
        tp = float(pick["t"])
        dp = float(pick.get("dur_s") or 0.0)
    else:
        # no scheduler record (e.g. a bare spool.claim): charge the
        # rename itself and let the wait end at its start
        tp, dp = tc - dc, 0.0
    # the wake wire's delivery stamp (event-driven servers): the wall
    # clock when the listener woke for this job, clamped between the
    # submit-visible boundary and the pick start so the telescoping
    # identity survives clock jitter; absent (poll path), the wake is
    # the scan itself and the phase is zero
    wakes = [r for r in mine if r.get("phase") == "wake_latency"]
    before_pick = [
        r for r in wakes if float(r.get("t") or 0.0) <= (tp - dp) + 1e-9
    ]
    wake = before_pick[-1] if before_pick else None
    if wake is not None:
        tw = min(max(float(wake["t"]), ts), tp - dp)
    else:
        tw = ts
    phases = {
        "submit_visible": ts - tq0,
        "wake_latency": tw - ts,
        "scan_wait": (tp - dp) - tw,
        "sched_pick": dp,
        "claim_rename": tc - tp,
        "residual": tq1 - tc,
    }
    total = sum(phases.values())
    named = total - phases["residual"]
    lost = [r for r in mine if r.get("phase") == "claim.lost"]
    out: Dict[str, Any] = {
        "job": job,
        "tenant": queued.get("tenant"),
        "queue_wait_s": span,
        "phases": {k: round(v, 9) for k, v in phases.items()},
        "sum_s": round(total, 9),
        "ok": abs(total - span) <= SUM_TOLERANCE_S,
        "coverage": (named / span) if span > 0 else 1.0,
        "claim_losses": len(lost),
        "claim_lost_s": round(
            sum(float(r.get("dur_s") or 0.0) for r in lost), 9
        ),
    }
    deliver = last("pool.deliver")
    if deliver is not None:
        out["mailbox_delivery_s"] = float(deliver.get("dur_s") or 0.0)
    pickups = [r for r in mine if r.get("phase") == "pool.pickup"]
    if pickups:
        # the gang waits for its slowest rank's pickup
        out["worker_pickup_s"] = max(
            float(r.get("dur_s") or 0.0) for r in pickups
        )
    return out


def decompose(
    root: str,
    *,
    spans: Optional[Iterable[Dict[str, Any]]] = None,
    cp: Optional[Iterable[Dict[str, Any]]] = None,
) -> List[Dict[str, Any]]:
    """Per-job queue-wait decompositions for every job with a
    ``queued`` span under ``root``, in submit order."""
    if spans is None:
        from ..observability import spans as _spans

        spans = _spans.load_spans([root])
    if cp is None:
        cp = load_cp(root)
    cp = list(cp)
    queued = sorted(
        (s for s in spans
         if s.get("span") == "queued" and s.get("job")),
        key=lambda s: float(s.get("t0") or 0.0),
    )
    return [decompose_job(q, cp) for q in queued]


def narrate_job(decomp: Dict[str, Any]) -> str:
    """One line an operator can act on: the queue wait and its top
    contributors by share — e.g. ``job j7: queue-wait 0.21 s = 71%
    scan wait + 18% submit fsync + 6% claim race lost``."""
    span = float(decomp.get("queue_wait_s") or 0.0)
    if span <= 0:
        return f"job {decomp.get('job')}: queue-wait 0 s"
    labels = {
        "submit_visible": "submit visibility",
        "wake_latency": "wake latency (wire delivery)",
        "scan_wait": "scan wait (poll interval + server busy)",
        "sched_pick": "scheduler pick",
        "claim_rename": "claim rename",
        "residual": "hand-off",
    }
    shares = [
        (max(0.0, float(v)) / span, labels[k])
        for k, v in (decomp.get("phases") or {}).items()
        if k in labels
    ]
    lost = float(decomp.get("claim_lost_s") or 0.0)
    if lost > 0:
        shares.append((lost / span, "claim race lost"))
    shares.sort(reverse=True)
    parts = [
        f"{share:.0%} {label}"
        for share, label in shares[:3] if share >= 0.01
    ]
    return (
        f"job {decomp.get('job')}: queue-wait {span:.3g} s = "
        + " + ".join(parts or ["(all phases < 1%)"])
    )


# ---------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------


def _pct(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    i = min(
        len(sorted_vals) - 1,
        max(0, int(round(q * (len(sorted_vals) - 1)))),
    )
    return sorted_vals[i]


def _wakeup_stats(recs: List[Dict[str, Any]]) -> Dict[str, Any]:
    total = len(recs)
    useful = sum(1 for r in recs if r.get("useful"))
    return {
        "total": total,
        "useful": useful,
        "wasted": total - useful,
        "wasted_ratio": (
            round((total - useful) / total, 4) if total else None
        ),
    }


def profile_report(
    root: str,
    *,
    cp: Optional[List[Dict[str, Any]]] = None,
    spans: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """The whole story for one spool: per-phase latency percentiles,
    the syscall budget, wakeup efficiency, claim contention, and the
    per-job queue-wait decomposition."""
    if cp is None:
        cp = load_cp(root)
    if spans is None:
        from ..observability import spans as _spans

        spans = _spans.load_spans([root])
    by_phase: Dict[str, List[float]] = {}
    for rec in cp:
        by_phase.setdefault(str(rec.get("phase")), []).append(
            float(rec.get("dur_s") or 0.0)
        )
    phases = {}
    for name in sorted(by_phase):
        vals = sorted(by_phase[name])
        phases[name] = {
            "count": len(vals),
            "p50_s": _pct(vals, 0.50),
            "p99_s": _pct(vals, 0.99),
            "total_s": round(sum(vals), 9),
        }

    def _ops(kinds: frozenset) -> int:
        return sum(
            int(r.get("n") or 1)
            for r in cp if r.get("phase") in kinds
        )

    claims = len(by_phase.get("claim", []))
    losses = len(by_phase.get("claim.lost", []))
    jobs = max(1, claims)
    syscalls = {
        "fsyncs": _ops(FSYNC_PHASES),
        "renames": _ops(RENAME_PHASES),
        "dir_scans": _ops(DIR_SCAN_PHASES),
        "jobs": claims,
    }
    for key in ("fsyncs", "renames", "dir_scans"):
        syscalls[f"{key}_per_job"] = round(syscalls[key] / jobs, 2)
    decomps = decompose(root, spans=spans, cp=cp)
    dec_summary: Dict[str, Any] = {"jobs": len(decomps)}
    if decomps:
        covs = sorted(float(d["coverage"]) for d in decomps)
        dec_summary.update({
            "complete": sum(1 for d in decomps if d["ok"]),
            "coverage_p50": round(_pct(covs, 0.50), 4),
            "coverage_min": round(covs[0], 4),
        })
        for stat, q in (("p50", 0.50), ("p99", 0.99)):
            dec_summary[f"phase_{stat}_s"] = {
                name: _pct(sorted(
                    float(d["phases"][name]) for d in decomps
                ), q)
                for name in QUEUE_PHASES
            }
    dispatch = dispatch_durations(spans)
    return {
        "schema": REPORT_SCHEMA,
        "root": os.path.abspath(root),
        "records": len(cp),
        "phases": phases,
        "wakeups": {
            "server": _wakeup_stats([
                r for r in cp if r.get("phase") == "loop.wakeup"
            ]),
            "pool": _wakeup_stats([
                r for r in cp if r.get("phase") == "pool.wakeup"
            ]),
        },
        "claims": {
            "won": claims,
            "lost": losses,
            "lost_ratio": (
                round(losses / (claims + losses), 4)
                if (claims + losses) else None
            ),
            "lost_s_total": round(sum(by_phase.get("claim.lost", [])), 9),
        },
        "syscalls": syscalls,
        "dispatch_p50_s": _pct(dispatch, 0.50),
        "dispatch_p99_s": _pct(dispatch, 0.99),
        "decomposition": dec_summary,
        "per_job": decomps,
    }


def _fmt_s(value: Optional[float]) -> str:
    if value is None:
        return "-"
    if value < 1e-3:
        return f"{value * 1e6:.0f}us"
    if value < 1.0:
        return f"{value * 1e3:.1f}ms"
    return f"{value:.3f}s"


def format_report(report: Dict[str, Any]) -> str:
    """The human rendering of :func:`profile_report`."""
    lines = [
        f"control-plane profile: {report.get('records', 0)} record(s) "
        f"in {report.get('root')}",
    ]
    phases = report.get("phases") or {}
    if phases:
        lines.append("  phase latency (count / p50 / p99 / total):")
        width = max(len(n) for n in phases)
        for name in sorted(phases):
            st = phases[name]
            lines.append(
                f"    {name:<{width}}  {st['count']:>5}  "
                f"{_fmt_s(st['p50_s']):>8}  {_fmt_s(st['p99_s']):>8}  "
                f"{_fmt_s(st['total_s']):>9}"
            )
    sc = report.get("syscalls") or {}
    if sc.get("jobs"):
        lines.append(
            f"  syscall budget ({sc['jobs']} dispatched job(s)): "
            f"{sc.get('fsyncs_per_job')} fsyncs + "
            f"{sc.get('renames_per_job')} renames + "
            f"{sc.get('dir_scans_per_job')} dir-scans per job"
        )
    for plane in ("server", "pool"):
        wk = (report.get("wakeups") or {}).get(plane) or {}
        if wk.get("total"):
            lines.append(
                f"  {plane} wakeups: {wk['total']} "
                f"({wk['useful']} useful, "
                f"{wk['wasted_ratio']:.0%} wasted)"
            )
    cl = report.get("claims") or {}
    if cl.get("lost"):
        lines.append(
            f"  claim contention: {cl['lost']} race(s) lost vs "
            f"{cl['won']} won ({cl['lost_ratio']:.0%}), "
            f"{_fmt_s(cl['lost_s_total'])} burned"
        )
    dec = report.get("decomposition") or {}
    if dec.get("jobs"):
        lines.append(
            f"  queue-wait decomposition: {dec.get('complete', 0)}/"
            f"{dec['jobs']} job(s) telescope exactly; coverage p50 "
            f"{dec.get('coverage_p50', 0):.1%} (min "
            f"{dec.get('coverage_min', 0):.1%})"
        )
        p50 = dec.get("phase_p50_s") or {}
        if p50:
            lines.append(
                "    p50 by phase: " + ", ".join(
                    f"{name}={_fmt_s(p50.get(name))}"
                    for name in QUEUE_PHASES
                )
            )
    for d in (report.get("per_job") or [])[:8]:
        lines.append("  " + narrate_job(d))
    if not phases:
        lines.append(
            "  (no cp records — arm with M4T_CP_PROFILE=1 and serve)"
        )
    return "\n".join(lines)


def format_cp_narration(report: Dict[str, Any]) -> str:
    """The doctor's control-plane section: one actionable line per
    job (:func:`narrate_job`) plus the wakeup/contention summary —
    :func:`format_report` minus the phase table, for embedding under
    the serving timeline."""
    lines = [
        f"control-plane profile ({report.get('records', 0)} micro-"
        "span(s), M4T_CP_PROFILE):"
    ]
    for d in (report.get("per_job") or [])[:16]:
        lines.append("  " + narrate_job(d))
    sc = report.get("syscalls") or {}
    if sc.get("jobs"):
        lines.append(
            f"  syscall budget: {sc.get('fsyncs_per_job')} fsyncs + "
            f"{sc.get('renames_per_job')} renames + "
            f"{sc.get('dir_scans_per_job')} dir-scans per job"
        )
    for plane in ("server", "pool"):
        wk = (report.get("wakeups") or {}).get(plane) or {}
        if wk.get("total"):
            lines.append(
                f"  {plane} wakeups: {wk['total']} ({wk['useful']} "
                f"useful, {wk['wasted_ratio']:.0%} wasted)"
            )
    cl = report.get("claims") or {}
    if cl.get("lost"):
        lines.append(
            f"  claim contention: {cl['lost']} race(s) lost vs "
            f"{cl['won']} won, {_fmt_s(cl['lost_s_total'])} burned"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------
# OpenMetrics
# ---------------------------------------------------------------------


def render_cp_families(out: List[str], report: Dict[str, Any]) -> None:
    """Append the ``m4t_cp_*`` exposition families for a
    :func:`profile_report` (shared by the serving exporter; the caller
    owns the trailing ``# EOF``)."""
    from ..observability import export as _export

    g = _export._Family(
        out, "m4t_cp_phase_seconds", "gauge",
        "Control-plane micro-span latency quantiles per phase "
        "(serving/profile.py, armed via M4T_CP_PROFILE).",
    )
    for name in sorted(report.get("phases") or {}):
        st = report["phases"][name]
        for quantile, key in (("p50", "p50_s"), ("p99", "p99_s")):
            g.sample(st.get(key), phase=name, quantile=quantile)
    c = _export._Family(
        out, "m4t_cp_phase_ops_total", "counter",
        "Control-plane operations profiled, per phase.",
    )
    for name in sorted(report.get("phases") or {}):
        c.sample(report["phases"][name]["count"], phase=name)
    sc = report.get("syscalls") or {}
    c = _export._Family(
        out, "m4t_cp_fsync_total", "counter",
        "fsync calls the control plane paid while profiled.",
    )
    c.sample(sc.get("fsyncs", 0))
    c = _export._Family(
        out, "m4t_cp_rename_total", "counter",
        "Atomic renames the control plane paid while profiled.",
    )
    c.sample(sc.get("renames", 0))
    c = _export._Family(
        out, "m4t_cp_dir_scan_total", "counter",
        "Directory scans the control plane paid while profiled.",
    )
    c.sample(sc.get("dir_scans", 0))
    c = _export._Family(
        out, "m4t_cp_poll_wakeups_total", "counter",
        "Poll-loop wakeups by usefulness (plane: server loop / pool "
        "worker mailbox). wasted = woke, scanned, found nothing.",
    )
    for plane in ("server", "pool"):
        wk = (report.get("wakeups") or {}).get(plane) or {}
        c.sample(wk.get("useful", 0), plane=plane, useful="true")
        c.sample(wk.get("wasted", 0), plane=plane, useful="false")
    c = _export._Family(
        out, "m4t_cp_claim_races_lost_total", "counter",
        "Pending->running renames lost to a peer server (federated "
        "claim contention).",
    )
    c.sample((report.get("claims") or {}).get("lost", 0))


# ---------------------------------------------------------------------
# CLI + selftest
# ---------------------------------------------------------------------


def selftest() -> int:
    """Device-free proof of the profiler: a stub-runner serving loop
    under ``M4T_CP_PROFILE`` emits real cp records from the actual
    instrumented sites; every job's decomposition telescopes exactly
    at >=90% coverage; disarmed, the same loop writes no cp sink at
    all and the profiler costs one falsy check."""
    import tempfile

    from .server import Server
    from .spool import Spool

    prev_env = os.environ.get(ENV_VAR)
    prev_active, prev_checked = active, _env_checked

    def _serve(tmp: str) -> Spool:
        spool = Spool(os.path.join(tmp, "spool"))
        for i in range(4):
            r = spool.submit({
                "id": f"p{i}", "tenant": f"t{i % 2}",
                "cmd": ["-c", "pass"],
            })
            assert r["status"] == "queued", r
        server = Server(
            spool, nproc=1, max_jobs=4, poll_s=0.01,
            runner=lambda *a: (0, []), log=lambda msg: None,
        )
        assert server.serve() == 0
        return spool

    try:
        # disarmed: no sink appears, no schema changes
        disarm()
        os.environ.pop(ENV_VAR, None)
        with tempfile.TemporaryDirectory() as tmp:
            spool = _serve(tmp)
            assert profile_paths(spool.root) == [], "unarmed cp sink!"
            assert active is None

        # armed from env: the real instrumented sites
        os.environ[ENV_VAR] = "1"
        disarm()
        with tempfile.TemporaryDirectory() as tmp:
            spool = _serve(tmp)
            cp = load_cp(spool.root)
            assert cp, "armed run wrote no cp records"
            seen = {r["phase"] for r in cp}
            assert seen <= PHASES, sorted(seen - PHASES)
            for needed in ("submit", "submit.fsync", "submit.rename",
                           "claim", "sched.pick", "loop.scan",
                           "loop.wakeup", "finish", "finish.fsync"):
                assert needed in seen, (needed, sorted(seen))
            report = profile_report(spool.root)
            assert report["records"] == len(cp)
            assert report["claims"]["won"] == 4
            assert report["syscalls"]["fsyncs_per_job"] >= 1
            dec = report["decomposition"]
            assert dec["jobs"] == 4 and dec["complete"] == 4, dec
            assert dec["coverage_min"] >= 0.90, dec
            for d in report["per_job"]:
                assert d["ok"], d
                line = narrate_job(d)
                assert d["job"] in line and "queue-wait" in line, line
            text = format_report(report)
            assert "syscall budget" in text, text
            assert "queue-wait decomposition" in text, text
            out: List[str] = []
            render_cp_families(out, report)
            prom = "\n".join(out)
            for family in ("m4t_cp_phase_seconds", "m4t_cp_fsync_total",
                           "m4t_cp_poll_wakeups_total",
                           "m4t_cp_claim_races_lost_total"):
                assert family in prom, family
    finally:
        disarm()
        if prev_env is None:
            os.environ.pop(ENV_VAR, None)
        else:
            os.environ[ENV_VAR] = prev_env
        globals()["active"] = prev_active
        globals()["_env_checked"] = prev_checked
    print("cp profile selftest ok")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    argv = list(sys.argv[1:] if argv is None else argv)
    if "--selftest" in argv:
        return selftest()
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.serving.profile",
        description="Report control-plane micro-span profiles from a "
        "serving spool (arm serving with M4T_CP_PROFILE=1 first).",
    )
    parser.add_argument("spool", help="spool root directory")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)
    report = profile_report(args.spool)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(format_report(report))
    return 0 if report.get("records") else 2


if __name__ == "__main__":
    sys.exit(main())
