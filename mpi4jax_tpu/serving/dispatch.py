"""Event-driven dispatch plane: wake wires, batching, coalescing stats.

BENCH r17 measured the serving control plane into a corner: a warm job
executes in 0.015 s but waits 0.060 s (p50) in the queue, and 0.059 s
of that is ``scan_wait`` — literally the poll interval — plus 2 fsyncs,
4 renames and 6 dir-scans of filesystem traffic per dispatched job.
This module is the event-driven replacement for the sleep/scan/claim-one
hot path. The spool stays the durable source of truth (every pillar
below degrades to the PR 14 semantics when disarmed); what changes is
*when* the loop wakes and *how many* jobs each wake moves:

- **Wake wires** (:func:`open_listener`): a per-spool notification
  channel so a submit wakes the serve loop — and the serve loop wakes
  pool-worker mailboxes — in microseconds instead of a poll interval.
  Three wires, best first: ``inotify`` (the pending-dir rename *is*
  the event; passive, nothing to send), a localhost datagram socket
  (the listener advertises its port in ``<dir>/wake.json``; submitters
  fire one best-effort datagram), and ``poll-fallback`` (a plain
  bounded sleep). Every wire's :meth:`~WakeListener.wait` is bounded
  by the caller's poll interval, so the retained poll loop **is** the
  lost-wakeup recovery: a dropped datagram or missed inotify event
  costs one poll interval, never correctness.
- **Job coalescing** (:func:`coalesce`): pending jobs with the same
  execution fingerprint (module/argv/nproc/env/budgets) are fused into
  one sub-mesh dispatch the way continuous-batching inference servers
  fuse requests. Each coalesced job keeps its own id, trace, spans,
  audits and terminal record; only the world execution is shared.
  Jobs with per-job state (``resume_dir``, ``fault_plan``, per-job
  ``verify``) never coalesce.
- **Dispatch accounting** (:class:`DispatchStats`): wakeups by wire,
  claim-batch sizes, coalesced-job and group-commit counters,
  persisted atomically to ``<root>/dispatch.json``
  (schema ``m4t-dispatch/1``) for ``status`` and the OpenMetrics
  exporter (``m4t_dispatch_*`` families).

The batched-claim and group-commit pillars live where the durability
is: :meth:`Spool.claim_batch`, :meth:`Spool.fence` +
:meth:`Spool.finish_batch` (one fsync per batch of terminal records,
crash-recovered by the PR 14 interrupted-transition sweep), and
:meth:`FairScheduler.pick_batch` / ``commit_batch`` (tenant
round-robin fairness holds across a batch boundary). The serve loop
that ties it together is ``Server(fastpath=...)``.

Everything here is strictly opt-in: ``Server(fastpath=...)`` /
``serve --fastpath`` / ``M4T_DISPATCH_FASTPATH`` for pool workers.
The default paths stay byte-identical (the PR 17 drift pins hold).

CLI::

    python -m mpi4jax_tpu.serving dispatch --selftest
    python -m mpi4jax_tpu.serving.dispatch --selftest
"""

from __future__ import annotations

import ctypes
import ctypes.util
import json
import os
import select
import socket
import struct
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import profile as _profile

DISPATCH_SCHEMA = "m4t-dispatch/1"

#: the socket wire's rendezvous file, beside the watched directory
WAKE_NAME = "wake.json"

#: the persisted dispatch-plane counters (status + exporter surface)
SNAPSHOT_NAME = "dispatch.json"

#: arms the event-driven mailbox path in pool workers (inherited
#: through spawn); a wire name ("inotify" / "socket" / "poll") forces
#: that wire, any other non-empty value auto-selects
ENV_FASTPATH = "M4T_DISPATCH_FASTPATH"

WIRE_INOTIFY = "inotify"
WIRE_SOCKET = "socket"
WIRE_POLL = "poll-fallback"

#: inotify event masks (linux/inotify.h) — the rename that lands a
#: pending entry / mailbox item is IN_MOVED_TO; IN_CREATE covers
#: non-rename writers
_IN_CREATE = 0x00000100
_IN_MOVED_TO = 0x00000080
_IN_NONBLOCK = 0x00000800  # O_NONBLOCK on every port we run on


class WakeListener:
    """One end of a wake wire. ``wait`` blocks up to ``timeout_s`` for
    the first event, then drains whatever else is immediately ready —
    so a burst of submits costs one wake, not one scan per datagram.
    Subclasses set :attr:`wire` to the name ``status`` reports."""

    wire = WIRE_POLL

    def wait(self, timeout_s: float) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self) -> "WakeListener":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class PollWire(WakeListener):
    """The always-correct fallback: a bounded sleep, no events. The
    serve loop's own directory scan finds the work — exactly the
    pre-PR-20 behavior, which is why every other wire can afford to be
    best-effort."""

    wire = WIRE_POLL

    def wait(self, timeout_s: float) -> List[Dict[str, Any]]:
        if timeout_s > 0:
            time.sleep(timeout_s)
        return []


class _Libc:
    """Lazily resolved libc inotify entry points (ctypes, no deps)."""

    _lock = threading.Lock()
    _libc: Any = None
    _failed = False

    @classmethod
    def get(cls) -> Any:
        with cls._lock:
            if cls._failed:
                return None
            if cls._libc is None:
                try:
                    name = ctypes.util.find_library("c")
                    libc = ctypes.CDLL(name, use_errno=True)
                    # probe: all three symbols must exist
                    libc.inotify_init1
                    libc.inotify_add_watch
                    libc.inotify_rm_watch
                    cls._libc = libc
                except (OSError, AttributeError, TypeError):
                    cls._failed = True
                    return None
            return cls._libc


def inotify_available() -> bool:
    """Whether the inotify wire can be constructed on this host."""
    if not sys.platform.startswith("linux"):
        return False
    return _Libc.get() is not None


class InotifyWire(WakeListener):
    """Watch a directory for entry arrivals via inotify. Passive: the
    atomic rename that makes a pending entry (or mailbox item) visible
    *is* the notification, so submitters need no code at all and a
    crashed listener misses nothing durable."""

    wire = WIRE_INOTIFY

    def __init__(self, watch_dir: str):
        libc = _Libc.get()
        if libc is None:
            raise OSError("inotify unavailable (libc probe failed)")
        self.watch_dir = os.path.abspath(watch_dir)
        os.makedirs(self.watch_dir, exist_ok=True)
        fd = libc.inotify_init1(_IN_NONBLOCK)
        if fd < 0:
            raise OSError(ctypes.get_errno(), "inotify_init1 failed")
        self._fd = fd
        wd = libc.inotify_add_watch(
            fd, os.fsencode(self.watch_dir), _IN_MOVED_TO | _IN_CREATE
        )
        if wd < 0:
            err = ctypes.get_errno()
            os.close(fd)
            raise OSError(err, "inotify_add_watch failed")
        self._wd = wd

    def _drain(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        while True:
            try:
                buf = os.read(self._fd, 65536)
            except BlockingIOError:
                break
            except OSError:
                break
            off = 0
            while off + 16 <= len(buf):
                _wd, _mask, _cookie, nlen = struct.unpack_from(
                    "iIII", buf, off
                )
                name = buf[off + 16: off + 16 + nlen].split(b"\0", 1)[0]
                off += 16 + nlen
                text = os.fsdecode(name)
                if not text or text.startswith(".tmp-"):
                    continue
                ev: Dict[str, Any] = {"wire": self.wire, "name": text}
                # entry names carry a 20-digit time_ns prefix (spool
                # entries and mailbox items both): recover the submit
                # stamp so the listener can attribute wake latency
                head = text.split("-", 1)
                if head and head[0].isdigit():
                    ev["t"] = int(head[0]) / 1e9
                    rest = text.split("-", 1)[1] if "-" in text else ""
                    if rest.endswith(".json"):
                        ev["job"] = rest[: -len(".json")]
                out.append(ev)
            if not buf:
                break
        return out

    def wait(self, timeout_s: float) -> List[Dict[str, Any]]:
        try:
            ready, _, _ = select.select(
                [self._fd], [], [], max(0.0, timeout_s)
            )
        except (OSError, ValueError):
            return []
        if not ready:
            return []
        return self._drain()

    def close(self) -> None:
        libc = _Libc.get()
        try:
            if libc is not None:
                libc.inotify_rm_watch(self._fd, self._wd)
        except OSError:
            pass
        try:
            os.close(self._fd)
        except OSError:
            pass


class SocketWire(WakeListener):
    """A localhost datagram socket. The listener binds an ephemeral
    port and advertises it atomically in ``<advertise_dir>/wake.json``;
    :func:`notify` reads the advertisement and fires one best-effort
    datagram per submit. Datagrams carry ``{"job", "t"}`` so the
    listener can attribute wake latency; loss is recovered by the
    bounded poll."""

    wire = WIRE_SOCKET

    def __init__(self, advertise_dir: str):
        self.advertise_dir = os.path.abspath(advertise_dir)
        os.makedirs(self.advertise_dir, exist_ok=True)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self._sock.bind(("127.0.0.1", 0))
        self.port = self._sock.getsockname()[1]
        self.path = os.path.join(self.advertise_dir, WAKE_NAME)
        tmp = f"{self.path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({
                "schema": DISPATCH_SCHEMA, "wire": self.wire,
                "port": self.port, "pid": os.getpid(), "t": time.time(),
            }, f)
        os.replace(tmp, self.path)

    def wait(self, timeout_s: float) -> List[Dict[str, Any]]:
        try:
            ready, _, _ = select.select(
                [self._sock], [], [], max(0.0, timeout_s)
            )
        except (OSError, ValueError):
            return []
        if not ready:
            return []
        out: List[Dict[str, Any]] = []
        while True:
            try:
                data, _addr = self._sock.recvfrom(4096)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                break
            ev: Dict[str, Any] = {"wire": self.wire}
            try:
                obj = json.loads(data.decode("utf-8", "replace"))
                if isinstance(obj, dict):
                    if obj.get("job"):
                        ev["job"] = str(obj["job"])
                    if obj.get("t") is not None:
                        ev["t"] = float(obj["t"])
            except (ValueError, TypeError):
                pass
            out.append(ev)
        return out

    def close(self) -> None:
        # retract the advertisement iff it is still ours — a newer
        # listener's wake.json must survive this one's shutdown
        try:
            with open(self.path) as f:
                rec = json.load(f)
            if rec.get("port") == self.port and rec.get("pid") == os.getpid():
                os.unlink(self.path)
        except (OSError, ValueError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def open_listener(
    watch_dir: str,
    *,
    advertise_dir: Optional[str] = None,
    prefer: Optional[str] = None,
) -> WakeListener:
    """Open the best available wake wire for ``watch_dir``.

    ``prefer`` forces a wire by name (``"inotify"`` / ``"socket"`` /
    ``"poll"``); construction failures fall through the chain
    inotify -> socket -> poll, so the result is always usable. The
    socket wire advertises in ``advertise_dir`` (default: the watch
    dir's parent — the spool/worker root, where :func:`notify` looks).
    """
    if advertise_dir is None:
        advertise_dir = os.path.dirname(os.path.abspath(watch_dir))
    order: List[str]
    if prefer in (WIRE_INOTIFY, "inotify"):
        order = [WIRE_INOTIFY, WIRE_SOCKET, WIRE_POLL]
    elif prefer in (WIRE_SOCKET, "socket"):
        order = [WIRE_SOCKET, WIRE_POLL]
    elif prefer in (WIRE_POLL, "poll"):
        order = [WIRE_POLL]
    else:
        order = [WIRE_INOTIFY, WIRE_SOCKET, WIRE_POLL]
    for wire in order:
        try:
            if wire == WIRE_INOTIFY:
                if not inotify_available():
                    continue
                return InotifyWire(watch_dir)
            if wire == WIRE_SOCKET:
                return SocketWire(advertise_dir)
            return PollWire()
        except OSError:
            continue
    return PollWire()


def notify(root: str, *, job: Optional[str] = None) -> bool:
    """Fire one best-effort wake datagram at whoever advertised a
    socket wire under ``root``. Called from ``Spool.submit`` (after
    the entry rename — the event must never precede the durable fact)
    and from the pool controller after a mailbox write.

    Costs one failed ``stat`` when nothing is listening; never raises,
    never blocks: wake delivery is advisory, the bounded poll is the
    contract."""
    path = os.path.join(os.path.abspath(root), WAKE_NAME)
    if not os.path.exists(path):
        return False
    try:
        with open(path) as f:
            rec = json.load(f)
        port = int(rec.get("port") or 0)
        if not (0 < port < 65536):
            return False
        payload = json.dumps({
            "job": job, "t": _profile.wall(),
        }).encode("utf-8")
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            sock.setblocking(False)
            sock.sendto(payload, ("127.0.0.1", port))
        finally:
            sock.close()
        return True
    except (OSError, ValueError, TypeError):
        return False


# ---------------------------------------------------------------------
# job coalescing
# ---------------------------------------------------------------------


def coalesce_key(spec: Any) -> Optional[Tuple]:
    """The execution fingerprint under which jobs may share one
    dispatch, or None when ``spec`` must run alone. Two jobs coalesce
    only when the spawned world would be *indistinguishable*: same
    entry point, argv, world size, env and retry/deadline budgets.
    Per-job state (checkpoint dirs, fault plans, per-job verify)
    opts a job out — its dispatch is not a pure function of the
    fingerprint."""
    if getattr(spec, "resume_dir", None):
        return None
    if getattr(spec, "fault_plan", None) is not None:
        return None
    if getattr(spec, "verify", False):
        return None
    env = getattr(spec, "env", None) or {}
    return (
        getattr(spec, "module", None),
        tuple(getattr(spec, "cmd", None) or ()),
        int(getattr(spec, "nproc", 1)),
        float(getattr(spec, "timeout_s", 0.0) or 0.0),
        int(getattr(spec, "retries", 0)),
        float(getattr(spec, "backoff_s", 0.5)),
        tuple(sorted(env.items())),
    )


def coalesce(specs: List[Any]) -> List[List[Any]]:
    """Group claimed specs into dispatch groups, preserving claim
    order: the first job of each fingerprint anchors its group's
    position (FIFO fairness over packing greed), later same-shape jobs
    fold into it. Non-coalescible specs ride alone."""
    groups: List[List[Any]] = []
    by_key: Dict[Tuple, List[Any]] = {}
    for spec in specs:
        key = coalesce_key(spec)
        if key is None:
            groups.append([spec])
            continue
        group = by_key.get(key)
        if group is None:
            group = [spec]
            by_key[key] = group
            groups.append(group)
        else:
            group.append(spec)
    return groups


# ---------------------------------------------------------------------
# dispatch-plane accounting (status + exporter surface)
# ---------------------------------------------------------------------

#: batch-size samples retained for the exporter's quantiles
_MAX_SAMPLES = 1024


class DispatchStats:
    """Counters the event-driven loop maintains and persists to
    ``<root>/dispatch.json``: the active wire, wakeups per wire,
    claim-batch sizes, coalescing and group-commit tallies. All
    methods are cheap and none ever raises."""

    def __init__(self, wire: str):
        self.wire = str(wire)
        self.wakeups: Dict[str, int] = {}
        self.batches = 0
        self.batch_sizes: List[int] = []
        self.jobs = 0
        self.coalesced_jobs = 0
        self.groups = 0
        self.group_commits = 0
        self.committed_jobs = 0

    def wakeup(self, wire: str, n: int = 1) -> None:
        self.wakeups[wire] = self.wakeups.get(wire, 0) + int(n)

    def batch(self, size: int) -> None:
        self.batches += 1
        self.jobs += int(size)
        self.batch_sizes.append(int(size))
        if len(self.batch_sizes) > _MAX_SAMPLES:
            del self.batch_sizes[: len(self.batch_sizes) - _MAX_SAMPLES]

    def group(self, size: int) -> None:
        self.groups += 1
        if size > 1:
            # jobs that shared a dispatch they would each have paid for
            self.coalesced_jobs += int(size)

    def group_commit(self, jobs: int) -> None:
        if jobs > 0:
            self.group_commits += 1
            self.committed_jobs += int(jobs)

    def to_json(self) -> Dict[str, Any]:
        sizes = sorted(self.batch_sizes)

        def pct(q: float) -> Optional[int]:
            if not sizes:
                return None
            i = min(len(sizes) - 1, int(round(q * (len(sizes) - 1))))
            return sizes[i]

        jobs = max(1, self.committed_jobs)
        return {
            "schema": DISPATCH_SCHEMA,
            "wire": self.wire,
            "wakeups": dict(self.wakeups),
            "batches": self.batches,
            "jobs": self.jobs,
            "batch_size_p50": pct(0.50),
            "batch_size_p90": pct(0.90),
            "batch_size_max": (sizes[-1] if sizes else None),
            "groups": self.groups,
            "coalesced_jobs": self.coalesced_jobs,
            "group_commits": self.group_commits,
            # 1 submit fsync per job + 1 group-commit fsync per flush:
            # the group-commit effect the exporter graphs (< 2.0 at
            # load; the cp profiler measures the exact figure)
            "fsyncs_per_job": (
                round(1.0 + self.group_commits / jobs, 4)
                if self.committed_jobs else None
            ),
            "t": time.time(),
        }

    def write(self, root: str) -> None:
        path = os.path.join(os.path.abspath(root), SNAPSHOT_NAME)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(self.to_json(), f, indent=1)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def load_snapshot(root: str) -> Optional[Dict[str, Any]]:
    """The persisted dispatch-plane counters for a spool root, or None
    when no event-driven loop ever served it."""
    path = os.path.join(os.path.abspath(root), SNAPSHOT_NAME)
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(rec, dict) or rec.get("schema") != DISPATCH_SCHEMA:
        return None
    return rec


# ---------------------------------------------------------------------
# selftest + CLI
# ---------------------------------------------------------------------


def selftest() -> int:
    """Device-free proof of the dispatch plane: every wire round-trips
    (or falls back cleanly), coalescing preserves ids and order,
    batched claims lease every id exactly once under racing servers,
    group commit lands one terminal record per job with a single
    fsync, and the full fastpath serve loop drains a stub mix."""
    import tempfile

    from .scheduler import FairScheduler
    from .server import Server
    from .spool import Spool

    # -- wires --------------------------------------------------------
    with tempfile.TemporaryDirectory() as tmp:
        watch = os.path.join(tmp, "pending")
        os.makedirs(watch)
        # poll: bounded, eventless
        lst = open_listener(watch, prefer="poll")
        assert lst.wire == WIRE_POLL, lst.wire
        t0 = time.monotonic()
        assert lst.wait(0.01) == []
        assert time.monotonic() - t0 < 1.0
        lst.close()
        # socket: advertise -> notify -> event, retract on close
        lst = open_listener(watch, advertise_dir=tmp, prefer="socket")
        assert lst.wire == WIRE_SOCKET, lst.wire
        assert os.path.exists(os.path.join(tmp, WAKE_NAME))
        assert notify(tmp, job="jx")
        evs = lst.wait(2.0)
        assert any(e.get("job") == "jx" for e in evs), evs
        lst.close()
        assert not os.path.exists(os.path.join(tmp, WAKE_NAME))
        assert not notify(tmp, job="jy")  # nobody listening: no-op
        # inotify (where the host has it): the rename is the event
        if inotify_available():
            lst = open_listener(watch, prefer="inotify")
            assert lst.wire == WIRE_INOTIFY, lst.wire
            name = f"{time.time_ns():020d}-jz.json"
            tmp_path = os.path.join(watch, f".tmp-{name}")
            with open(tmp_path, "w") as f:
                f.write("{}")
            os.replace(tmp_path, os.path.join(watch, name))
            evs = lst.wait(2.0)
            assert any(e.get("job") == "jz" for e in evs), evs
            lst.close()

    # -- coalescing ---------------------------------------------------
    from .spool import parse_job

    same = [parse_job({"id": f"c{i}", "cmd": ["-c", "pass"]})
            for i in range(3)]
    odd = parse_job({"id": "odd", "cmd": ["-c", "print(1)"]})
    solo = parse_job({"id": "solo", "cmd": ["-c", "pass"],
                      "resume_dir": "/tmp/x"})
    groups = coalesce([same[0], odd, same[1], solo, same[2]])
    shapes = [[s.id for s in g] for g in groups]
    assert shapes == [["c0", "c1", "c2"], ["odd"], ["solo"]], shapes
    assert coalesce_key(solo) is None

    # -- batched claims: every id exactly once under racing servers --
    with tempfile.TemporaryDirectory() as tmp:
        spool = Spool(os.path.join(tmp, "spool"))
        spool.configure(32)
        for i in range(8):
            r = spool.submit({"id": f"b{i}", "cmd": ["-c", "pass"]})
            assert r["status"] == "queued", r
        wins: Dict[str, List[str]] = {}
        barrier = threading.Barrier(3)

        def racer(sid: str) -> None:
            mine = spool.pending()
            barrier.wait()
            won = spool.claim_batch(mine, server=sid)
            wins[sid] = [s.id for s in won]

        threads = [threading.Thread(target=racer, args=(f"s{i}",))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        claimed = [j for ids in wins.values() for j in ids]
        assert sorted(claimed) == [f"b{i}" for i in range(8)], wins

    # -- fairness across a batch boundary -----------------------------
    sched = FairScheduler()
    mix = [parse_job({"id": f"f{i}", "tenant": t, "cmd": ["-c", "pass"]})
           for i, t in enumerate(["a", "a", "a", "b", "c"])]
    picked = sched.pick_batch(mix, 3)
    assert [s.id for s in picked] == ["f0", "f3", "f4"], [
        s.id for s in picked
    ]  # round-robin across the batch, not 3x tenant a
    sched.commit_batch(picked)
    rest = [s for s in mix if s not in picked]
    again = sched.pick_batch(rest, 3)
    assert [s.id for s in again] == ["f1", "f2"], [s.id for s in again]

    # -- the fastpath loop end to end (group commit + coalescing) -----
    with tempfile.TemporaryDirectory() as tmp:
        spool = Spool(os.path.join(tmp, "spool"))
        spool.configure(32)
        for i in range(6):
            r = spool.submit({
                "id": f"e{i}", "tenant": f"t{i % 2}",
                "cmd": ["-c", "pass"],
            })
            assert r["status"] == "queued", r
        runs: List[int] = []

        def runner(spec: Any, world: int, *a: Any) -> Tuple[int, List]:
            runs.append(world)
            return 0, []

        server = Server(
            spool, nproc=1, max_jobs=6, poll_s=0.02,
            fastpath="socket", runner=runner, log=lambda m: None,
        )
        assert server.serve() == 0
        done = {r["id"]: r for r in spool.done()}
        assert sorted(done) == [f"e{i}" for i in range(6)], sorted(done)
        assert all(r["outcome"] == "completed" for r in done.values())
        # coalescing: 6 same-shape jobs took < 6 world executions
        assert 0 < len(runs) < 6, runs
        snap = load_snapshot(spool.root)
        assert snap is not None and snap["wire"] == WIRE_SOCKET, snap
        assert snap["jobs"] == 6, snap
        assert snap["coalesced_jobs"] > 0, snap
        assert snap["group_commits"] >= 1, snap

    print("dispatch selftest ok")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    argv = list(sys.argv[1:] if argv is None else argv)
    if "--selftest" in argv:
        return selftest()
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.serving.dispatch",
        description="Inspect a spool's event-driven dispatch plane "
        "(serve with --fastpath to populate it).",
    )
    parser.add_argument("spool", help="spool root directory")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)
    snap = load_snapshot(args.spool)
    if snap is None:
        print(
            "no dispatch snapshot — serve this spool with --fastpath",
            file=sys.stderr,
        )
        return 2
    if args.json:
        print(json.dumps(snap, indent=1, sort_keys=True))
        return 0
    wakeups = snap.get("wakeups") or {}
    print(
        f"dispatch: event-driven (wire: {snap.get('wire')}, "
        f"{sum(wakeups.values())} wakeup(s), "
        f"{snap.get('batches', 0)} batch(es) / {snap.get('jobs', 0)} "
        f"job(s), batch p50 {snap.get('batch_size_p50')}, "
        f"{snap.get('coalesced_jobs', 0)} coalesced, "
        f"{snap.get('group_commits', 0)} group commit(s), "
        f"fsyncs/job {snap.get('fsyncs_per_job')})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
