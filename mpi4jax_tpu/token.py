"""Program-order sequencing of communication ops (token chain).

The reference guarantees that all communication calls inside a jitted
program execute in program order by registering a single JAX *ordered
effect* and threading XLA's runtime token through every lowering
(``_src/utils.py:45-53``, ``_src/jax_compat.py:74-100``). That design
cannot be reused here: ordered effects are rejected inside
``shard_map``, which is where TPU-native collectives live.

Equivalent TPU-native mechanism: an ambient *value token* — a scalar
``uint32`` threaded through ``lax.optimization_barrier`` ties around
every op:

    x', tok = optimization_barrier((x, tok_in))     # op can't hoist
    y = collective(x')
    tok_out, _ = optimization_barrier((tok, y))     # successor waits

``optimization_barrier`` is a real HLO op: XLA may not move computation
across it, so op N+1's collective transitively depends on op N's result
— the same happens-before edge the reference gets from token threading.
Within one SPMD program this is belt-and-braces (every rank runs the
*same* program, so any reorder is identical everywhere and cannot
deadlock, unlike the reference's per-rank programs —
``tests/collective_ops/test_send_and_recv.py:91-110``), but it pins the
op order deterministically, which keeps collective schedules stable and
profiles comparable.

The ambient token lives in a small per-trace registry keyed on
``jax.core.get_opaque_trace_state()``; entering a new trace starts a
fresh chain (tokens never leak across jit boundaries). The registry
also hosts the point-to-point *channel matcher* used by
``send``/``recv`` (see ``ops/_p2p.py``).
"""

from __future__ import annotations

import collections
from typing import Any, Deque, Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import config
from .jax_compat import get_opaque_trace_state as _get_opaque_trace_state

_MAX_TRACE_STATES = 64


class _TraceState:
    __slots__ = ("key", "token", "pending_sends", "shm_wire")

    def __init__(self, key):
        self.key = key
        self.token = jnp.zeros((), jnp.uint32)
        # FIFO of pending sends for the trace-time send/recv matcher.
        self.pending_sends: List[Dict[str, Any]] = []
        # shm backend wire: an output of the previous native call,
        # threaded as a real operand into the next one (see shm_wire()).
        self.shm_wire = None


_states: Deque[_TraceState] = collections.deque(maxlen=_MAX_TRACE_STATES)

#: (trace key, error text) pairs for states evicted while holding
#: unmatched sends; the *offending* trace's next op raises the error
#: (see _current_state) so the failure lands on the buggy program, not
#: on whatever bystander computation happened to allocate the state
#: that triggered the eviction. A list because OpaqueTraceState is
#: unhashable (compared by ==, like _states).
_poisoned: Deque[Tuple[Any, str]] = collections.deque(maxlen=_MAX_TRACE_STATES)


def _current_state() -> _TraceState:
    key = _get_opaque_trace_state()
    for i, (pkey, msg) in enumerate(_poisoned):
        if pkey == key:
            del _poisoned[i]
            raise RuntimeError(msg)
    for st in _states:
        if st.key == key:
            return st
    evicted = None
    if len(_states) == _states.maxlen:
        evicted = _states.popleft()
    st = _TraceState(key)
    _states.append(st)
    if evicted is not None and evicted.pending_sends:
        # Evicting a state with unmatched sends: raising *here* would
        # fail whatever unrelated computation allocated the 65th state,
        # far from the buggy code — so warn loudly (identifying the
        # offender) and arrange for the offending trace itself to raise
        # if it ever issues another op. If it never does, the warning
        # is the only signal, which is sound: an unmatched send emits
        # no collective at all, and the only party that could observe
        # missing data — the matching recv — fails hard on its own
        # ("no matching send", ops/p2p.py) whichever trace it is in;
        # parallel.spmd additionally hard-errors at trace end
        # (check_no_pending_sends).
        import warnings

        tags = [rec["tag"] for rec in evicted.pending_sends]
        msg = (
            f"mpi4jax_tpu: {len(evicted.pending_sends)} send(s) (tags "
            f"{tags}) were never matched by a recv in their traced "
            f"program (trace state {evicted.key!r}) and their trace "
            "state was evicted. On the TPU backend a send must be "
            "paired with a recv inside the same jit/shard_map trace."
        )
        warnings.warn(msg, RuntimeWarning, stacklevel=3)
        _poisoned.append((evicted.key, msg))
    return st


def check_no_pending_sends() -> None:
    """Raise if the current trace holds sends that were never matched
    by a recv — called at the end of ``parallel.spmd`` bodies so the
    primary entry point fails loudly instead of silently dropping a
    transfer. (For raw ``shard_map`` users, state eviction emits a
    RuntimeWarning and poisons the offending trace, which raises on its
    next op; the matching recv — the only consumer of the lost data —
    always fails hard on its own. See ``_current_state``.)"""
    st = _current_state()
    if st.pending_sends:
        tags = [rec["tag"] for rec in st.pending_sends]
        raise RuntimeError(
            f"{len(st.pending_sends)} send(s) (tags {tags}) were never "
            "matched by a recv in this traced program; on the TPU backend "
            "every send must pair with a recv in the same trace "
            "(mpi4jax_tpu/ops/p2p.py docstring)."
        )


def _no_active_trace() -> bool:
    """True only in plain eager execution (no jit/shard_map/vmap/grad
    trace anywhere on the stack). Checking the trace *state* — not
    whether particular operand values are tracers — matters: inside a
    trace, ops on closed-over constants (``barrier``'s literal token
    operand especially) must still get their ties, or the collective
    has no consumers and XLA DCEs it. Best-effort on a private API:
    returns False (keep the ties) if it moves."""
    try:
        from jax._src import core as _core

        return bool(_core.trace_state_clean())
    except Exception:
        return False


def ordered_call(fn, inputs: Tuple):
    """Run ``fn(*inputs)`` with its inputs tied to the ambient token
    and the token advanced past its outputs.

    ``fn`` returns a tuple of arrays. Returns that tuple.

    Plain eager calls (no active trace) skip the ties: XLA executes
    eager dispatches in submission order per device, so program order
    already holds and each ``optimization_barrier`` would only add a
    dispatch round-trip (the reference's eager ops likewise run
    straight through ``apply_primitive``, ``_src/utils.py:56-57``).
    The shm backend's cross-call ordering is carried by the operand
    wire either way (``shm_wire``).
    """
    if config.NO_ORDERING or _no_active_trace():
        # fast path before any trace-state lookup: plain eager calls
        # must not pay the deque scan / state allocation either
        return tuple(fn(*inputs))
    st = _current_state()
    token = st.token
    if inputs:
        tied = lax.optimization_barrier(tuple(inputs) + (token,))
        inputs, token = tied[:-1], tied[-1]
    outputs = tuple(fn(*inputs))
    if outputs:
        advanced = lax.optimization_barrier((token,) + outputs)
        st.token = advanced[0]
        outputs = advanced[1:]
    else:
        st.token = token
    return outputs


def pending_sends() -> List[Dict[str, Any]]:
    return _current_state().pending_sends


def drain_pending_sends() -> List[Tuple[Any, List[Dict[str, Any]]]]:
    """Return and clear *every* trace state's unmatched sends (and any
    pending poison markers), as ``(trace_key, [send records])`` pairs.

    Two consumers: the test harness's teardown leak check (a test that
    leaks a send must fail itself, not poison whichever later test
    next touches the evicted state), and the static linter, which
    reports sends left pending when its trace closed as M4T103
    findings. Unlike :func:`check_no_pending_sends` this inspects all
    registered states, not just the caller's current trace — a leaked
    send lives under the *traced program's* key, which the caller (in
    eager context at teardown time) no longer occupies."""
    leaks: List[Tuple[Any, List[Dict[str, Any]]]] = []
    for st in _states:
        if st.pending_sends:
            leaks.append((st.key, list(st.pending_sends)))
            st.pending_sends.clear()
    _poisoned.clear()
    return leaks


def shm_wire():
    """Current shm-backend wire value for this trace (or None).

    The ``optimization_barrier`` value-token chain above is *advisory*:
    XLA (the CPU pipeline in particular) may delete the barriers, after
    which two independent side-effecting custom calls can be scheduled
    in either order — for the blocking shm runtime that reorder is a
    deadlock (a rank's recv scheduled before its own send). The shm
    path therefore also threads a **real operand**: every native call
    consumes the previous call's output (ignored by the handler) and
    publishes one of its own outputs as the next wire — producer/
    consumer edges no compiler pass may break. This is the reference's
    XLA-token threading (``_src/jax_compat.py:74-77``) realized with
    value tokens.
    """
    return _current_state().shm_wire


def set_shm_wire(value) -> None:
    _current_state().shm_wire = value


class NOTSET:
    """Sentinel for the removed explicit-token API (the reference
    errors with a migration message if ``token=`` is passed,
    ``_src/utils.py:30-42``)."""


def raise_if_token_is_set(token) -> None:
    if token is not NOTSET:
        raise TypeError(
            "mpi4jax_tpu ops sequence themselves automatically; the "
            "explicit token argument is not supported (parity with the "
            "reference's post-0.8 API, _src/utils.py:30-42)."
        )
