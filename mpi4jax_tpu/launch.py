"""Multi-process launcher: the framework's ``mpirun``.

The reference's CPU workflow is ``mpirun -n N python script.py``
(``README.rst:83-88``) with libmpi doing rendezvous and transport.
This launcher reproduces that workflow on the native shared-memory
backend:

    python -m mpi4jax_tpu.launch -n 4 script.py [args...]
    python -m mpi4jax_tpu.launch -n 2 -m pytest tests/

Each child process imports ``mpi4jax_tpu``, joins the shm world named
in its environment (``runtime/shm.py:init_from_env``, the analog of
mpi4py's import-time ``MPI_Init``), and runs the script unchanged.
Fail-fast parity with the reference's ``MPI_Abort``
(``mpi_ops_common.h:60-78``): if any rank exits nonzero, the launcher
terminates the whole world and propagates the exit code.

Observability (``--events-dir DIR``): every rank writes a per-rank
JSONL event sink (``events-rank<k>.jsonl``, fsync'd), arms the flight
recorder to dump into ``DIR`` on the way down, and emits heartbeats —
the artifact layout the cross-rank doctor consumes. On any failure
(nonzero rank, or no progress within ``--hang-timeout`` seconds, the
``MPI_Abort``-less failure mode mpirun never diagnoses) the launcher
tears the world down and prints the doctor's diagnosis: which rank
diverged/hung at which collective sequence number.

Live telemetry plane (``--live`` / ``--dashboard`` /
``--metrics-port``): a launcher-side monitor tails the per-rank sinks
*while the world runs* (``observability/live.py``), streams the
doctor's verdicts (``stream_doctor.py``) and exports OpenMetrics
(``export.py``: ``DIR/metrics.prom`` snapshot + optional localhost
``/metrics`` endpoint). A hang **confirmed** by the streaming doctor
(the world stalled past ``--live-grace`` with a named wedged/behind
rank) tears the world down immediately with the diagnosis attached —
seconds after the wedge instead of at ``--hang-timeout`` — and
confirmed straggler/anomaly verdicts land as ``retune`` events in
``DIR/live.jsonl`` that ``--tune`` and ``planner tune
--from-verdicts`` feed back through the autotuner.

Pre-flight verification (``--verify``): before any rank spawns, the
target's ``M4T_LINT_TARGETS`` are linted and every rank's concrete
collective schedule is enumerated and simulated at ``-n`` ranks
(``analysis/{schedule,simulate}.py``); a deadlock (M4T201, with a
rank-cycle witness) or cross-rank order mismatch (M4T202) blocks the
launch — the bug the doctor would name post-mortem is named pre-spawn
instead, for free. ``--algo FILE`` sideloads declarative collective
algorithms (``m4t-algo/1``, ``planner/algo.py``) into every rank's
registry; under ``--verify`` each file is proven at ``-n`` ranks
(simulate + M4T204 chunk coverage + M4T205 cost admission) first, and
an armed plan naming an unproven ``algo:*`` impl blocks the same way.

Adaptive planning (``planner/``): ``--plan PLAN.json`` arms a tuned
collective plan cache in every rank (``M4T_PLAN_CACHE``) so plannable
collectives route per plan key; ``--tune`` (with ``--events-dir`` and
``--plan``) turns a clean run into a tuning run — ranks sample per-op
runtime latency, and afterwards the autotuner joins achieved GB/s
against the analytic cost model over the keys the run emitted and
pins the winners into the plan (``docs/planner.md``).

Resilience (``resilience/``): ``--fault-plan`` arms a deterministic
fault-injection plan in every rank (chaos testing); ``--retries K
--backoff S --resume-dir CKPTROOT`` runs the world under the
self-healing supervisor — failed attempts are diagnosed by the doctor
and classified: transient failures (hang, dead rank, plain crash,
preemption) restart from the latest valid checkpoint with exponential
backoff (``M4T_RESUME_STEP`` exported to the children), deterministic
ones (MISMATCH) fail fast with the diagnosis. With retries, each
attempt gets its own ``DIR/attempt<k>`` artifact directory and every
verdict lands in ``DIR/supervisor.jsonl``. ``--retries 0`` (the
default) is byte-for-byte the old single-attempt behavior.

Elastic resume (``--elastic --min-ranks K``, with retries and
``--resume-dir``): ranks that exit with the preemption signature
(``PREEMPT_EXIT`` 143 from a :class:`resilience.PreemptGuard` grace
exit, or an unhandled SIGTERM) are counted as *capacity lost* rather
than a bug — the next attempt restarts at the shrunk world, the newest
``m4t-ckpt/2`` checkpoint is resharded N→M offline through a planned
schedule whose peak scratch is bounded by two shard sizes
(``resilience/reshard.py``), ``--verify`` re-proves the target
deadlock-free at M ranks before any rank spawns, and the plan cache's
world-keyed entries simply stop matching at M (plan keys include
world), so collective routing falls back to the default policy by
construction. The ``supervisor.jsonl`` audit records every world-size
transition (old world, new world, reshard source step) and the doctor
narrates them post-mortem.

Serving plane (``mpi4jax_tpu/serving/``, ``python -m
mpi4jax_tpu.serving serve``): a long-lived queue-draining supervisor
multiplexes many submitted jobs over this machine through the same
spawn path — :func:`make_world_args` + :func:`spawn_world` are the
reuse seam it (and any other harness) drives, so per-rank environment
construction lives in exactly one place (:func:`rank_env`).
"""

from __future__ import annotations

import argparse
import os
import random
import signal
import subprocess
import sys
import time
import uuid


def _run_doctor(events_dir):
    """Post-mortem: merge the per-rank artifacts in ``events_dir`` and
    print the cross-rank diagnosis. Never raises — the diagnosis must
    not mask the exit code it is explaining."""
    try:
        from .observability import doctor

        report = doctor.diagnose([events_dir])
        if report is None:
            sys.stderr.write(
                f"mpi4jax_tpu.launch: no telemetry records in "
                f"{events_dir}; nothing to diagnose\n"
            )
            return
        sys.stderr.write(
            "mpi4jax_tpu.launch: post-mortem diagnosis "
            f"({events_dir}):\n{doctor.format_report(report)}\n"
        )
    except Exception as exc:  # pragma: no cover — diagnosis best-effort
        sys.stderr.write(f"mpi4jax_tpu.launch: doctor failed: {exc!r}\n")


def _run_perf_report(events_dir):
    """``--perf``: join the per-rank latency events against the
    analytic cost model and print the achieved-bandwidth table.
    Best-effort like the doctor."""
    try:
        from .observability import doctor, perf

        by_rank = doctor.load([events_dir])
        if not by_rank:
            sys.stderr.write(
                f"mpi4jax_tpu.launch: no telemetry records in "
                f"{events_dir}; no perf attribution\n"
            )
            return
        sys.stderr.write(
            "mpi4jax_tpu.launch: perf attribution "
            f"({events_dir}):\n{perf.format_table(perf.attribute(by_rank))}\n"
        )
    except Exception as exc:  # pragma: no cover — attribution best-effort
        sys.stderr.write(f"mpi4jax_tpu.launch: perf report failed: {exc!r}\n")


def _run_tune(events_dir, plan_path):
    """``--tune``: post-run autotuning over the artifacts this world
    just wrote — derive per-impl achieved bandwidth via the perf
    attribution join, sweep the keys the run actually emitted (cost-
    model seeded, plus any keys the streaming doctor's ``retune``
    recommendations name), and pin the winners into ``plan_path``
    (merged over any existing cache). Best-effort like the doctor: a
    tune failure must not change the run's exit code."""
    try:
        from . import config
        from .planner import autotune, plan as _plan

        platform = config.PLATFORM_CLASS or "cpu"
        table = autotune.measured_table_from_events(
            [events_dir], platform=platform
        )
        keys = autotune.keys_from_events([events_dir], platform=platform)
        # the closed loop: live straggler/anomaly verdicts recommend
        # keys too (normally a subset of the emitted set, but a
        # rotated-away emission can survive only in its verdict)
        vkeys = autotune.keys_from_verdicts(
            [events_dir], platform=platform
        )
        keys += [k for k in vkeys if k not in keys]
        if not keys:
            sys.stderr.write(
                "mpi4jax_tpu.launch: --tune: no plannable emissions in "
                f"{events_dir}; nothing to tune\n"
            )
            return
        if vkeys:
            sys.stderr.write(
                f"mpi4jax_tpu.launch: --tune: {len(vkeys)} key(s) "
                "flagged by live retune recommendations\n"
            )
        planobj, report = autotune.sweep(keys, measured=table)
        if os.path.exists(plan_path):
            try:
                planobj = _plan.merge(
                    _plan.load(plan_path, platform=platform), planobj
                )
            except _plan.PlanError as exc:
                sys.stderr.write(
                    f"mpi4jax_tpu.launch: --tune: replacing invalid "
                    f"cache {plan_path}: {exc} [{exc.reason}]\n"
                )
        _plan.save(planobj, plan_path)
        measured_n = sum(1 for r in report if r["source"] == "measured")
        sys.stderr.write(
            f"mpi4jax_tpu.launch: --tune: pinned {len(keys)} key(s) "
            f"({measured_n} measured) into plan {planobj.plan_id} at "
            f"{plan_path}\n"
        )
    except Exception as exc:  # pragma: no cover — tuning best-effort
        sys.stderr.write(f"mpi4jax_tpu.launch: --tune failed: {exc!r}\n")


def _run_overlap_report(events_dir):
    """``--overlap``: print the exposed-communication summary over the
    artifacts this world just wrote. Best-effort like the doctor — a
    report failure must not change the run's exit code."""
    try:
        from .observability import doctor, overlap

        rep = overlap.build_report(doctor.load([events_dir]))
        if not rep["ranks"]:
            sys.stderr.write(
                "mpi4jax_tpu.launch: --overlap: no step spans in "
                f"{events_dir}; wrap the step loop in obs.step_span()\n"
            )
            return
        sys.stderr.write(
            "mpi4jax_tpu.launch: overlap attribution "
            f"({events_dir}):\n{overlap.format_exposed(rep)}\n"
        )
    except Exception as exc:  # pragma: no cover — report best-effort
        sys.stderr.write(
            f"mpi4jax_tpu.launch: overlap report failed: {exc!r}\n"
        )


def _propose_placement(events_dir, audit_path=None):
    """Close the confirmed-straggler retune loop (ROADMAP item 1
    follow-on): when the live plane confirmed a straggler and the
    evidence is link-localized, derive a re-permutation proposal from
    the verdicts (``planner/placement.derive_from_verdicts``), prove
    it, write it beside the artifacts as ``placement-proposal.json``,
    and audit the proposal in ``supervisor.jsonl``. Never arms
    anything by itself — the operator (or the next launch) picks the
    proposal up explicitly via ``--place``. Best-effort."""
    try:
        from .observability import events
        from .planner import placement

        doc, evidence = placement.derive_from_verdicts([events_dir])
        if doc is None:
            reason = evidence.get("reason", "no evidence")
            if evidence.get("verdicts"):
                # only narrate when there *were* verdicts to act on
                sys.stderr.write(
                    "mpi4jax_tpu.launch: no placement proposal: "
                    f"{reason}\n"
                )
            return
        reports = placement.verify(doc)
        from .analysis import placement_check

        if not placement_check.reports_clean(reports):
            sys.stderr.write(
                "mpi4jax_tpu.launch: placement proposal failed "
                "M4T206 verification; discarded\n"
            )
            return
        doc = dict(doc)
        doc["proof"] = placement.build_proof(doc, reports)
        out = os.path.join(events_dir, "placement-proposal.json")
        placement.save(doc, out)
        record = {
            "event": "placement_proposal",
            "perm": doc["perm"],
            "method": doc["method"],
            "expected_s": doc["expected_s"],
            "identity_s": doc["identity_s"],
            "gain": doc.get("gain"),
            "fingerprint": doc["fingerprint"],
            "path": out,
            "evidence": doc.get("verdict_evidence"),
        }
        if audit_path:
            try:
                events.EventLog(audit_path).append(
                    events.event("supervisor", **record)
                )
            except OSError:
                pass
        sys.stderr.write(
            "mpi4jax_tpu.launch: straggler verdicts propose "
            f"re-permutation {doc['perm']} "
            f"(expected {doc['expected_s']:.3g}s vs identity "
            f"{doc['identity_s']:.3g}s) — written to {out}; arm with "
            "--place to apply\n"
        )
    except Exception as exc:  # pragma: no cover — proposal best-effort
        sys.stderr.write(
            f"mpi4jax_tpu.launch: placement proposal failed: {exc!r}\n"
        )


def _verify_prelaunch(args, world=None) -> int:
    """``--verify``: prove the target's collective schedules
    deadlock-free at ``-n`` ranks *before any rank spawns*.

    The target script/module must declare its per-rank entry points in
    ``M4T_LINT_TARGETS`` (the linter convention, docs/static-analysis.md).
    Every target is linted (M4T1xx) and its per-rank schedule is
    enumerated and simulated (M4T2xx): any error-severity finding — a
    deadlock with a rank-cycle witness, a cross-rank order mismatch,
    an unprovable schedule — blocks the launch with exit 1. A target
    that declares no entry points is a warning, not a block (there is
    nothing to verify). Returns 0 to proceed.

    ``world`` overrides ``-n`` — the elastic supervisor re-proves the
    target at the *shrunk* world before respawning (a program
    deadlock-free at 4 ranks is not automatically deadlock-free at 2).
    """
    world = args.nproc if world is None else int(world)

    # --algo files gate first: a sideloaded collective algorithm that
    # deadlocks (M4T201), drops a chunk (M4T204), or breaks its cost
    # contract (M4T205) at *this* world must never reach a rank's
    # registry. Same verdict surface as `planner algo check`.
    algo_files = list(getattr(args, "algo", None) or ())
    if algo_files:
        from .analysis import algo_check

        blocked_algos = False
        for path in algo_files:
            sys.stderr.write(
                f"mpi4jax_tpu.launch: --verify: proving algorithm "
                f"{path!r} at n={world} before spawning\n"
            )
            reports = algo_check.check_file(path, [world])
            for rep in reports:
                sys.stderr.write(rep.to_text() + "\n")
            if not algo_check.reports_clean(reports):
                blocked_algos = True
        if blocked_algos:
            sys.stderr.write(
                "mpi4jax_tpu.launch: --verify BLOCKED the launch: an "
                "--algo file failed verification at this world — no "
                "rank was spawned. Fix the findings above or drop the "
                "algorithm.\n"
            )
            return 1

    # an armed plan routing through an unregistered (unproven or
    # proof-stale) algorithm impl is the same class of failure:
    # refuse pre-spawn with the registry's reason, not at step 1.
    plan_path = getattr(args, "plan", None)
    if plan_path and os.path.exists(plan_path):
        from .planner import algo as _algomod
        from .planner import plan as _planmod

        try:
            armed = _planmod.load(plan_path)
        except Exception:
            armed = None  # main's own --plan validation reports this
        if armed is not None:
            bad = []
            for key, ent in sorted(armed.entries.items()):
                impl = getattr(ent, "impl", "")
                if impl.startswith("algo:") and _algomod.get(impl) is None:
                    bad.append((key, impl))
            if bad:
                for key, impl in bad:
                    sys.stderr.write(
                        f"mpi4jax_tpu.launch: --verify: plan entry "
                        f"{key!r} routes through {impl!r}, which is "
                        f"not a registered (proof-verified) "
                        f"algorithm\n"
                    )
                sys.stderr.write(
                    "mpi4jax_tpu.launch: --verify BLOCKED the launch: "
                    "the armed plan names unproven algorithm impl(s) "
                    "— no rank was spawned. Re-prove them (`python -m "
                    "mpi4jax_tpu.planner algo check --write-proof`) "
                    "or re-tune the plan.\n"
                )
                return 1

    target = args.module if args.module else args.cmd[0]
    sys.stderr.write(
        f"mpi4jax_tpu.launch: --verify: proving {target!r} "
        f"deadlock-free at n={world} before spawning\n"
    )
    try:
        from .analysis import lint_module, verify_module
        from .analysis.__main__ import _import_target

        module, _fn = _import_target(target)
    except Exception as exc:
        sys.stderr.write(
            f"mpi4jax_tpu.launch: --verify: cannot import {target!r}: "
            f"{exc}\n"
        )
        return 1
    try:
        lint_reports = lint_module(module, world=world)
        sim_reports = verify_module(module, world=world)
    except Exception as exc:
        sys.stderr.write(
            f"mpi4jax_tpu.launch: --verify failed: {exc!r}\n"
        )
        return 1
    if not sim_reports and not lint_reports:
        sys.stderr.write(
            f"mpi4jax_tpu.launch: --verify: {target!r} declares no "
            f"M4T_LINT_TARGETS (at world {world}); nothing to "
            "verify — proceeding\n"
        )
        return 0
    blocked = False
    for rep in lint_reports:
        errs = [f for f in rep.findings if f.severity == "error"]
        if rep.error is not None or errs:
            blocked = True
            sys.stderr.write(rep.to_text() + "\n")
    for rep in sim_reports:
        if rep.verdict != "deadlock-free" and (
            rep.verdict in ("unprovable", "error")
            or any(f.severity == "error" for f in rep.findings)
        ):
            blocked = True
        sys.stderr.write(rep.to_text() + "\n")
    if blocked:
        sys.stderr.write(
            "mpi4jax_tpu.launch: --verify BLOCKED the launch: the "
            "schedule simulator found a deadlock/mismatch (or could "
            "not prove its absence) — no rank was spawned. Fix the "
            "findings above or launch without --verify.\n"
        )
        return 1
    sys.stderr.write(
        f"mpi4jax_tpu.launch: --verify: {len(sim_reports)} target(s) "
        f"proved deadlock-free at n={world}; spawning\n"
    )
    return 0


def _place_prelaunch(args, world=None) -> int:
    """``--place``: arm a rank-placement permutation only after its
    M4T206 schedule-equivalence proof holds at this world, *before any
    rank spawns*.

    Truth over trust: the stamped proof is necessary but not
    sufficient — the simulator re-runs over the permuted edge mapping
    here, so a placement proven against yesterday's registry still
    re-proves against today's. Any failure (unreadable document,
    fingerprint drift, missing/stale proof, world mismatch, or a live
    M4T206 finding) blocks the launch with the witness on stderr and
    no rank spawned. On success ``M4T_PLACEMENT`` is exported, which
    ``rank_env`` copies into every rank: ``parallel.mesh.world_mesh``
    and ``comm.CartComm`` then apply the permutation transparently.
    """
    path = getattr(args, "place", None)
    if not path:
        return 0
    world = args.nproc if world is None else int(world)
    from .analysis import placement_check
    from .planner import placement as _placemod

    sys.stderr.write(
        f"mpi4jax_tpu.launch: --place: verifying placement {path!r} "
        f"(M4T206) at n={world} before spawning\n"
    )
    try:
        doc = _placemod.load(path)
    except _placemod.PlacementError as exc:
        sys.stderr.write(
            f"mpi4jax_tpu.launch: --place BLOCKED the launch: {path}: "
            f"{exc} [{exc.reason}] — no rank was spawned.\n"
        )
        return 1
    if int(doc.get("world") or 0) != world:
        sys.stderr.write(
            f"mpi4jax_tpu.launch: --place BLOCKED the launch: "
            f"placement {path} was derived for world {doc.get('world')}"
            f", this launch is -n {world} — no rank was spawned. "
            "Re-derive it (`python -m mpi4jax_tpu.planner placement "
            "derive --topo ...`).\n"
        )
        return 1
    stale = _placemod.proof_mismatch(doc)
    if stale is not None:
        sys.stderr.write(
            f"mpi4jax_tpu.launch: --place BLOCKED the launch: {path}: "
            f"{stale} — no rank was spawned. An unproven permutation "
            "must never route traffic; re-prove it (`python -m "
            "mpi4jax_tpu.planner placement derive`).\n"
        )
        return 1
    reports = _placemod.verify(doc)
    for rep in reports:
        sys.stderr.write(rep.to_text() + "\n")
    if not placement_check.reports_clean(reports):
        sys.stderr.write(
            "mpi4jax_tpu.launch: --place BLOCKED the launch: the "
            "permutation failed M4T206 re-verification (witnesses "
            "above) — no rank was spawned.\n"
        )
        return 1
    os.environ[_placemod.ENV_VAR] = _placemod.arm_string(doc)
    gain = doc.get("gain")
    sys.stderr.write(
        f"mpi4jax_tpu.launch: --place: permutation {doc['perm']} "
        f"verified against {len(reports)} program(s)"
        + (f" (expected gain {gain:.2f}x)" if gain else "")
        + "; arming M4T_PLACEMENT\n"
    )
    return 0


def _replace_placement_elastic(args, new_world, search_dirs) -> None:
    """Elastic placement: the shrunk world cannot reuse the old
    permutation (M4T206 proofs are per-world), so re-derive from the
    newest probed topology map restricted to the surviving ranks and
    re-prove at ``new_world`` — or disarm, loudly. Placement must
    never block a shrink it cannot help."""
    from .planner import placement as _placemod

    if not (getattr(args, "place", None)
            or os.environ.get(_placemod.ENV_VAR)):
        return
    from .analysis import placement_check
    from .observability import topology as _topology

    def _log(msg):
        sys.stderr.write(f"mpi4jax_tpu.launch: elastic: {msg}\n")

    topo = None
    try:
        found = _topology.find([d for d in search_dirs if d])
        if found:
            topo = _topology.load(found)
    except (OSError, ValueError):
        topo = None
    if topo is not None and int(topo.get("world") or 0) >= new_world:
        sub_edges = {
            k: v for k, v in (topo.get("edges") or {}).items()
            if max(_topology.parse_edge(k)) < new_world
        }
        sub = dict(topo, world=new_world, edges=sub_edges)
        try:
            doc = _placemod.derive(sub, source="elastic")
            reports = _placemod.verify(doc)
            if placement_check.reports_clean(reports):
                os.environ[_placemod.ENV_VAR] = _placemod.arm_string(doc)
                gain = doc.get("gain")
                _log(
                    f"re-derived placement {doc['perm']} at the shrunk "
                    f"world {new_world} (M4T206 verified"
                    + (f", expected gain {gain:.2f}x" if gain else "")
                    + ")"
                )
                return
            _log(
                f"re-derived placement failed M4T206 at world "
                f"{new_world}; disarming"
            )
        except (ValueError, _placemod.PlacementError) as exc:
            _log(f"placement re-derivation failed ({exc}); disarming")
    else:
        _log(
            f"no probed topology map covers the shrunk world "
            f"{new_world}; disarming placement"
        )
    os.environ.pop(_placemod.ENV_VAR, None)


#: rank exit signatures that read "preemption notice honored": the
#: PreemptGuard's graceful 143, or death by unhandled SIGTERM
_PREEMPT_RCS = (143, -signal.SIGTERM)


def make_world_args(**overrides):
    """An args namespace carrying every field :func:`spawn_world` and
    :func:`_verify_prelaunch` read, at the CLI defaults.

    The reuse seam for harnesses that spawn worlds without going
    through the argv parser — the serving plane
    (``mpi4jax_tpu/serving/``) builds one of these per job attempt.
    Unknown field names are a :class:`TypeError`, so a harness cannot
    silently set a flag the spawn path never reads.
    """
    args = argparse.Namespace(
        nproc=1, module=None, cmd=[],
        events_dir=None, hang_timeout=0.0, heartbeat=5.0,
        doctor=False, live=False, live_grace=None, dashboard=False,
        metrics_port=None, perf=False, overlap=False,
        plan=None, tune=False,
        verify=False, algo=None, place=None, static_check="off",
        fault_plan=None,
        retries=0, backoff=1.0, resume_dir=None,
        elastic=False, min_ranks=1,
        plan_cache_env=None, _live_report=None,
        trace_id=None, job_id=None,
        probe_topology=False,
    )
    for key, value in overrides.items():
        if not hasattr(args, key):
            raise TypeError(f"make_world_args: unknown field {key!r}")
        setattr(args, key, value)
    return args


def rank_env(
    rank,
    world,
    *,
    shm_name,
    shm_gen,
    launcher_pid=None,
    base_env=None,
    extra_env=None,
    events_dir=None,
    heartbeat=5.0,
    static_check="off",
    fault_plan=None,
    fault_attempt=0,
    plan_cache=None,
    resume_step=None,
    runtime_sampling=False,
    perf_watch=False,
    overlap=False,
    mesh=True,
    trace_id=None,
    job_id=None,
):
    """The environment one spawned rank runs under — world membership
    (shm segment name + generation nonce + rank/size), telemetry
    arming, plan cache, fault plan, and resume step. Extracted from
    the spawn loop so every harness that launches ranks (the CLI
    launcher, the serving plane, tests) builds rank environments
    through one seam and cannot drift.

    ``mesh=False`` keeps the rank *identity* (``M4T_RANK`` /
    ``M4T_SIZE`` — telemetry, fault scoping, group bookkeeping) but
    withholds the shm segment coordinates, so importing the package
    does **not** join a native world. The serving plane's resident
    worker pool (``serving/pool.py``) spawns un-meshed workers by
    default: warm processes that serve in-process payloads and can be
    killed/respawned one at a time without wedging segment peers.

    ``trace_id``/``job_id`` export the serving plane's per-job trace
    context (``M4T_TRACE_ID``/``M4T_JOB_ID``): every telemetry record
    the rank writes then carries the job's trace id, which is what
    lets the multi-plane trace merge and the SLO attribution join a
    job's collective slices to its lifecycle spans."""
    env = dict(os.environ if base_env is None else base_env)
    if extra_env:
        env.update({str(k): str(v) for k, v in extra_env.items()})
    env.update(
        M4T_RANK=str(rank),
        M4T_SIZE=str(world),
        # world membership is for *direct* children only:
        # runtime/shm.py refuses to join when the parent pid doesn't
        # match, so a rank's own subprocesses (pytest spawning helper
        # scripts) never attach as duplicate ranks of the live world
        M4T_LAUNCHER_PID=str(
            os.getpid() if launcher_pid is None else launcher_pid
        ),
        JAX_PLATFORMS="cpu",
    )
    if mesh:
        env.update(
            M4T_SHM_NAME=shm_name,
            M4T_SHM_GEN=str(shm_gen),
        )
    else:
        # an un-meshed worker must not inherit a live world's segment
        # coordinates from the harness environment either
        env.pop("M4T_SHM_NAME", None)
        env.pop("M4T_SHM_GEN", None)
    if static_check and static_check != "off":
        env["M4T_STATIC_CHECK"] = static_check
    if fault_plan:
        env["M4T_FAULT_PLAN"] = fault_plan
        env["M4T_FAULT_ATTEMPT"] = str(fault_attempt)
    if plan_cache:
        # arm the collective plan cache in every rank
        # (planner/dispatch.py validates and arms at import)
        env["M4T_PLAN_CACHE"] = plan_cache
    if resume_step is not None:
        env["M4T_RESUME_STEP"] = str(resume_step)
    if trace_id:
        env["M4T_TRACE_ID"] = str(trace_id)
    if job_id:
        env["M4T_JOB_ID"] = str(job_id)
    if events_dir:
        # literal {rank} on purpose: each child resolves the template
        # from its own M4T_RANK (events.py), so the launcher and any
        # grandchildren agree on the layout
        env.update(
            M4T_TELEMETRY="1",
            M4T_TELEMETRY_EVENTS=os.path.join(
                events_dir, "events-rank{rank}.jsonl"
            ),
            M4T_TELEMETRY_FSYNC="1",
            M4T_FLIGHT_RECORDER_DIR=events_dir,
            M4T_HEARTBEAT=str(heartbeat),
        )
        if runtime_sampling:
            env.update(
                M4T_TELEMETRY_RUNTIME="1",
                M4T_PERF_WATCH="1" if perf_watch else "0",
            )
        if overlap:
            # overlap observatory (observability/overlap.py): step
            # spans + compute spans land on the same per-rank sink and
            # are joined against the runtime latency intervals
            env["M4T_STEP_SPAN"] = "1"
    return env


def _spawn_world(
    args,
    events_dir,
    *,
    attempt=0,
    resume_step=None,
    fault_plan_env=None,
    world=None,
    extra_env=None,
    span_fn=None,
):
    """Spawn and babysit one world of ``world`` ranks (default
    ``-n``); returns ``(exit_code, preempted_ranks)``.

    ``span_fn(name, t0, t1)``, when given, receives one ``spawn``
    lifecycle span covering the fork loop (all ranks Popen'd) — the
    serving plane records it on the job's trace so a cold-spawn-bound
    job is attributable from the span chain alone.

    One *attempt* in supervisor terms: a fresh shm segment name and
    generation nonce every time, so a restarted world can never attach
    a dead predecessor's segment (the ADVICE round-5 TOCTOU — the
    nonce is validated in the segment header by ``runtime/shmcc.cpp``).
    On the first nonzero rank exit the world is terminated, given a
    grace period to dump flight recorders, then killed — a surviving
    rank wedged inside a native collective must not hold the launcher
    (or the retry loop) hostage.

    ``preempted_ranks`` are ranks that exited with the preemption
    signature (``PREEMPT_EXIT`` 143, or an unhandled SIGTERM) *on
    their own*, before the launcher began tearing the world down —
    launcher-terminated survivors never count. Under ``--elastic`` a
    preempt-first failure gets a short settle window before teardown
    so co-preempted ranks (a whole host's worth, in real fleets) are
    counted together; the elastic supervisor then restarts at
    ``world - len(preempted)``.
    """
    world = args.nproc if world is None else int(world)
    shm_name = f"/m4t_{os.getpid()}_{attempt}_{uuid.uuid4().hex[:8]}"
    # nonzero u32: 0 means "no generation check" to the extension
    shm_gen = random.getrandbits(32) | 1
    procs = []
    monitor = None
    preempted = set()
    try:
        spawn_t0 = time.time()
        for rank in range(world):
            # --tune needs the runtime latency samples (the measured
            # side of the sweep); --live needs them for the exec-start
            # wedge evidence, straggler samples, and the anomaly feed
            env = rank_env(
                rank, world,
                shm_name=shm_name,
                shm_gen=shm_gen,
                extra_env=extra_env,
                events_dir=events_dir,
                heartbeat=args.heartbeat,
                static_check=args.static_check,
                fault_plan=fault_plan_env,
                fault_attempt=attempt,
                plan_cache=getattr(args, "plan_cache_env", None),
                resume_step=resume_step,
                runtime_sampling=(args.perf or args.tune or args.live
                                  or getattr(args, "overlap", False)),
                perf_watch=(args.perf or args.live),
                overlap=getattr(args, "overlap", False),
                trace_id=getattr(args, "trace_id", None),
                job_id=getattr(args, "job_id", None),
            )
            cmd = [sys.executable]
            if os.environ.get("M4T_LAUNCH_COVERAGE"):
                # Run each rank under parallel-mode coverage so CI can
                # `coverage combine` the per-rank data files with the
                # single-process run (the reference's
                # covecov-coverage.yml merges 1-rank and mpirun runs
                # the same way).
                cmd += ["-m", "coverage", "run", "-p"]
            if args.module:
                cmd += ["-m", args.module]
            cmd += args.cmd
            procs.append(subprocess.Popen(cmd, env=env))
        if span_fn is not None:
            try:
                span_fn("spawn", spawn_t0, time.time())
            except Exception:
                pass  # span recording must never take the world down

        if args.live and events_dir:
            # launcher-side live telemetry plane: tail the per-rank
            # sinks, stream the doctor, export OpenMetrics — and let
            # a *confirmed* hang tear the world down with a named
            # culprit instead of waiting out --hang-timeout
            from .observability.live import LiveMonitor

            monitor = LiveMonitor(
                events_dir,
                grace_s=args.live_grace,
                prom_path=os.path.join(events_dir, "metrics.prom"),
                http_port=args.metrics_port,
                dashboard=args.dashboard,
            ).start()

        exit_code = 0
        done = [False] * len(procs)
        deadline = (
            time.monotonic() + args.hang_timeout if args.hang_timeout > 0
            else None
        )
        # armed when the world is being torn down after a rank failure:
        # survivors get this long to run signal handlers (flight-
        # recorder dumps), then SIGKILL — a rank wedged in a native
        # collective spin can't run Python handlers at all
        term_deadline = None
        # armed under --elastic when the first failure is a preemption
        # exit: wait briefly before teardown so co-preempted ranks
        # finish their own grace exits and are counted as capacity
        # loss, not as launcher-terminated survivors
        settle_deadline = None
        while not all(done):
            for i, p in enumerate(procs):
                if done[i]:
                    continue
                rc = p.poll()
                if rc is None:
                    continue
                done[i] = True
                if rc in _PREEMPT_RCS and term_deadline is None:
                    preempted.add(i)
                if rc != 0 and exit_code == 0:
                    exit_code = rc
                    if getattr(args, "elastic", False) and (
                        rc in _PREEMPT_RCS
                    ):
                        sys.stderr.write(
                            f"mpi4jax_tpu.launch: rank {i} exited with "
                            f"the preemption signature ({rc}); settling "
                            "before teardown to count co-preempted "
                            "ranks\n"
                        )
                        settle_deadline = time.monotonic() + 1.0
                    else:
                        sys.stderr.write(
                            f"mpi4jax_tpu.launch: rank {i} exited with "
                            f"code {rc}; terminating world\n"
                        )
                        term_deadline = time.monotonic() + 10.0
                        for q in procs:
                            if q.poll() is None:
                                q.terminate()
            if settle_deadline is not None and term_deadline is None and (
                all(done) or time.monotonic() > settle_deadline
            ):
                settle_deadline = None
                if not all(done):
                    sys.stderr.write(
                        "mpi4jax_tpu.launch: "
                        f"{len(preempted)} rank(s) preempted "
                        f"({','.join(map(str, sorted(preempted)))}); "
                        "terminating the survivors\n"
                    )
                    term_deadline = time.monotonic() + 10.0
                    for q in procs:
                        if q.poll() is None:
                            q.terminate()
            if term_deadline is not None and not all(done) and (
                time.monotonic() > term_deadline
            ):
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                break
            if (
                monitor is not None
                and not all(done)
                and term_deadline is None
                and monitor.escalation() is not None
            ):
                # the streaming doctor *confirmed* a hang/mismatch:
                # act now, with the diagnosis attached, instead of
                # burning the rest of --hang-timeout
                alive = [i for i, p in enumerate(procs) if p.poll() is None]
                args._live_report = monitor.escalation()
                sys.stderr.write(
                    "mpi4jax_tpu.launch: streaming doctor confirmed a "
                    f"verdict; rank(s) {','.join(map(str, alive))} "
                    "still running — terminating world early\n"
                    + monitor.doctor.format_escalation() + "\n"
                )
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                grace = time.monotonic() + 5.0
                while time.monotonic() < grace and any(
                    p.poll() is None for p in procs
                ):
                    time.sleep(0.05)
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                exit_code = 124
                break
            if deadline is not None and not all(done) and (
                time.monotonic() > deadline
            ):
                alive = [i for i, p in enumerate(procs) if p.poll() is None]
                sys.stderr.write(
                    f"mpi4jax_tpu.launch: hang watchdog fired after "
                    f"{args.hang_timeout:g}s; rank(s) "
                    f"{','.join(map(str, alive))} still running — "
                    "terminating world\n"
                )
                # SIGTERM first: a rank blocked in Python dumps its
                # flight recorder from the handler; a rank wedged in a
                # native collective wait can't run the handler and
                # needs the SIGKILL below (its trace-time events are
                # already fsync'd on disk).
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                grace = time.monotonic() + 5.0
                while time.monotonic() < grace and any(
                    p.poll() is None for p in procs
                ):
                    time.sleep(0.05)
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                exit_code = 124
                break
            time.sleep(0.02)
        if getattr(args, "elastic", False) and preempted and (
            exit_code in _PREEMPT_RCS
        ):
            # normalize the world's exit to the canonical preemption
            # signature (a guardless rank dies -SIGTERM) so the
            # supervisor classifies it as "preempted", not "crash"
            from .resilience.supervisor import PREEMPT_EXIT

            exit_code = PREEMPT_EXIT
        return exit_code, sorted(preempted)
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        for p in procs:
            p.wait()
        return 130, sorted(preempted)
    finally:
        if monitor is not None:
            monitor.stop()
        # shm_unlink parity: rank 0's atexit unlinks; sweep in case it
        # died before doing so.
        path = "/dev/shm" + shm_name
        try:
            os.unlink(path)
        except OSError:
            pass


#: public name of the one-attempt spawn primitive: harnesses that
#: multiplex many worlds over this machine (``mpi4jax_tpu/serving/``)
#: call this with a :func:`make_world_args` namespace per attempt
spawn_world = _spawn_world


#: wall-clock budget for one probe world: the sweep is O(world *
#: payloads * repeats) short sendrecvs, so a probe that outlives this
#: is wedged, not slow
_PROBE_TIMEOUT_S = 120.0


def _run_probe_world(args, out_dir, *, world=None):
    """Spawn a short probe world (``mpi4jax_tpu.observability.topology
    probe``) before the workload: every rank sweeps ``sendrecv`` over
    the CartComm edges and rank 0 merges the fitted ``m4t-topo/1``
    map into ``out_dir/topology.json`` — the link truth the doctor's
    link-bound classifier, the per-link exporters, and ``planner tune
    --topo`` all consume. Probe telemetry deliberately does not ride
    the run's ``--events-dir`` sinks (a sweep's thousands of sendrecvs
    would drown the workload's record stream). A failed probe is a
    warning, never a launch blocker: the run proceeds with the
    uniform-peak model, exactly as before. Returns the map path or
    None."""
    from .observability import topology as _topology

    world = args.nproc if world is None else int(world)
    if world < 2:
        sys.stderr.write(
            "mpi4jax_tpu.launch: --probe-topology skipped: a world of "
            f"{world} rank(s) has no links to measure\n"
        )
        return None
    probe_args = make_world_args(
        nproc=world,
        module="mpi4jax_tpu.observability.topology",
        cmd=["probe", "--out", out_dir],
        hang_timeout=_PROBE_TIMEOUT_S,
    )
    exit_code, _preempted = _spawn_world(probe_args, None, world=world)
    path = os.path.join(out_dir, _topology.MAP_BASENAME)
    if exit_code == 0 and os.path.isfile(path):
        try:
            topo = _topology.load(path)
        except (OSError, ValueError) as exc:
            sys.stderr.write(
                "mpi4jax_tpu.launch: topology probe produced an "
                f"unusable map ({exc}); continuing without one\n"
            )
            return None
        sys.stderr.write(
            f"mpi4jax_tpu.launch: topology probe: {topo['world']} "
            f"ranks, {len(topo['edges'])} measured edge(s) -> {path}\n"
        )
        return path
    sys.stderr.write(
        f"mpi4jax_tpu.launch: topology probe failed (exit {exit_code}); "
        "continuing without a link map\n"
    )
    return None


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.launch", description=__doc__
    )
    parser.add_argument("-n", "--nproc", type=int, required=True)
    parser.add_argument(
        "-m", dest="module", default=None,
        help="run a module (like python -m) instead of a script",
    )
    parser.add_argument(
        "--events-dir", default=None, metavar="DIR",
        help="per-rank telemetry directory: each rank appends events "
        "to DIR/events-rank<k>.jsonl (fsync'd), arms flight-recorder "
        "dumps into DIR, and heartbeats; failures get a cross-rank "
        "doctor diagnosis",
    )
    parser.add_argument(
        "--hang-timeout", type=float, default=0.0, metavar="S",
        help="wall-clock budget for the whole world; exceeded -> "
        "terminate every rank, run the doctor over --events-dir, "
        "exit 124 (0 = no watchdog)",
    )
    parser.add_argument(
        "--heartbeat", type=float, default=5.0, metavar="S",
        help="per-rank heartbeat period under --events-dir "
        "(the doctor's hung-vs-dead signal; default %(default)s)",
    )
    parser.add_argument(
        "--doctor", action="store_true",
        help="always print the cross-rank diagnosis at the end, not "
        "just on failure (requires --events-dir); a mismatch the "
        "backend happened to survive still gets named",
    )
    parser.add_argument(
        "--live", action="store_true",
        help="live telemetry plane (requires --events-dir): tail the "
        "per-rank sinks while the world runs, stream the doctor's "
        "verdicts (a confirmed hang tears the world down with the "
        "diagnosis *before* --hang-timeout), write an OpenMetrics "
        "snapshot to EVENTS_DIR/metrics.prom, and record verdict + "
        "retune events in EVENTS_DIR/live.jsonl; implies runtime "
        "latency sampling and the perf anomaly watch in every rank",
    )
    parser.add_argument(
        "--live-grace", type=float, default=None, metavar="S",
        help="streaming-doctor stall grace: a hang verdict is "
        "confirmed only after the whole world made no progress for S "
        "seconds (default M4T_LIVE_GRACE, 5s)",
    )
    parser.add_argument(
        "--dashboard", action="store_true",
        help="print a one-line live status to stderr every ~2s "
        "(implies --live; the full-screen view is `python -m "
        "mpi4jax_tpu.observability.live DIR --follow`)",
    )
    parser.add_argument(
        "--metrics-port", type=int, default=None, metavar="N",
        help="serve the live OpenMetrics text on "
        "http://127.0.0.1:N/metrics while the world runs (implies "
        "--live; 0 picks a free port)",
    )
    parser.add_argument(
        "--perf", action="store_true",
        help="performance attribution mode (requires --events-dir): "
        "every rank samples per-op runtime latency "
        "(M4T_TELEMETRY_RUNTIME) and runs the live anomaly watch "
        "(M4T_PERF_WATCH — a collective regressing mid-run warns "
        "immediately); at the end the launcher prints the per-op "
        "achieved-bandwidth / %%-of-peak table",
    )
    parser.add_argument(
        "--overlap", action="store_true",
        help="arm the overlap observatory (requires --events-dir): "
        "every rank gets M4T_STEP_SPAN=1 plus runtime latency "
        "sampling, so step loops wrapped in obs.step_span() / "
        "obs.compute_span() record per-step compute/communication "
        "occupancy; the launcher prints the exposed-communication "
        "summary at the end (full report: `python -m "
        "mpi4jax_tpu.observability.overlap DIR`)",
    )
    parser.add_argument(
        "--plan", default=None, metavar="PLAN.json",
        help="arm a collective plan cache (planner/plan.py, "
        "M4T_PLAN_CACHE) in every rank: plannable collectives "
        "(AllReduce/ReduceScatter/AllGather) route per plan key; an "
        "invalid cache blocks the launch. With --tune this is also "
        "where the tuned plan is written",
    )
    parser.add_argument(
        "--tune", action="store_true",
        help="post-run autotuning (requires --events-dir and --plan): "
        "ranks sample per-op runtime latency; after a clean run the "
        "autotuner joins achieved GB/s against the cost model over "
        "the keys the run emitted and pins winners into --plan "
        "(merged over the existing cache)",
    )
    parser.add_argument(
        "--verify", action="store_true",
        help="fail-fast pre-spawn gate: lint + schedule-simulate the "
        "target's M4T_LINT_TARGETS at -n ranks (analysis/simulate.py) "
        "and refuse to spawn any rank unless every per-rank schedule "
        "is proven deadlock-free (M4T201/M4T202 block with a concrete "
        "witness)",
    )
    parser.add_argument(
        "--algo", action="append", default=None, metavar="ALGO.json",
        help="sideload a collective algorithm file (m4t-algo/1, "
        "planner/algo.py) into every rank's registry via "
        "M4T_ALGO_PATH; may repeat. With --verify each file is "
        "proven at -n ranks (simulate + chunk coverage + cost "
        "admission) before any rank spawns — an unproven algorithm "
        "blocks the launch",
    )
    parser.add_argument(
        "--place", default=None, metavar="PLACE.json",
        help="arm a topology-aware rank placement (m4t-place/1, "
        "planner/placement.py): the permutation is re-verified "
        "schedule-equivalent (M4T206) at -n ranks before any rank "
        "spawns — an unproven, stale, or world-mismatched placement "
        "blocks the launch with a witness; on success every rank "
        "inherits M4T_PLACEMENT and the world mesh / CartComm "
        "neighbor tables ride the permuted links. With --elastic the "
        "shrunk world re-derives placement from the probed topology "
        "map (or disarms)",
    )
    parser.add_argument(
        "--static-check", choices=("off", "warn", "error"), default="off",
        help="set M4T_STATIC_CHECK for every rank: screen each op "
        "emission at trace time with the site-local static-analysis "
        "rules (analysis/emit_check.py) and warn or raise; the full "
        "jaxpr linter is `python -m mpi4jax_tpu.analysis`",
    )
    parser.add_argument(
        "--fault-plan", default=None, metavar="SPEC",
        help="chaos mode: arm a deterministic fault-injection plan "
        "(path to, or inline, JSON — resilience/faults.py) in every "
        "rank via M4T_FAULT_PLAN; validated against -n before any "
        "rank spawns",
    )
    parser.add_argument(
        "--retries", type=int, default=0, metavar="K",
        help="self-healing supervisor: restart the world up to K times "
        "after *transient* failures (hang/dead rank/plain crash per "
        "the doctor's verdict); deterministic failures (MISMATCH) "
        "fail fast. 0 (default) = today's single-attempt behavior",
    )
    parser.add_argument(
        "--backoff", type=float, default=1.0, metavar="S",
        help="first restart delay in seconds; doubles per retry with "
        "jitter, capped at 60s (default %(default)s)",
    )
    parser.add_argument(
        "--resume-dir", default=None, metavar="CKPTROOT",
        help="CheckpointManager root (resilience/ckpt.py): before each "
        "restart the newest *valid* checkpoint step is found here and "
        "exported to every rank as M4T_RESUME_STEP",
    )
    parser.add_argument(
        "--elastic", action="store_true",
        help="elastic world-size resume (requires --retries and "
        "--resume-dir): ranks exiting with the preemption signature "
        "(PREEMPT_EXIT 143 / SIGTERM) count as capacity lost, and the "
        "next attempt restarts at the shrunk world — the newest "
        "m4t-ckpt/2 checkpoint is resharded N->M offline "
        "(resilience/reshard.py, peak scratch bounded by 2 shard "
        "sizes), --verify re-proves the target at M ranks, and the "
        "plan cache's world-keyed entries simply stop matching (plan "
        "keys include world, so routing at M falls back to the "
        "default policy by construction)",
    )
    parser.add_argument(
        "--probe-topology", action="store_true",
        help="active topology probe (requires --events-dir, -n >= 2): "
        "before the workload spawns, a short probe world sweeps "
        "sendrecv over every CartComm edge at a few payload sizes and "
        "persists the fitted per-link alpha/beta map as "
        "EVENTS_DIR/topology.json (m4t-topo/1, "
        "observability/topology.py) — the doctor then classifies "
        "stragglers link-bound vs rank-bound against it and `planner "
        "tune --topo` prices impls per edge; with --elastic the "
        "shrunk world is re-probed before its first attempt",
    )
    parser.add_argument(
        "--min-ranks", type=int, default=1, metavar="K",
        help="elastic floor: never shrink below K ranks — fewer "
        "survivors than K is a give-up, not a smaller world "
        "(default %(default)s)",
    )
    parser.add_argument("cmd", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.nproc < 1:
        parser.error("-n must be >= 1")
    if args.nproc > 64:
        # kMaxRanks in runtime/shmcc.cpp (the shm segment itself is
        # runtime-sized from -n; 64 is a sanity bound on single-host
        # oversubscription); checked here so a too-large world fails
        # immediately instead of after the join timeout.
        parser.error("-n must be <= 64 (shm backend kMaxRanks)")
    if not args.cmd and not args.module:
        parser.error("missing script")

    if args.retries < 0:
        parser.error("--retries must be >= 0")
    if args.backoff < 0:
        parser.error("--backoff must be >= 0")
    if args.min_ranks < 1:
        parser.error("--min-ranks must be >= 1")
    if args.min_ranks > args.nproc:
        parser.error("--min-ranks cannot exceed -n")
    if args.elastic and (args.retries < 1 or not args.resume_dir):
        parser.error("--elastic requires --retries >= 1 (the restart "
                     "loop) and --resume-dir (the checkpoint to "
                     "reshard)")

    if args.algo:
        args.algo = [os.path.abspath(p) for p in args.algo]
        for path in args.algo:
            if not os.path.exists(path):
                parser.error(f"--algo: {path} does not exist")
        # rank_env copies os.environ, so extending M4T_ALGO_PATH here
        # sideloads the files into every rank's registry (and into
        # this process's own registry, which --verify's armed-plan
        # check consults) across every spawn path, including the
        # supervisor's restarts
        dirs = []
        for path in args.algo:
            d = os.path.dirname(path)
            if d not in dirs:
                dirs.append(d)
        prior = os.environ.get("M4T_ALGO_PATH")
        if prior:
            dirs += [d for d in prior.split(os.pathsep) if d]
        os.environ["M4T_ALGO_PATH"] = os.pathsep.join(dirs)

    if args.verify:
        rc = _verify_prelaunch(args)
        if rc != 0:
            return rc

    if args.place:
        # unconditional (not only under --verify): an armed permutation
        # reroutes every neighbor exchange, so it is simulator-verified
        # or it does not spawn
        args.place = os.path.abspath(args.place)
        rc = _place_prelaunch(args)
        if rc != 0:
            return rc

    events_dir = args.events_dir
    if args.dashboard or args.metrics_port is not None:
        args.live = True
    if args.live and not events_dir:
        parser.error("--live requires --events-dir (the per-rank "
                     "sinks are what it tails)")
    if args.perf and not events_dir:
        parser.error("--perf requires --events-dir (it reads the "
                     "per-rank latency events back)")
    if args.tune and not (events_dir and args.plan):
        parser.error("--tune requires --events-dir (the measurements) "
                     "and --plan (where the tuned plan is written)")
    if args.probe_topology and not events_dir:
        parser.error("--probe-topology requires --events-dir (where "
                     "topology.json is persisted)")
    if getattr(args, "overlap", False) and not events_dir:
        parser.error("--overlap requires --events-dir (the step spans "
                     "and latency samples it joins live there)")
    if events_dir:
        events_dir = os.path.abspath(events_dir)
        os.makedirs(events_dir, exist_ok=True)

    # the streaming doctor's confirmed report of the last attempt, if
    # any (stashed by _spawn_world on live escalation): the supervisor
    # classifies it when the offline doctor can't read anything
    args._live_report = None

    args.plan_cache_env = None
    if args.plan:
        plan_path = os.path.abspath(args.plan)
        args.plan = plan_path
        if os.path.exists(plan_path):
            from .planner import plan as _planmod

            try:
                _planmod.load(plan_path)
            except _planmod.PlanError as e:
                parser.error(f"--plan: {plan_path}: {e} [{e.reason}]")
            args.plan_cache_env = plan_path
        elif not args.tune:
            parser.error(f"--plan: {plan_path} does not exist "
                         "(tune one with --tune or "
                         "`python -m mpi4jax_tpu.planner tune`)")

    fault_plan_env = None
    if args.fault_plan:
        from .resilience import faults

        spec = args.fault_plan
        if os.path.exists(spec):
            spec = os.path.abspath(spec)
        try:
            faults.FaultPlan.load(spec).validate_world(args.nproc)
        except faults.FaultPlanError as e:
            parser.error(f"--fault-plan: {e}")
        fault_plan_env = spec

    resume_dir = args.resume_dir
    if resume_dir:
        resume_dir = os.path.abspath(resume_dir)
        os.makedirs(resume_dir, exist_ok=True)

    if args.retries == 0:
        # the pre-supervisor contract, preserved exactly: one attempt,
        # flat artifact layout, same exit codes
        if args.probe_topology:
            _run_probe_world(args, events_dir)
        exit_code, _preempted = _spawn_world(
            args, events_dir, fault_plan_env=fault_plan_env
        )
        if events_dir and (exit_code != 0 or args.doctor):
            _run_doctor(events_dir)
        if events_dir and args.perf:
            _run_perf_report(events_dir)
        if args.tune and exit_code == 0:
            _run_tune(events_dir, args.plan)
        if events_dir and getattr(args, "overlap", False):
            _run_overlap_report(events_dir)
        if events_dir:
            # confirmed-straggler retune loop: link-localized verdicts
            # propose a re-permutation (audited in supervisor.jsonl)
            _propose_placement(
                events_dir,
                os.path.join(events_dir, "supervisor.jsonl"),
            )
        return exit_code

    # -- supervised path (--retries K) --------------------------------
    from .resilience.supervisor import RetryPolicy, Supervisor

    state = {
        "dir": events_dir,
        "world": args.nproc,      # world the NEXT attempt spawns at
        "world_ran": args.nproc,  # world the LAST attempt ran at
        "preempted": [],
        "transition": None,       # elastic shrink decided for next
        "blocked": None,          # elastic give-up reason, if any
        "last_exit": 0,
        "probed_world": None,     # world size the topology map covers
    }

    def attempt_dir(attempt):
        if not events_dir:
            return None
        d = os.path.join(events_dir, f"attempt{attempt:02d}")
        os.makedirs(d, exist_ok=True)
        return d

    def run_fn(attempt, resume_step):
        if state["blocked"]:
            # elastic give-up: not enough survivors (or the shrunk
            # world failed verification) — burning a spawn here would
            # just pretend capacity came back
            sys.stderr.write(
                f"mpi4jax_tpu.launch: attempt {attempt} not spawned: "
                f"{state['blocked']}\n"
            )
            return state["last_exit"] or 1
        d = attempt_dir(attempt)
        state["dir"] = d
        world = state["world"]
        state["world_ran"] = world
        if args.probe_topology and events_dir and (
            state["probed_world"] != world
        ):
            # first attempt, or the elastic supervisor shrank the
            # world: the old map's edges name ranks that no longer
            # exist, so the surviving links are re-measured before the
            # workload spawns at the new size
            _run_probe_world(args, events_dir, world=world)
            state["probed_world"] = world
        sys.stderr.write(
            f"mpi4jax_tpu.launch: attempt {attempt} (world {world})"
            + (f" (resuming from step {resume_step})"
               if resume_step is not None else "")
            + (f" [{d}]" if d else "")
            + "\n"
        )
        exit_code, preempted = _spawn_world(
            args, d,
            attempt=attempt,
            resume_step=resume_step,
            fault_plan_env=fault_plan_env,
            world=world,
        )
        state["preempted"] = preempted
        state["last_exit"] = exit_code
        return exit_code

    def diagnose_fn(attempt):
        d = state.get("dir")
        live_report = args._live_report
        args._live_report = None  # one attempt's evidence only
        if not d:
            return live_report
        try:
            from .observability import doctor

            report = doctor.diagnose([d])
        except Exception as exc:
            sys.stderr.write(
                f"mpi4jax_tpu.launch: doctor failed: {exc!r}\n"
            )
            return live_report
        if report is None:
            # nothing readable post-mortem: the streaming doctor's
            # confirmed report (same m4t-doctor/1 schema) still lets
            # the supervisor classify transient vs deterministic
            return live_report
        sys.stderr.write(
            "mpi4jax_tpu.launch: post-mortem diagnosis "
            f"({d}):\n{doctor.format_report(report)}\n"
        )
        return report

    def _log(msg):
        sys.stderr.write(f"mpi4jax_tpu.launch: {msg}\n")

    def _elastic_shrink():
        """Decide the next attempt's world after a preemption: shrink
        to the survivors, reshard the newest checkpoint to the new
        world, and re-prove the target there. Returns the resume step
        (or None), having updated ``state``."""
        from .resilience import reshard as _reshard
        from .resilience.ckpt import CheckpointManager

        old_world = state["world"]
        lost = len(state["preempted"])
        new_world = old_world - lost
        if new_world < args.min_ranks:
            state["blocked"] = (
                f"elastic: only {new_world} survivor(s) of {old_world} "
                f"after {lost} preemption(s) — below --min-ranks "
                f"{args.min_ranks}; giving up"
            )
            _log(state["blocked"])
            return None
        _log(
            f"elastic: {lost} rank(s) preempted "
            f"({','.join(map(str, state['preempted']))}); shrinking "
            f"world {old_world} -> {new_world}"
        )
        mgr = CheckpointManager(resume_dir, world=new_world)
        info = mgr.latest_valid(world=new_world, allow_reshard=True)
        resume = None
        reshard_src = None
        if info is None:
            _log(
                "elastic: no valid checkpoint to carry over; the "
                f"shrunk world restarts from step 0"
            )
        elif not info.world_mismatch:
            resume = info.step  # already at the new world
        elif not info.sharded:
            _log(
                f"elastic: checkpoint step {info.step} (world "
                f"{info.world}) predates {info.schema or 'm4t-ckpt/1'} "
                "sharded manifests and cannot be resharded; the "
                "shrunk world restarts from step 0"
            )
        else:
            try:
                new_info = _reshard.reshard_checkpoint(
                    mgr, info, new_world,
                    log=lambda m: _log(f"elastic: {m}"),
                )
                resume = new_info.step
                reshard_src = {
                    "step": info.step, "world": info.world,
                }
            except Exception as exc:
                _log(
                    f"elastic: reshard of step {info.step} failed "
                    f"({exc!r}); the shrunk world restarts from step 0"
                )
        if args.verify and _verify_prelaunch(args, world=new_world) != 0:
            state["blocked"] = (
                f"elastic: --verify failed at the shrunk world "
                f"{new_world}; giving up"
            )
            _log(state["blocked"])
            return None
        _replace_placement_elastic(
            args, new_world, [state.get("dir"), events_dir, resume_dir]
        )
        state["transition"] = {
            "world": old_world,
            "next_world": new_world,
            "resharded_from": reshard_src,
        }
        state["world"] = new_world
        return resume

    def resume_fn():
        if not resume_dir:
            return None
        try:
            if args.elastic and state["preempted"]:
                return _elastic_shrink()
            from .resilience.ckpt import CheckpointManager

            info = CheckpointManager(
                resume_dir, world=state["world"]
            ).latest_valid(world=state["world"])
            return None if info is None else info.step
        except Exception as exc:
            sys.stderr.write(
                f"mpi4jax_tpu.launch: checkpoint scan failed: {exc!r}\n"
            )
            return None

    def extra_fn(attempt):
        rec = {"world": state["world_ran"]}
        if state["preempted"]:
            rec["preempted_ranks"] = list(state["preempted"])
        transition = state["transition"]
        if transition is not None:
            rec["next_world"] = transition["next_world"]
            src = transition.get("resharded_from")
            if src:
                rec["resharded_from_step"] = src["step"]
                rec["resharded_from_world"] = src["world"]
            state["transition"] = None
        if state["blocked"]:
            rec["elastic_blocked"] = state["blocked"]
        return rec

    audit_root = events_dir or resume_dir
    sup = Supervisor(
        run_fn,
        policy=RetryPolicy(retries=args.retries, backoff_s=args.backoff),
        diagnose_fn=diagnose_fn,
        resume_fn=resume_fn,
        extra_fn=extra_fn,
        audit_path=(
            os.path.join(audit_root, "supervisor.jsonl")
            if audit_root else None
        ),
        log=lambda msg: sys.stderr.write(f"mpi4jax_tpu.launch: {msg}\n"),
    )
    exit_code = sup.run()
    if events_dir and args.doctor and exit_code == 0:
        _run_doctor(state["dir"])
    if events_dir and args.perf and state.get("dir"):
        _run_perf_report(state["dir"])
    if args.tune and exit_code == 0 and state.get("dir"):
        _run_tune(state["dir"], args.plan)
    if getattr(args, "overlap", False) and state.get("dir"):
        _run_overlap_report(state["dir"])
    if state.get("dir"):
        _propose_placement(
            state["dir"],
            (os.path.join(audit_root, "supervisor.jsonl")
             if audit_root else None),
        )
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
