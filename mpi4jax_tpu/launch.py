"""Multi-process launcher: the framework's ``mpirun``.

The reference's CPU workflow is ``mpirun -n N python script.py``
(``README.rst:83-88``) with libmpi doing rendezvous and transport.
This launcher reproduces that workflow on the native shared-memory
backend:

    python -m mpi4jax_tpu.launch -n 4 script.py [args...]
    python -m mpi4jax_tpu.launch -n 2 -m pytest tests/

Each child process imports ``mpi4jax_tpu``, joins the shm world named
in its environment (``runtime/shm.py:init_from_env``, the analog of
mpi4py's import-time ``MPI_Init``), and runs the script unchanged.
Fail-fast parity with the reference's ``MPI_Abort``
(``mpi_ops_common.h:60-78``): if any rank exits nonzero, the launcher
terminates the whole world and propagates the exit code.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
import uuid


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.launch", description=__doc__
    )
    parser.add_argument("-n", "--nproc", type=int, required=True)
    parser.add_argument(
        "-m", dest="module", default=None,
        help="run a module (like python -m) instead of a script",
    )
    parser.add_argument("cmd", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.nproc < 1:
        parser.error("-n must be >= 1")
    if args.nproc > 64:
        # kMaxRanks in runtime/shmcc.cpp (the shm segment itself is
        # runtime-sized from -n; 64 is a sanity bound on single-host
        # oversubscription); checked here so a too-large world fails
        # immediately instead of after the join timeout.
        parser.error("-n must be <= 64 (shm backend kMaxRanks)")
    if not args.cmd and not args.module:
        parser.error("missing script")

    shm_name = f"/m4t_{os.getpid()}_{uuid.uuid4().hex[:8]}"
    procs = []
    try:
        for rank in range(args.nproc):
            env = dict(os.environ)
            env.update(
                M4T_SHM_NAME=shm_name,
                M4T_RANK=str(rank),
                M4T_SIZE=str(args.nproc),
                # world membership is for *direct* children only:
                # runtime/shm.py refuses to join when the parent pid
                # doesn't match, so a rank's own subprocesses (pytest
                # spawning helper scripts) never attach as duplicate
                # ranks of the live world
                M4T_LAUNCHER_PID=str(os.getpid()),
                JAX_PLATFORMS="cpu",
            )
            cmd = [sys.executable]
            if os.environ.get("M4T_LAUNCH_COVERAGE"):
                # Run each rank under parallel-mode coverage so CI can
                # `coverage combine` the per-rank data files with the
                # single-process run (the reference's
                # covecov-coverage.yml merges 1-rank and mpirun runs
                # the same way).
                cmd += ["-m", "coverage", "run", "-p"]
            if args.module:
                cmd += ["-m", args.module]
            cmd += args.cmd
            procs.append(subprocess.Popen(cmd, env=env))

        exit_code = 0
        done = [False] * len(procs)
        while not all(done):
            for i, p in enumerate(procs):
                if done[i]:
                    continue
                rc = p.poll()
                if rc is None:
                    continue
                done[i] = True
                if rc != 0 and exit_code == 0:
                    exit_code = rc
                    sys.stderr.write(
                        f"mpi4jax_tpu.launch: rank {i} exited with code "
                        f"{rc}; terminating world\n"
                    )
                    for q in procs:
                        if q.poll() is None:
                            q.terminate()
            time.sleep(0.02)
        return exit_code
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        for p in procs:
            p.wait()
        return 130
    finally:
        # shm_unlink parity: rank 0's atexit unlinks; sweep in case it
        # died before doing so.
        path = "/dev/shm" + shm_name
        try:
            os.unlink(path)
        except OSError:
            pass


if __name__ == "__main__":
    sys.exit(main())
