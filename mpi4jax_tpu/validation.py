"""Runtime type validation for static op arguments.

Re-implementation of the reference's ``@enforce_types`` decorator
(``_src/validation.py:8-94``): static arguments are type-checked at
call time, with a dedicated error message when a traced value is passed
where a static one is required (the classic jit misuse,
``_src/validation.py:77-88``).
"""

from __future__ import annotations

import functools
import inspect

import jax


def _type_names(types) -> str:
    if not isinstance(types, tuple):
        types = (types,)
    return " or ".join(t.__name__ for t in types)


def enforce_types(**argtypes):
    """Decorator: ``@enforce_types(root=int, comm=(type(None), Comm))``.

    Accepts numpy-style scalar ints transparently by normalizing with
    ``int``/``bool`` checks where the expected type allows it.
    """

    def decorator(fn):
        sig = inspect.signature(fn)
        for name in argtypes:
            if name not in sig.parameters:
                raise ValueError(
                    f"enforce_types: {fn.__name__} has no argument {name!r}"
                )

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            bound = sig.bind(*args, **kwargs)
            bound.apply_defaults()
            for name, types in argtypes.items():
                value = bound.arguments[name]
                if not isinstance(types, tuple):
                    types = (types,)
                if isinstance(value, types):
                    continue
                if isinstance(value, jax.core.Tracer):
                    raise TypeError(
                        f"{fn.__name__}: argument {name!r} must be static "
                        f"({_type_names(types)}), but got a traced value. "
                        "This usually means the argument was passed through "
                        "jax.jit without being marked static "
                        "(reference behavior: _src/validation.py:77-88)."
                    )
                raise TypeError(
                    f"{fn.__name__}: argument {name!r} must be of type "
                    f"{_type_names(types)}, got {type(value).__name__}"
                )
            return fn(*args, **kwargs)

        return wrapped

    return decorator
