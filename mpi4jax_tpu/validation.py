"""Runtime type validation for static op arguments.

Re-implementation of the reference's ``@enforce_types`` decorator
(``_src/validation.py:8-94``): static arguments are type-checked at
call time, with a dedicated error message when a traced value is passed
where a static one is required (the classic jit misuse,
``_src/validation.py:77-88``).
"""

from __future__ import annotations

import functools
import inspect

import numpy as np

import jax


def _type_names(types) -> str:
    if not isinstance(types, tuple):
        types = (types,)
    return " or ".join(t.__name__ for t in types)


def _normalize_numpy_scalar(value, types):
    """Map a numpy scalar onto the matching allowed Python type:
    ``np.bool_`` -> ``bool`` where ``bool`` is accepted, ``np.integer``
    -> ``int`` where ``int`` is accepted (``np.int64`` does **not**
    subclass ``int`` on 64-bit Linux, so a bare isinstance check
    rejects the most common numpy scalar). Returns the normalized
    value, or None when no normalization applies. bool is checked
    first: ``np.bool_`` is not an ``np.integer``, but ``bool`` *is* a
    subclass of ``int``, so the order here keeps True from turning
    into 1 unless only ``int`` is accepted."""
    if isinstance(value, np.bool_):
        if bool in types:
            return bool(value)
        if int in types:
            return int(value)
    elif isinstance(value, np.integer) and int in types:
        return int(value)
    return None


def enforce_types(**argtypes):
    """Decorator: ``@enforce_types(root=int, comm=(type(None), Comm))``.

    Accepts numpy-style scalar ints transparently by normalizing with
    ``int``/``bool`` checks where the expected type allows it: the
    wrapped function sees a real ``int``/``bool``, so downstream
    static-parameter hashing and comparisons behave identically no
    matter whether the caller passed ``3`` or ``np.int64(3)``.
    """

    def decorator(fn):
        sig = inspect.signature(fn)
        for name in argtypes:
            if name not in sig.parameters:
                raise ValueError(
                    f"enforce_types: {fn.__name__} has no argument {name!r}"
                )

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            bound = sig.bind(*args, **kwargs)
            bound.apply_defaults()
            for name, types in argtypes.items():
                value = bound.arguments[name]
                if not isinstance(types, tuple):
                    types = (types,)
                if isinstance(value, types):
                    continue
                normalized = _normalize_numpy_scalar(value, types)
                if normalized is not None:
                    bound.arguments[name] = normalized
                    continue
                if isinstance(value, jax.core.Tracer):
                    raise TypeError(
                        f"{fn.__name__}: argument {name!r} must be static "
                        f"({_type_names(types)}), but got a traced value. "
                        "This usually means the argument was passed through "
                        "jax.jit without being marked static "
                        "(reference behavior: _src/validation.py:77-88)."
                    )
                raise TypeError(
                    f"{fn.__name__}: argument {name!r} must be of type "
                    f"{_type_names(types)}, got {type(value).__name__}"
                )
            return fn(*bound.args, **bound.kwargs)

        return wrapped

    return decorator
