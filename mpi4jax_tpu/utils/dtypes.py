"""Dtype support tables.

The reference maps numpy dtype names to MPI datatype handles
(``_src/utils.py:101-128``: f32/f64/f128, c64/c128, i8–i64, u8–u64,
bool). On the XLA path no marshalling is needed — any dtype XLA can
AllReduce works — so the tables here describe *reduction* support:

The XLA path needs no dtype table — the native/generic dispatch is by
operator (``ops/allreduce.py``: psum/pmax/pmin exist for SUM/MAX/MIN,
anything XLA can add/compare works). The native shm backend's C++
reductions (``runtime/shmcc.cpp:accumulate_dtype``) cover the
reference's integer/float set minus ``float128`` (no TPU/XLA meaning);
complex64/128 reduce with SUM/PROD only (matching MPI); copy ops accept
any dtype byte-wise.
"""

from __future__ import annotations

import numpy as np

#: dtypes the native shm backend reduces in C++ (complex64/complex128
#: support SUM/PROD only, matching MPI and the reference dtype table)
SHM_REDUCTION_DTYPES = frozenset(
    np.dtype(d)
    for d in (
        np.float32, np.float64,
        np.int8, np.int16, np.int32, np.int64,
        np.uint8, np.uint16, np.uint32, np.uint64,
        np.bool_,
        np.complex64, np.complex128,
    )
)


def is_shm_reduction_dtype(dtype) -> bool:
    return np.dtype(dtype) in SHM_REDUCTION_DTYPES
