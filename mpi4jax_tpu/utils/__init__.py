"""Shared utilities (reference ``_src/utils.py`` / ``validation.py``
analog surface, re-exported for convenience)."""

from .dtypes import (  # noqa: F401
    SHM_REDUCTION_DTYPES,
    is_shm_reduction_dtype,
)
from ..validation import enforce_types  # noqa: F401
from ..config import env_flag, is_falsy, is_truthy  # noqa: F401
from ..token import NOTSET, raise_if_token_is_set  # noqa: F401
