"""Profiler integration (superset observability subsystem).

The reference's only tracing facility is the per-op ``DebugTimer`` log
(``mpi_ops_common.h:154-206``) — mirrored here by ``set_logging``
(``debug.py``). On TPU the native tool is the XLA profiler: its traces
show every HLO collective (AllReduce/AllGather/CollectivePermute) with
per-op device timing and ICI utilization, which is exactly the
visibility the reference's log lines approximate. This module wraps it
in two ergonomic entry points so comm-heavy sections can be profiled
without touching ``jax.profiler`` directly:

    from mpi4jax_tpu.utils import profiling

    with profiling.trace("/tmp/m4t-trace"):       # TensorBoard dir
        step(params, batch)

    profiling.annotate("halo-exchange")           # decorator/context
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


def device_sync(tree) -> None:
    """Genuinely wait for every array in ``tree`` to finish computing.

    ``jax.block_until_ready`` is the documented synchronization point,
    but some PJRT transports resolve buffer-ready events before the
    computation has finished (measured on the axon TPU tunnel: a 0.7 s
    matmul chain reports "ready" in 0.2 ms while fetching its scalar
    result takes the full 0.7 s). A device-to-host transfer is the only
    operation that provably waits everywhere, so benchmark timings must
    close with one. This fetches a single element per leaf — negligible
    transfer volume, true wait.
    """
    leaves = [x for x in jax.tree.leaves(tree) if hasattr(x, "dtype")]
    jax.block_until_ready(leaves)  # correct sync on conforming backends
    probes = [x.ravel()[-1:] if getattr(x, "ndim", 0) else x for x in leaves]
    jax.device_get(probes)


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture an XLA profiler trace of the enclosed block.

    The trace lands in ``log_dir`` in TensorBoard format (open with
    ``tensorboard --logdir``, or upload the contained ``.perfetto``
    file to ui.perfetto.dev). Collectives appear under their HLO names
    with device-time ranges — the TPU-native analog of reading the
    reference's DebugTimer log.
    """
    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def emission_scope(name: str) -> Iterator[None]:
    """Profiler auto-annotation for one op emission (``m4t.<op>``).

    Used by ``ops/_core.py`` around every collective's ``bind``: the
    enclosed trace-time emission is wrapped in

    - ``jax.named_scope(name)`` — the scope lands in the HLO metadata
      of every op the emission creates, so XLA profiler traces
      attribute device/ICI time to the mpi4jax-level op (search for
      ``m4t.`` in the trace viewer), not just the HLO instruction name;
    - ``jax.profiler.TraceAnnotation(name)`` — in eager execution the
      same name appears on the host timeline.

    With telemetry on the name carries the emission correlation id
    (``m4t.allreduce.<cid>``), joining the trace region to the debug
    log line and the metrics record.
    """
    with jax.named_scope(name):
        with jax.profiler.TraceAnnotation(name):
            yield


def annotate(name: Optional[str] = None):
    """Named region for profiler traces: usable as a decorator or a
    context manager. Regions nest and show up on the trace timeline,
    letting a comm-heavy section (a halo-exchange group, a ring
    rotation) be attributed at a glance.

    ``@annotate()`` on a function uses the function's name.
    """
    if callable(name):  # bare @annotate usage
        return jax.profiler.annotate_function(name)

    class _Region:
        def __call__(self, fn):
            return jax.profiler.annotate_function(fn, name=name)

        def __enter__(self):
            self._ctx = jax.profiler.TraceAnnotation(name or "m4t")
            self._ctx.__enter__()
            return self

        def __exit__(self, *exc):
            return self._ctx.__exit__(*exc)

    return _Region()
