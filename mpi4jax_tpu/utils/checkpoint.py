"""Checkpoint/restore for distributed training state.

The reference has no checkpointing at all (SURVEY.md §5: "Checkpoint /
resume: None anywhere in the tree"), so this is a superset subsystem:
a thin wrapper over `orbax.checkpoint` that saves/restores the pytrees
our models train (params, solver states), preserving shardings on
restore when a mesh is supplied.

Saves are atomic: the checkpoint is written to ``path + ".tmp"`` and
renamed into place only once fully on disk, so a process killed
mid-save (the supervisor's SIGKILL, a preemption) can never leave a
half-written directory at ``path`` — it leaves ``path`` untouched (old
checkpoint intact, or absent) plus ``.tmp`` litter that the next save
sweeps. The step-tagged history/retention/validity layer above this is
``resilience/ckpt.py``'s CheckpointManager.

This module also provides the device-free array IO the ``m4t-ckpt/2``
per-rank shard layout is built on (:func:`save_array` /
:func:`open_array`): plain ``.npy`` files written atomically and read
back memory-mapped, so the offline reshard CLI can move slices of an
N-rank checkpoint without jax, orbax, or ever materializing a global
array. jax itself is imported lazily for the same reason.
"""

from __future__ import annotations

import os
import shutil
from typing import Any

import numpy as np


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save(path: str, state: Any) -> None:
    """Save a pytree of arrays to ``path`` (a directory), atomically:
    the data lands in ``path + ".tmp"`` first and is renamed over
    ``path`` only when complete."""
    path = os.path.abspath(path)
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    ckpt = _checkpointer()
    ckpt.save(tmp, state, force=True)
    ckpt.wait_until_finished()
    if os.path.exists(path):
        shutil.rmtree(path)
    os.replace(tmp, path)


def restore(path: str, template: Any) -> Any:
    """Restore a pytree saved by :func:`save`. ``template`` provides
    structure/shape/dtype (and sharding, if its leaves are sharded
    arrays — restored leaves then land on the same mesh layout)."""
    import jax

    path = os.path.abspath(path)
    ckpt = _checkpointer()
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=getattr(a, "sharding", None)
        ),
        template,
    )
    return ckpt.restore(path, abstract)


# ---------------------------------------------------------------------
# device-free array IO (the m4t-ckpt/2 shard layer)
# ---------------------------------------------------------------------


def save_array(path: str, arr: np.ndarray) -> None:
    """Write one ``.npy`` atomically: staged at ``path + ".tmp"`` and
    renamed into place, so a writer killed mid-save leaves the old
    file (or nothing), never a torn one. The array is written exactly
    as passed — callers pick a portable dtype (``reshard.LeafSpec
    .wire_dtype``) so any vanilla-numpy reader can load it back."""
    path = os.path.abspath(path)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.save(f, np.ascontiguousarray(arr))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def open_array(path: str, *, mmap: bool = True) -> np.ndarray:
    """Read a :func:`save_array` file back, memory-mapped by default —
    slicing then touches only the bytes the slice covers, which is
    what keeps the reshard executor's peak memory at the planned
    bound instead of one-global-array."""
    return np.load(
        os.path.abspath(path), mmap_mode="r" if mmap else None,
        allow_pickle=False,
    )
