"""Checkpoint/restore for distributed training state.

The reference has no checkpointing at all (SURVEY.md §5: "Checkpoint /
resume: None anywhere in the tree"), so this is a superset subsystem:
a thin wrapper over `orbax.checkpoint` that saves/restores the pytrees
our models train (params, solver states), preserving shardings on
restore when a mesh is supplied.
"""

from __future__ import annotations

import os
from typing import Any

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save(path: str, state: Any) -> None:
    """Save a pytree of arrays to ``path`` (a directory)."""
    path = os.path.abspath(path)
    ckpt = _checkpointer()
    ckpt.save(path, state, force=True)
    ckpt.wait_until_finished()


def restore(path: str, template: Any) -> Any:
    """Restore a pytree saved by :func:`save`. ``template`` provides
    structure/shape/dtype (and sharding, if its leaves are sharded
    arrays — restored leaves then land on the same mesh layout)."""
    path = os.path.abspath(path)
    ckpt = _checkpointer()
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=getattr(a, "sharding", None)
        ),
        template,
    )
    return ckpt.restore(path, abstract)
