"""Infrastructure unit tests: validation, communicators, version gate,
config parsing, debug-log contract, capability queries — the analog of
the reference's ``test_validation.py`` / ``test_decorators.py`` /
``test_jax_compat.py`` / ``test_has_cuda.py`` (SURVEY.md §4 item 9)."""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as m4t
from mpi4jax_tpu import config, debug, jax_compat
from mpi4jax_tpu.comm import CartComm, Comm, resolve_comm
from mpi4jax_tpu.runtime import shm as _shm
from mpi4jax_tpu.validation import enforce_types

from tests.conftest import needs_supported_jax

from tests.conftest import WORLD


# --- enforce_types (reference test_validation.py) ---


def test_enforce_types_accepts():
    @enforce_types(a=int, b=(str, type(None)))
    def f(a, b=None):
        return a

    assert f(1) == 1
    assert f(1, "x") == 1


def test_enforce_types_rejects():
    @enforce_types(a=int)
    def f(a):
        return a

    with pytest.raises(TypeError, match="must be of type int"):
        f("nope")


def test_enforce_types_tracer_error():
    # the dedicated jit-misuse message (reference validation.py:77-88)
    @enforce_types(a=int)
    def f(x, a):
        return x * a

    with pytest.raises(TypeError, match="static"):
        jax.jit(f)(jnp.ones(2), 3)


def test_enforce_types_unknown_arg():
    with pytest.raises(ValueError):
        enforce_types(nope=int)(lambda a: a)


# --- communicators ---


def test_comm_hashable_and_eq():
    assert Comm("x") == Comm("x")
    assert Comm("x") != Comm("y")
    assert hash(Comm(("a", "b"))) == hash(Comm(("a", "b")))
    assert Comm("x").Clone() == Comm("x")


def test_cartcomm_topology():
    cart = CartComm(dims=(2, 4), periods=(False, True))
    assert cart.nranks == 8
    assert cart.coords(5) == (1, 1)
    assert cart.rank_at((1, 1)) == 5
    # periodic x wrap
    assert cart.neighbor(4, 1, -1) == 7
    # closed y boundary
    assert cart.neighbor(1, 0, -1) == m4t.PROC_NULL
    src, dst = cart.shift(1, +1)
    assert dst[0] == 1 and src[0] == 3  # ring within row 0


def test_cartcomm_shift_mirror():
    cart = CartComm(dims=(2, 2), periods=(True, True))
    src, dst = cart.shift(0, 1)
    for r in range(4):
        if dst[r] >= 0:
            assert src[dst[r]] == r


def test_resolve_comm_outside_mesh():
    # outside any mesh: the eager world — size 1 standalone, the
    # launcher world's size under `python -m mpi4jax_tpu.launch`
    bound = resolve_comm(None)
    assert bound.size == WORLD and bound.axes == ()
    if WORLD > 1:
        assert bound.backend == "shm"


def test_resolve_comm_type_error():
    with pytest.raises(TypeError):
        resolve_comm("world")


@needs_supported_jax  # typo detection reads AbstractMesh.manual_axes (jax>=0.6)
def test_resolve_comm_typo_inside_mesh_raises(mesh, per_rank):
    # An axis-name typo inside a shard_map must fail loudly, not
    # silently resolve to a size-1 world where every collective is an
    # identity (round-1 VERDICT "silent-wrong-answer hole").
    from functools import partial

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    arr = per_rank(lambda r: np.float32(r))

    def f(x):
        return m4t.allreduce(x, op=m4t.SUM, comm=Comm("rank"))  # typo

    sm = partial(
        shard_map, mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks"),
        check_vma=False,
    )
    with pytest.raises(NameError, match="typo"):
        jax.jit(sm(f))(jnp.asarray(arr))


@pytest.mark.skipif(
    _shm.active(), reason="vmap-of-FFI not defined on the shm backend"
)
def test_resolve_comm_vmap_axis_still_works():
    # vmap axis names are not mesh axes; collectives over them (or over
    # the default world comm at size 1) must keep working.
    out = jax.vmap(
        lambda x: m4t.allreduce(x, op=m4t.SUM), axis_name="batch"
    )(jnp.arange(4.0))
    np.testing.assert_allclose(np.asarray(out), np.arange(4.0))


def test_comm_rank_inside_mesh(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(0))
    out = run_spmd(
        lambda x: x + m4t.get_default_comm().Get_rank().astype(jnp.float32), arr
    )
    np.testing.assert_allclose(out, np.arange(8.0))


# --- version gate (reference test_jax_compat.py) ---


def test_versiontuple():
    assert jax_compat.versiontuple("0.9.0") == (0, 9, 0)
    assert jax_compat.versiontuple("0.10.1.dev3") == (0, 10, 1)
    assert jax_compat.versiontuple("1.2") == (1, 2)


def test_version_gate_warns(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_NO_WARN_JAX_VERSION", raising=False)
    with pytest.warns(UserWarning, match="newer than the latest"):
        jax_compat.check_jax_version("99.0.0")


def test_version_gate_silenced(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_NO_WARN_JAX_VERSION", "1")
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        jax_compat.check_jax_version("99.0.0")


def test_version_gate_minimum():
    with pytest.raises(RuntimeError, match="requires jax"):
        jax_compat.check_jax_version("0.4.0")


# --- config parsing (reference test_decorators.py truthy/falsy) ---


def test_truthy_falsy():
    assert config.is_truthy("1") and config.is_truthy("ON") and config.is_truthy("true")
    assert config.is_falsy("0") and config.is_falsy("OFF") and config.is_falsy("false")
    assert not config.is_truthy("banana")


def test_env_flag(monkeypatch):
    monkeypatch.setenv("M4T_TEST_FLAG", "on")
    assert config.env_flag("M4T_TEST_FLAG") is True
    monkeypatch.setenv("M4T_TEST_FLAG", "garbage")
    assert config.env_flag("M4T_TEST_FLAG", default=False) is False


# --- debug-log contract (reference test_common.py:118-146) ---


def test_emission_log_format(capsys):
    m4t.set_logging(True)
    try:
        m4t.allreduce(jnp.ones(4), op=m4t.SUM)
    finally:
        m4t.set_logging(False)
    out = capsys.readouterr().out
    assert re.search(
        rf"emit \| [a-z0-9]{{8}} \| AllReduce \[4 items, op=SUM, n={WORLD}\]",
        out,
    ), out


def test_set_get_logging():
    m4t.set_logging(True)
    assert m4t.get_logging() is True
    m4t.set_logging(False)
    assert m4t.get_logging() is False


def test_runtime_log_per_rank(capfd, run_spmd, per_rank):
    # device-side callback log: r{rank} | {id} | {Op} ... done
    # (reference DebugTimer format, test_common.py:118-146)
    m4t.set_logging(True, runtime=True)
    try:
        arr = per_rank(lambda r: np.float32(r))
        run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM), arr)
        jax.effects_barrier()  # drain pending async callbacks
    finally:
        m4t.set_logging(False, runtime=False)
    out = capfd.readouterr().out
    assert re.search(r"r\d \| [a-z0-9]{8} \| AllReduce .* done", out), out


# --- capability queries (reference test_has_cuda.py / test_has_sycl.py) ---


def test_capability_queries():
    assert m4t.has_cuda_support() is False
    assert m4t.has_sycl_support() is False
    assert isinstance(m4t.has_tpu_support(), bool)
    assert isinstance(m4t.has_shm_support(), bool)


def test_shmcomm_outside_world():
    if _shm.active():
        # inside a launcher world the constructor succeeds and reports
        # the world geometry
        c = m4t.ShmComm()
        assert c.Get_size() == WORLD
        assert 0 <= c.Get_rank() < WORLD
    else:
        with pytest.raises(RuntimeError, match="launch"):
            m4t.ShmComm()


# --- ordering token ---


def test_opt_barrier_chain_in_hlo(mesh):
    from functools import partial

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    sm = partial(
        shard_map, mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks"),
        check_vma=False,
    )

    def f(x):
        a = m4t.allreduce(x, op=m4t.SUM)
        b = m4t.allreduce(a * 2, op=m4t.MAX)
        return b

    txt = jax.jit(sm(f)).lower(jnp.arange(8.0).reshape(8, 1)).as_text()
    assert txt.count("optimization_barrier") >= 4


def test_no_ordering_env(monkeypatch, run_spmd, per_rank):
    monkeypatch.setattr(config, "NO_ORDERING", True)
    arr = per_rank(lambda r: np.float32(r))
    out = run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM), arr)
    np.testing.assert_allclose(out, np.full(8, arr.sum()))


def test_barrier_inside_jit_not_dced(mesh):
    # Regression: barrier binds a literal token operand; the eager
    # fast-path skip must key on trace *state*, not operand
    # concreteness, or the barrier's collective loses its ties inside
    # jit and XLA DCEs it entirely.
    from functools import partial

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    sm = partial(
        shard_map, mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks"),
        check_vma=False,
    )

    def f(x):
        m4t.barrier()
        return m4t.allreduce(x, op=m4t.SUM)

    txt = jax.jit(sm(f)).lower(jnp.arange(8.0).reshape(8, 1)).as_text()
    # barrier's scalar psum + the allreduce, chained: both must survive
    assert txt.count("all_reduce") >= 2, (
        "barrier's collective was DCE'd from the trace"
    )
    assert txt.count("optimization_barrier") >= 4


def test_eager_latency_fast_path(monkeypatch):
    # plain eager ops skip the optimization_barrier ties (no active
    # trace) — pin the skip by counting barrier calls, not just output
    from jax import lax

    from mpi4jax_tpu import token

    calls = []
    real = lax.optimization_barrier
    monkeypatch.setattr(
        token.lax, "optimization_barrier",
        lambda *a, **k: calls.append(1) or real(*a, **k),
    )
    out1 = m4t.allreduce(jnp.ones(3), op=m4t.SUM)
    out2 = m4t.allreduce(out1 * 2, op=m4t.MAX)
    np.testing.assert_allclose(np.asarray(out2), 2.0 * WORLD)
    assert calls == [], f"eager ops emitted {len(calls)} barrier ties"


# --- profiler integration (superset observability) ---


def test_profiler_trace_capture(tmp_path, run_spmd, per_rank):
    from mpi4jax_tpu.utils import profiling

    logdir = str(tmp_path / "trace")
    arr = per_rank(lambda r: np.float32(r))
    with profiling.trace(logdir):
        with profiling.annotate("allreduce-under-trace"):
            run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM), arr)
    import os as _os

    found = [
        _os.path.join(dp, f)
        for dp, _, fs in _os.walk(logdir)
        for f in fs
        if f.endswith((".pb", ".json.gz", ".xplane.pb"))
    ]
    assert found, f"no trace artifacts written under {logdir}"


def test_profiler_annotate_decorator(run_spmd, per_rank):
    from mpi4jax_tpu.utils import profiling

    @profiling.annotate("named-section")
    def section(x):
        return m4t.allreduce(x, op=m4t.SUM)

    arr = per_rank(lambda r: np.float32(1))
    out = run_spmd(section, arr)
    np.testing.assert_allclose(np.asarray(out).ravel(), 8.0)


def test_multihost_initialize_single_process():
    # parallel.initialize() is the jax.distributed entry (reference
    # launch model replacement); it must be called before any JAX
    # computation, so drive it in a fresh process.
    import os
    import subprocess
    import sys
    import textwrap

    import socket

    with socket.socket() as s_:
        s_.bind(("", 0))
        port = s_.getsockname()[1]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {repo!r})
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        import jax
        jax.config.update("jax_platforms", "cpu")
        from mpi4jax_tpu import parallel
        parallel.initialize(
            coordinator_address="localhost:{port}",
            num_processes=1, process_id=0,
        )
        m = parallel.world_mesh()
        assert m.devices.size == 8
        print("INIT_OK")
    """)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, "-c", script], env=env,
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stderr
    assert "INIT_OK" in res.stdout
