"""Resident warm worker pool (``mpi4jax_tpu/serving/pool.py``).

Covers the ISSUE-11 acceptance surface:

- the ``wedge`` fault action: parses, scopes like ``hang``, silences
  the heartbeat daemon before blocking (the deterministic pool-doctor
  test shape);
- ``observability/live.HeartbeatTail``: bounded-memory liveness over
  one sink, freshness by *arrival* time (a respawned worker never
  looks alive on its predecessor's heartbeats);
- mailbox protocol: atomic item/result writes, FIFO claim order;
- ``run_item``: in-process payload execution (exit codes, exceptions,
  argv shapes) and the hygiene contract — pending-send drain, fault
  plan unscoping, env-bleed rollback, telemetry registry reset,
  sub-mesh ``job_comm()`` exposure;
- the pool doctor (stub handles, fake clock — fully deterministic):
  ready-on-first-beat, wedged / exited / start-timeout quarantines
  with respawn, elastic retirement on preemption exits, gang
  ``peer_lost`` teardown;
- dispatch: runner round-trip over real mailbox files, job deadline
  -> ``job_timeout`` quarantine, the two-strikes poisoned rule
  (strike, poison, refuse), hygiene quarantine after a leaky job;
- exporter + doctor narration of pool health;
- real resident workers (subprocess): warm round-trip smoke, and the
  slow chaos e2e — SIGKILL one worker mid-job, assert the pool
  respawns, the job retries, and every queued job id ends terminal.
"""

import json
import os
import signal
import threading
import time

import pytest

from mpi4jax_tpu.observability import doctor, events, live
from mpi4jax_tpu.resilience import faults
from mpi4jax_tpu.serving import Server, Spool, parse_job
from mpi4jax_tpu.serving import export as sexport
from mpi4jax_tpu.serving import pool as pool_mod
from mpi4jax_tpu.serving.pool import WorkerPool

pytestmark = [pytest.mark.serving, pytest.mark.pool]


# ---------------------------------------------------------------------
# the wedge fault action
# ---------------------------------------------------------------------


def test_wedge_action_parses_like_hang():
    plan = faults.FaultPlan.parse({"faults": [
        {"rank": 1, "op": "AllReduce", "nth": 3, "action": "wedge"},
    ]})
    rule = plan.rules[0]
    assert rule.action == "wedge" and rule.nth == 3 and rule.rank == 1
    plan.validate_world(2)
    with pytest.raises(faults.FaultPlanError):
        plan.validate_world(1)  # rank 1 out of range, like any action


def test_wedge_action_rejected_fields_still_checked():
    with pytest.raises(faults.FaultPlanError, match="action"):
        faults.FaultPlan.parse({"faults": [{"op": "*",
                                            "action": "wedgie"}]})


def test_wedge_silences_heartbeat_then_blocks(monkeypatch):
    silenced = []
    monkeypatch.setattr(
        events, "silence_heartbeat", lambda: silenced.append(True)
    )

    class _Break(Exception):
        pass

    def _no_sleep(s):
        raise _Break

    monkeypatch.setattr(faults.time, "sleep", _no_sleep)
    plan = faults.FaultPlan.parse({"faults": [
        {"rank": 0, "op": "AllReduce", "nth": 1, "action": "wedge"},
    ]})
    faults.arm(plan, rank=0, attempt=0)
    try:
        with pytest.raises(_Break):
            faults.on_emission(
                "AllReduce", cid="t", nbytes=4, dtype="float32",
                shape=(1,), axes=[], world=2,
            )
    finally:
        faults.disarm()
    # the heartbeat daemon was silenced BEFORE the block: from the
    # outside the process is now emission-less, heartbeat-less, and
    # alive — only a heartbeat deadline can name it
    assert silenced == [True]


def test_silence_heartbeat_stops_the_daemon(tmp_path, monkeypatch):
    sink = events.EventLog(str(tmp_path / "s.jsonl"))
    monkeypatch.setattr(events, "get_sink", lambda: sink)
    monkeypatch.setattr(events, "_sink", sink, raising=False)
    stop = events.start_heartbeat(0.01, source="t")
    try:
        time.sleep(0.05)
        events.silence_heartbeat()
        n = len([r for r in events.read(str(tmp_path / "s.jsonl"))
                 if r.get("kind") == "heartbeat"])
        assert n >= 1
        time.sleep(0.05)
        n2 = len([r for r in events.read(str(tmp_path / "s.jsonl"))
                  if r.get("kind") == "heartbeat"])
        assert n2 == n  # no beats after the silence
    finally:
        stop()


# ---------------------------------------------------------------------
# HeartbeatTail
# ---------------------------------------------------------------------


def test_heartbeat_tail_freshness_is_arrival_time(tmp_path):
    path = str(tmp_path / "s.jsonl")
    now = [100.0]
    tail = live.HeartbeatTail(path, clock=lambda: now[0])
    assert tail.poll() == 0
    assert tail.heartbeat_age() is None
    log = events.EventLog(path)
    # the record's own t is ancient — freshness must come from when
    # the tail first SAW the line, not from what the line claims
    log.append(events.event("heartbeat", source="w", t=1.0))
    assert tail.poll() == 1
    assert tail.heartbeat_age() == 0.0
    now[0] = 103.0
    assert tail.heartbeat_age() == 3.0
    log.append(events.event("pool", event="job_start"))
    assert tail.poll() == 1
    assert tail.heartbeat_age() == 3.0  # non-heartbeats don't refresh
    assert tail.last_record_t == 103.0
    assert tail.records == 2


# ---------------------------------------------------------------------
# mailbox protocol + run_item
# ---------------------------------------------------------------------


def test_mailbox_writes_are_atomic_and_fifo(tmp_path):
    inbox = str(tmp_path / "inbox")
    os.makedirs(inbox)
    for i in (3, 1, 2):
        pool_mod._write_json_atomic(
            os.path.join(inbox, f"{i:020d}-it{i}.json"), {"i": i}
        )
    assert not [n for n in os.listdir(inbox) if n.startswith(".tmp-")]
    assert pool_mod._oldest_entry(inbox) == f"{1:020d}-it1.json"


BASE = {"schema": pool_mod.WORK_SCHEMA, "item": "i0", "job": "j0"}


def test_run_item_payload_shapes():
    assert pool_mod.run_item(
        {**BASE, "cmd": ["-c", "pass"]})["rc"] == 0
    assert pool_mod.run_item(
        {**BASE, "cmd": ["-c", "import sys; sys.exit(9)"]})["rc"] == 9
    r = pool_mod.run_item(
        {**BASE, "cmd": ["-c", "raise RuntimeError('x')"]})
    assert r["rc"] == 1 and "RuntimeError" in r["error"]
    r = pool_mod.run_item({**BASE, "cmd": [
        "-c", "import sys; assert sys.argv[1:] == ['a', 'b']", "a", "b",
    ]})
    assert r["rc"] == 0, r
    r = pool_mod.run_item({**BASE})
    assert r["rc"] == 1 and "module" in r["error"]


def test_run_item_hygiene_env_bleed_named_and_rolled_back():
    r = pool_mod.run_item({**BASE, "cmd": [
        "-c", "import os; os.environ['M4T_TEST_BLEED'] = '1'",
    ]})
    assert r["hygiene"]["env_bleed"] == ["M4T_TEST_BLEED"]
    assert not r["hygiene"]["clean"]
    assert "M4T_TEST_BLEED" not in os.environ


def test_run_item_hygiene_pending_sends(monkeypatch):
    import mpi4jax_tpu.token as token

    monkeypatch.setattr(
        token, "drain_pending_sends",
        lambda: [("trace", [{"op": "Send"}, {"op": "Send"}])],
    )
    r = pool_mod.run_item({**BASE, "cmd": ["-c", "pass"]})
    assert r["hygiene"]["pending_sends"] == 2
    assert not r["hygiene"]["clean"]


def test_run_item_hygiene_fault_plan_scoping():
    # a plan the payload armed itself is a violation...
    r = pool_mod.run_item({**BASE, "cmd": [
        "-c",
        "from mpi4jax_tpu.resilience import faults; "
        "faults.arm(faults.FaultPlan.parse("
        "{'faults': [{'op': '*', 'action': 'delay', 'ms': 1}]}))",
    ]})
    assert r["hygiene"]["fault_armed"] and not r["hygiene"]["clean"]
    assert faults.active_plan is None
    # ...one the job declared is scoped to the job and unscoped after
    r = pool_mod.run_item({
        **BASE, "cmd": ["-c", "pass"],
        "fault_plan": {"faults": [
            {"op": "*", "action": "delay", "ms": 1},
        ]},
    })
    assert r["rc"] == 0 and r["hygiene"]["clean"]
    assert faults.active_plan is None


def test_run_item_exposes_sub_mesh_group():
    r = pool_mod.run_item({
        **BASE,
        "cmd": ["-c",
                "import os, json; "
                "from mpi4jax_tpu.serving.pool import job_comm, "
                "job_group_rank; "
                "c = job_comm(); "
                "assert c.groups == ((2, 3), (0,), (1,)), c.groups; "
                "assert job_group_rank() == 1"],
        "group": {"ranks": [2, 3], "rank": 1, "size": 2, "world": 4},
    })
    assert r["rc"] == 0, r
    assert "M4T_POOL_GROUP" not in os.environ


def test_run_item_resume_step_scoped():
    r = pool_mod.run_item({
        **BASE,
        "cmd": ["-c",
                "import os; "
                "assert os.environ['M4T_RESUME_STEP'] == '7'"],
        "resume_step": 7,
    })
    assert r["rc"] == 0, r
    assert "M4T_RESUME_STEP" not in os.environ


# ---------------------------------------------------------------------
# the pool doctor (stub handles, fake clock)
# ---------------------------------------------------------------------


class _Handle:
    def __init__(self):
        self.rc = None
        self.ended = False
        self.pid = 4242

    def poll(self):
        return self.rc

    def terminate(self):
        self.ended = True

    kill = terminate

    def wait(self, timeout=None):
        pass


def _mkpool(tmp_path, n=2, **kw):
    now = [0.0]
    audits = []
    opts = dict(
        heartbeat_s=0.5, deadline_s=2.0, start_deadline_s=10.0,
        check_s=0.001,
    )
    opts.update(kw)
    pool = WorkerPool(
        str(tmp_path / "pool"), n,
        spawn_fn=lambda p, w: _Handle(),
        audit=lambda event, **f: audits.append(
            {"event": event, **f}),
        log=lambda m: None,
        clock=lambda: now[0],
        **opts,
    )
    pool.start(doctor=False)
    return pool, now, audits


def _beat(pool, rank):
    events.EventLog(
        pool_mod.worker_sink(pool.root, rank)
    ).append(events.event("heartbeat", source="w", t=time.time()))


def test_worker_ready_on_first_fresh_beat(tmp_path):
    pool, now, _ = _mkpool(tmp_path)
    assert [w.state for w in pool.workers] == ["starting", "starting"]
    _beat(pool, 0)
    pool.check()
    assert pool.workers[0].state == "idle"
    assert pool.workers[1].state == "starting"
    assert pool.idle_count() == 1


def test_wedged_worker_quarantined_and_respawned(tmp_path):
    pool, now, audits = _mkpool(tmp_path)
    for r in (0, 1):
        _beat(pool, r)
    pool.check()
    assert pool.idle_count() == 2
    now[0] = 3.0  # > deadline_s with no fresh beat: wedged
    pool.check()
    assert all(w.state == "starting" for w in pool.workers)
    assert all(w.incarnation == 2 for w in pool.workers)
    assert pool.counters["quarantines"] == {"wedged": 2}
    assert pool.counters["respawns"] == 2
    kinds = [a["event"] for a in audits]
    assert kinds.count("pool_quarantine") == 2
    assert kinds.count("pool_respawn") == 2
    # the respawned incarnation becomes ready on its own fresh beat
    _beat(pool, 0)
    pool.check()
    assert pool.workers[0].state == "idle"


def test_exited_worker_quarantined_with_rc(tmp_path):
    pool, now, audits = _mkpool(tmp_path, n=1)
    _beat(pool, 0)
    pool.check()
    pool.workers[0].handle.rc = 1
    pool.check()
    assert pool.workers[0].incarnation == 2
    assert pool.counters["quarantines"] == {"exited": 1}
    (q,) = [a for a in audits if a["event"] == "pool_quarantine"]
    assert q["reason"] == "exited" and q["rc"] == 1


def test_start_timeout_quarantines_a_mute_worker(tmp_path):
    pool, now, _ = _mkpool(tmp_path, n=1)
    now[0] = 11.0  # > start_deadline_s, never a beat
    pool.check()
    assert pool.counters["quarantines"] == {"start_timeout": 1}
    assert pool.workers[0].incarnation == 2


def test_elastic_preemption_retires_the_slot(tmp_path):
    pool, now, audits = _mkpool(tmp_path, elastic=True)
    for r in (0, 1):
        _beat(pool, r)
    pool.check()
    pool.workers[1].handle.rc = 143
    pool.check()
    assert pool.workers[1].state == "retired"
    assert pool.workers[1].incarnation == 1  # never respawned
    assert pool.capacity() == 1
    assert pool.counters["retired"] == 1
    assert [a for a in audits if a["event"] == "pool_retired"]
    # a retired slot stays retired through later checks
    now[0] = 100.0
    pool.check()
    assert pool.workers[1].state == "retired"


def _serve_stub(pool, rank, *, rc=0, hygiene=None):
    """Play one worker turn by hand: claim the oldest inbox item and
    answer it (the controller-side test's half of the mailbox)."""
    wdir = pool_mod.worker_dir(pool.root, rank)
    inbox = os.path.join(wdir, pool_mod.INBOX_DIR)
    deadline = time.monotonic() + 10.0
    while True:
        name = pool_mod._oldest_entry(inbox)
        if name is not None:
            break
        if time.monotonic() > deadline:
            raise AssertionError("no work item arrived")
        time.sleep(0.005)
    with open(os.path.join(inbox, name)) as f:
        item = json.load(f)
    os.unlink(os.path.join(inbox, name))
    result = {
        "schema": pool_mod.RESULT_SCHEMA,
        "item": item["item"], "job": item["job"],
        "attempt": item["attempt"], "rc": rc, "error": None,
        "elapsed_s": 0.0,
        "hygiene": hygiene or {"clean": True},
        "worker": rank, "incarnation": 1,
    }
    pool_mod._write_json_atomic(
        os.path.join(wdir, pool_mod.OUTBOX_DIR,
                     f"{item['item']}.json"),
        result,
    )
    return item


def test_runner_round_trip_over_the_mailbox(tmp_path):
    pool, now, audits = _mkpool(tmp_path, n=2)
    for r in (0, 1):
        _beat(pool, r)
    pool.check()
    spec = parse_job({"id": "j1", "cmd": ["-c", "pass"], "nproc": 2})
    out = []
    t = threading.Thread(
        target=lambda: out.append(
            pool.runner(spec, 2, None, 0, None)),
    )
    t.start()
    items = [_serve_stub(pool, 0), _serve_stub(pool, 1)]
    t.join(timeout=10.0)
    assert not t.is_alive()
    assert out == [(0, [])]
    # work items carried the sub-mesh partition
    assert items[0]["group"] == {
        "ranks": [0, 1], "rank": 0, "size": 2, "world": 2,
    }
    assert items[1]["group"]["rank"] == 1
    assert all(w.state == "idle" for w in pool.workers)
    assert [w.jobs_served for w in pool.workers] == [1, 1]
    assert [a["event"] for a in audits].count("pool_dispatch") == 1


def test_runner_nonzero_payload_rc_propagates(tmp_path):
    pool, now, _ = _mkpool(tmp_path, n=1)
    _beat(pool, 0)
    pool.check()
    spec = parse_job({"id": "j2", "cmd": ["-c", "x"]})
    out = []
    t = threading.Thread(
        target=lambda: out.append(pool.runner(spec, 1, None, 0, None)))
    t.start()
    _serve_stub(pool, 0, rc=5)
    t.join(timeout=10.0)
    assert out == [(5, [])]


def test_hygiene_failure_completes_the_job_but_heals_the_worker(
    tmp_path,
):
    pool, now, audits = _mkpool(tmp_path, n=1)
    _beat(pool, 0)
    pool.check()
    spec = parse_job({"id": "leaky", "cmd": ["-c", "pass"]})
    out = []
    t = threading.Thread(
        target=lambda: out.append(pool.runner(spec, 1, None, 0, None)))
    t.start()
    _serve_stub(pool, 0, rc=0, hygiene={
        "clean": False, "pending_sends": 3,
    })
    t.join(timeout=10.0)
    # the job's result stands...
    assert out == [(0, [])]
    # ...but the dirty worker was quarantined and respawned
    assert pool.counters["quarantines"] == {"hygiene": 1}
    assert pool.workers[0].incarnation == 2
    assert [a for a in audits if a["event"] == "pool_hygiene"]


def test_two_strikes_poisons_the_job(tmp_path):
    # a huge heartbeat deadline isolates the *job* deadline: this is
    # the native-wedge shape where the heartbeat daemon still runs
    # but the payload never finishes
    pool, now, audits = _mkpool(
        tmp_path, n=1, deadline_s=1000.0, start_deadline_s=2000.0,
    )
    _beat(pool, 0)
    pool.check()
    spec = parse_job({
        "id": "wedger", "cmd": ["-c", "x"], "timeout_s": 5.0,
    })

    def _attempt(attempt):
        out = []
        t = threading.Thread(target=lambda: out.append(
            pool.runner(spec, 1, None, attempt, None)))
        t.start()
        # wait for the dispatch, then blow the job deadline
        inbox = os.path.join(
            pool_mod.worker_dir(pool.root, 0), pool_mod.INBOX_DIR)
        deadline = time.monotonic() + 10.0
        while pool_mod._oldest_entry(inbox) is None:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        now[0] += 100.0
        t.join(timeout=10.0)
        assert not t.is_alive()
        # ready the respawned incarnation for the next attempt
        _beat(pool, 0)
        pool.check()
        return out[0]

    assert _attempt(0) == (124, [])
    assert pool.strikes("wedger") == 1 and not pool.poisoned("wedger")
    assert _attempt(1) == (124, [])
    assert pool.strikes("wedger") == 2 and pool.poisoned("wedger")
    # the third dispatch is refused outright — no worker is consumed
    assert pool.runner(spec, 1, None, 2, None) == (1, [])
    kinds = [a["event"] for a in audits]
    assert kinds.count("pool_strike") == 2
    assert kinds.count("pool_poisoned") == 1
    (refused,) = [a for a in audits if a["event"] == "pool_refused"]
    assert refused["reason"] == "poisoned"
    assert pool.counters["quarantines"] == {"job_timeout": 2}


def test_gang_peer_lost_teardown(tmp_path):
    pool, now, audits = _mkpool(tmp_path, n=2)
    for r in (0, 1):
        _beat(pool, r)
    pool.check()
    spec = parse_job({"id": "gang", "cmd": ["-c", "x"], "nproc": 2})
    out = []
    t = threading.Thread(
        target=lambda: out.append(pool.runner(spec, 2, None, 0, None)))
    t.start()
    deadline = time.monotonic() + 10.0
    while pool.idle_count() != 0:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    pool.workers[0].handle.rc = -signal.SIGKILL  # rank 0 vanishes
    t.join(timeout=10.0)
    assert not t.is_alive()
    (rc, preempted) = out[0]
    assert rc == -signal.SIGKILL and preempted == []
    q = pool.counters["quarantines"]
    # the dead rank AND its possibly-blocked gang peer were respawned
    assert q == {"exited": 1, "peer_lost": 1}, q
    assert all(w.incarnation == 2 for w in pool.workers)
    # a plain crash is not a wedge: no strike, no poison
    assert pool.strikes("gang") == 0 and not pool.poisoned("gang")


def test_runner_reports_preempted_group_ranks(tmp_path):
    pool, now, _ = _mkpool(tmp_path, n=2, elastic=True)
    for r in (0, 1):
        _beat(pool, r)
    pool.check()
    spec = parse_job({"id": "pre", "cmd": ["-c", "x"], "nproc": 2})
    out = []
    t = threading.Thread(
        target=lambda: out.append(pool.runner(spec, 2, None, 0, None)))
    t.start()
    deadline = time.monotonic() + 10.0
    while pool.idle_count() != 0:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    pool.workers[1].handle.rc = 143
    t.join(timeout=10.0)
    (rc, preempted) = out[0]
    assert rc == 143 and preempted == [1]
    assert pool.workers[1].state == "retired"
    assert pool.capacity() == 1


# ---------------------------------------------------------------------
# exporter + doctor narration
# ---------------------------------------------------------------------


def test_pool_snapshot_and_metrics_families(tmp_path):
    pool, now, _ = _mkpool(tmp_path, n=2)
    _beat(pool, 0)
    pool.check()
    pool.workers[1].handle.rc = 2
    pool.check()
    pool._write_state(force=True)
    # pool_snapshot reads only on-disk artifacts — point it at the
    # spool root the pool dir lives under
    snap = sexport.pool_snapshot(str(tmp_path))
    assert snap is not None and snap["size"] == 2
    assert snap["counters"]["quarantines"] == {"exited": 1}
    assert snap["heartbeat_age_s"]["0"] is not None
    text = sexport.render_serving_metrics({
        "depth": 0, "capacity": 4, "running": 0, "world": 2,
        "draining": False, "counts": {}, "rejected": {}, "jobs": [],
        "pool": snap,
    })
    for needle in (
        "m4t_pool_size 2",
        "m4t_pool_capacity 2",
        'm4t_pool_quarantines_total{reason="exited"} 1',
        "m4t_pool_respawns_total 1",
        'm4t_pool_worker_alive{worker="0"} 1',
        'm4t_pool_worker_incarnation{worker="1"} 2',
        'm4t_pool_worker_last_heartbeat_age{worker="0"}',
    ):
        assert needle in text, (needle, text)
    assert text.endswith("# EOF\n")


def test_no_pool_no_families(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    assert sexport.pool_snapshot(spool) is None
    text = sexport.render_serving_metrics(
        sexport.serving_snapshot(spool))
    assert "m4t_pool_" not in text


def test_doctor_narrates_pool_events(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    spool.audit("pool_start", size=2, mesh=False, heartbeat_s=0.5,
                deadline_s=3.0)
    spool.audit("pool_quarantine", worker=1, reason="wedged", job="j")
    spool.audit("pool_respawn", worker=1, incarnation=2)
    spool.audit("pool_strike", job="j", strikes=1, max_strikes=2,
                reason="wedged")
    spool.audit("pool_poisoned", job="j", strikes=2, reason="wedged")
    spool.audit("pool_retired", worker=0, rc=143, capacity=1, job="k")
    spool.audit("pool_stop", jobs=5, respawns=1)
    text = doctor.format_serving_timeline(
        doctor.load_serving_audit([spool.root]))
    for needle in (
        "warm pool: 2 resident worker(s)",
        "worker 1 quarantined — wedged",
        "respawned (incarnation 2)",
        "strike 1/2 against job j",
        "POISONED job j",
        "worker 0 preempted — slot retired, capacity 1",
        "warm pool stopped after 5 work item(s)",
    ):
        assert needle in text, (needle, text)


# ---------------------------------------------------------------------
# real resident workers (subprocess)
# ---------------------------------------------------------------------


def test_real_warm_pool_round_trip(tmp_path):
    """One resident worker, two jobs: both complete warm (the second
    re-uses the first's imports — no respawn, one incarnation)."""
    spool = Spool(str(tmp_path / "sp"))
    for i in range(2):
        assert spool.submit({
            "id": f"w{i}", "cmd": ["-c", "import mpi4jax_tpu"],
        })["status"] == "queued"
    pool = WorkerPool(
        os.path.join(spool.root, "pool"), 1,
        heartbeat_s=0.2, audit=spool.audit, log=lambda m: None,
    )
    server = Server(
        spool, nproc=1, max_jobs=2, poll_s=0.02, pool=pool,
        log=lambda m: None,
    )
    pool.start()
    try:
        rc = server.serve()
    finally:
        pool.stop(grace_s=2.0)
    assert rc == 0
    outcomes = {r["id"]: r["outcome"] for r in spool.done()}
    assert outcomes == {"w0": "completed", "w1": "completed"}
    w = pool.workers[0]
    assert w.jobs_served == 2 and w.incarnation == 1
    assert pool.counters["respawns"] == 0
    # state snapshot is on disk for the offline exporter / status CLI
    snap = sexport.pool_snapshot(spool)
    assert snap["workers"][0]["jobs_served"] == 2


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_worker_kill_respawns_and_no_job_lost(tmp_path):
    """ISSUE-11 acceptance: SIGKILL one resident worker mid-job. The
    pool quarantines and respawns it, the in-flight job retries under
    its per-job Supervisor and completes, the queued jobs drain, and
    every submitted job id ends terminal in ``serving.jsonl``."""
    spool = Spool(str(tmp_path / "sp"))
    spool.configure(8)
    assert spool.submit({
        "id": "victim", "tenant": "a",
        "cmd": ["-c", "import time; time.sleep(4.0)"],
        "retries": 2, "backoff_s": 0.1,
    })["status"] == "queued"
    for i in range(3):
        assert spool.submit({
            "id": f"q{i}", "tenant": "b", "cmd": ["-c", "pass"],
        })["status"] == "queued"

    pool = WorkerPool(
        os.path.join(spool.root, "pool"), 2,
        heartbeat_s=0.2, audit=spool.audit, log=lambda m: None,
    )
    server = Server(
        spool, nproc=2, max_jobs=4, poll_s=0.02, pool=pool,
        log=lambda m: None,
    )
    pool.start()
    out = []
    t = threading.Thread(target=lambda: out.append(server.serve()))
    t.start()
    try:
        # find the worker running "victim" and kill it mid-job
        deadline = time.monotonic() + 60.0
        target = None
        while target is None:
            assert time.monotonic() < deadline, "victim never dispatched"
            for w in pool.workers:
                if w.job == "victim" and w.state == "busy" and (
                    w.handle is not None
                ):
                    target = (w.rank, w.handle.pid)
            time.sleep(0.05)
        time.sleep(0.5)  # well inside the payload's sleep
        os.kill(target[1], signal.SIGKILL)
        t.join(timeout=120.0)
        assert not t.is_alive(), "serve loop never drained"
    finally:
        pool.stop(grace_s=2.0)
        if t.is_alive():
            t.join(timeout=10.0)
    assert out == [0]

    # zero jobs lost: every id terminal, the victim retried clean
    done = {r["id"]: r for r in spool.done()}
    assert {j: r["outcome"] for j, r in done.items()} == {
        "victim": "completed", "q0": "completed",
        "q1": "completed", "q2": "completed",
    }
    assert done["victim"]["attempts"] == 2
    terminal = {}
    for r in spool.audit_records():
        if r["event"] in ("completed", "failed", "rejected"):
            terminal[r["job"]] = r["event"]
    assert set(terminal) == {"victim", "q0", "q1", "q2"}
    assert all(v == "completed" for v in terminal.values())

    # the pool healed: the killed slot runs a fresh incarnation
    killed = pool.workers[target[0]]
    assert killed.incarnation == 2
    assert pool.counters["respawns"] >= 1
    q = pool.counters["quarantines"]
    assert q.get("exited", 0) >= 1, q
    kinds = [r["event"] for r in spool.audit_records()]
    assert "pool_quarantine" in kinds and "pool_respawn" in kinds
    # a crash is not a wedge: the victim was never poisoned
    assert not pool.poisoned("victim")
